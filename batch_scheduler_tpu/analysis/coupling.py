"""Checker ``coupling`` — formula-coupled "change-together" blocks, mechanized.

The scan ladder's bit-identity spine (PRs 2/6/7/8) rests on formulas that
are re-derived in multiple places: ``_select_best_fit``'s threshold/
remainder arithmetic is recomputed from summary histograms by
``_hist_select``, vmapped by ``_select_best_fit_wave``, approximated by the
top-K coarse rank, and bucket-shifted by the policy composite's key
override. The prose contract ("change all of them together",
ops/oracle.py) is exactly the kind a refactor silently breaks.

This checker pins each declared group member to an AST fingerprint (a
sha256 of the normalized AST, docstrings and line info stripped — comments
and formatting never trip it). Editing any member changes its fingerprint
and fails ``make analyze`` until the stamp file is regenerated with

    python -m batch_scheduler_tpu.analysis --stamp-coupling

which is the mechanical "I looked at every paired formula" acknowledgement
(back it with ``make bench-policy`` / ``make bench-xl`` / replay-gate, the
bit-identity gates — docs/static_analysis.md "Stamping a coupled change").

Stamps live in coupling_stamps.json next to this module.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from typing import Dict, List, Optional

from .findings import Finding

CHECKER = "coupling"

STAMP_FILE = os.path.join(os.path.dirname(__file__), "coupling_stamps.json")

# group name -> list of "relpath::qualname" members (relpath under repo root)
COUPLED_GROUPS: Dict[str, List[str]] = {
    # the tightest-first selection arithmetic and every re-derivation of it
    "selection-formula": [
        "batch_scheduler_tpu/ops/oracle.py::_cumsum",
        "batch_scheduler_tpu/ops/oracle.py::_select_best_fit",
        "batch_scheduler_tpu/ops/oracle.py::_hist_select",
        "batch_scheduler_tpu/ops/oracle.py::_select_best_fit_wave",
        "batch_scheduler_tpu/ops/oracle.py::_coarse_rank",
        "batch_scheduler_tpu/ops/oracle.py::assign_gangs_policy",
    ],
    # member-capacity computed in [.., R] layout host-side and re-derived in
    # the pallas kernel's transposed [R, N] layout
    "member-capacity": [
        "batch_scheduler_tpu/ops/oracle.py::_member_capacity",
        "batch_scheduler_tpu/ops/pallas_assign.py::_cap_t",
    ],
    # the device-resident state spine: the rows the host-side delta packer
    # rewrites must be exactly the rows the device holder scatter-applies
    # (same indices, same packed values) — delta-applied state diverging
    # from a full repack is the one failure bench-delta exists to forbid
    "delta-row-scatter": [
        "batch_scheduler_tpu/ops/snapshot.py::DeltaSnapshotPacker._delta_rows",
        "batch_scheduler_tpu/ops/snapshot.py::DeltaSnapshotPacker._group_rows",
        "batch_scheduler_tpu/ops/device_state.py::_scatter_impl",
        "batch_scheduler_tpu/ops/device_state.py::DeviceStateHolder.apply_rows",
    ],
    # the max-progress selection computed on device and its host-side
    # numpy twin: the coalescer demux (service.coalescer) re-derives each
    # tenant's `best` from the tenant's own padded progress args, so the
    # two formulas must change together or a coalesced tenant's response
    # drifts from its dedicated-sidecar run
    "find-max-group": [
        "batch_scheduler_tpu/ops/oracle.py::find_max_group",
        "batch_scheduler_tpu/ops/oracle.py::find_max_group_host",
    ],
    # the explain kernel's entry-leftover capture replays the serial scan
    # body (base and policy-composite forms): its captured leftover IS
    # the explanation's evidence, so the step formula must change
    # together with the scans it mirrors
    "explain-entry-capture": [
        "batch_scheduler_tpu/ops/oracle.py::assign_gangs",
        "batch_scheduler_tpu/ops/oracle.py::assign_gangs_policy",
        "batch_scheduler_tpu/ops/explain.py::_scan_take",
    ],
}


def _strip_docstring(fn: ast.AST) -> None:
    if (
        fn.body
        and isinstance(fn.body[0], ast.Expr)
        and isinstance(fn.body[0].value, ast.Constant)
        and isinstance(fn.body[0].value.value, str)
    ):
        fn.body = fn.body[1:] or [ast.Pass()]


def _find_function(tree: ast.AST, qualname: str) -> Optional[ast.AST]:
    parts = qualname.split(".")
    scope = tree.body
    node = None
    for part in parts:
        node = None
        for cand in scope:
            if (
                isinstance(cand, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                and cand.name == part
            ):
                node = cand
                break
        if node is None:
            return None
        scope = node.body
    return node


def fingerprint(root: str, member: str) -> Optional[str]:
    """sha256 fingerprint of one member's normalized AST, None if missing."""
    relpath, qualname = member.split("::", 1)
    path = os.path.join(root, relpath)
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    fn = _find_function(tree, qualname)
    if fn is None:
        return None
    _strip_docstring(fn)
    dump = ast.dump(fn, annotate_fields=True, include_attributes=False)
    return hashlib.sha256(dump.encode()).hexdigest()


def load_stamps(stamp_file: str = STAMP_FILE) -> Dict[str, Dict[str, str]]:
    try:
        with open(stamp_file, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def stamp(root: str, stamp_file: str = STAMP_FILE,
          groups: Optional[Dict[str, List[str]]] = None) -> Dict[str, Dict[str, str]]:
    """Regenerate the stamp file from the current tree."""
    groups = groups if groups is not None else COUPLED_GROUPS
    out: Dict[str, Dict[str, str]] = {}
    for group, members in groups.items():
        out[group] = {}
        for member in members:
            fp = fingerprint(root, member)
            if fp is not None:
                out[group][member] = fp
    with open(stamp_file, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    return out


def check(root: str, stamp_file: str = STAMP_FILE,
          groups: Optional[Dict[str, List[str]]] = None) -> List[Finding]:
    groups = groups if groups is not None else COUPLED_GROUPS
    stamps = load_stamps(stamp_file)
    findings: List[Finding] = []
    for group, members in groups.items():
        stamped = stamps.get(group, {})
        drifted = []
        for member in members:
            relpath, qualname = member.split("::", 1)
            fp = fingerprint(root, member)
            if fp is None:
                findings.append(
                    Finding(
                        CHECKER,
                        relpath,
                        0,
                        f"coupled group '{group}' member '{qualname}' not "
                        "found — a declared change-together formula was "
                        "moved or deleted without updating the registry "
                        "(analysis/coupling.py COUPLED_GROUPS)",
                    )
                )
                continue
            want = stamped.get(member)
            if want is None:
                findings.append(
                    Finding(
                        CHECKER,
                        relpath,
                        0,
                        f"coupled group '{group}' member '{qualname}' has no "
                        "stamp — run `python -m batch_scheduler_tpu.analysis "
                        "--stamp-coupling` after verifying the group",
                    )
                )
            elif want != fp:
                drifted.append((relpath, qualname))
        for relpath, qualname in drifted:
            others = [
                m.split("::", 1)[1] for m in members
                if m.split("::", 1)[1] != qualname
            ]
            findings.append(
                Finding(
                    CHECKER,
                    relpath,
                    0,
                    f"'{qualname}' changed but coupled group '{group}' was "
                    f"not re-stamped — verify the paired formulas "
                    f"({', '.join(others)}) still agree (the bit-identity "
                    "gates: bench-policy / bench-xl / replay-gate), then "
                    "`python -m batch_scheduler_tpu.analysis --stamp-coupling`",
                )
            )
    return findings
