from .apiserver import (
    AlreadyExistsError,
    APIServer,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from .clientset import Clientset, NodeInterface, PodGroupInterface, PodInterface
from .fake import new_simple_clientset
from .informers import PodGroupLister, SharedInformer, SharedInformerFactory

__all__ = [
    "AlreadyExistsError",
    "APIServer",
    "ConflictError",
    "NotFoundError",
    "WatchEvent",
    "Clientset",
    "NodeInterface",
    "PodGroupInterface",
    "PodInterface",
    "new_simple_clientset",
    "PodGroupLister",
    "SharedInformer",
    "SharedInformerFactory",
]
