"""Shared informers and listers.

Equivalent of the generated informer machinery the reference builds its
controller and Compare path on (reference pkg/generated/informers/
externalversions/factory.go:79-180, listers/podgroup/v1/podgroup.go:43-91):
a watch-driven local cache with event handlers, a ``has_synced`` barrier and
namespace-scoped listers reading the cache without touching the API server.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..api.serde import object_from_dict
from ..utils.drain import drain_queue
from .apiserver import APIServer, WatchEvent

__all__ = ["SharedInformer", "SharedInformerFactory", "PodGroupLister"]

_POLL_SECONDS = 0.1


class SharedInformer:
    """One kind's list+watch loop feeding a local store and handler set."""

    def __init__(self, api: APIServer, kind: str):
        self._api = api
        self.kind = kind
        self._store: Dict[Tuple[str, str], dict] = {}  # guarded-by: _lock
        # (label, value) -> store keys, maintained by _dispatch; backs the
        # raw label-selector reads (list_raw_by_label)
        self._label_index: Dict[Tuple[str, str], set] = {}  # guarded-by: _lock
        self._lock = threading.RLock()
        # registration-time only; published to the hot path as the _tables
        # tuple, swapped atomically under the GIL (see _rebuild_tables)
        self._handlers: List[dict] = []
        self._rebuild_tables()
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # lazily-built typed views for read-only hot paths (queue compare
        # runs two lister reads per heap comparison); keyed by store-dict
        # identity so any update invalidates
        self._typed_cache: Dict[Tuple[str, str], tuple] = {}  # guarded-by: _lock

    # -- registration ------------------------------------------------------

    def add_event_handler(
        self,
        on_add: Optional[Callable] = None,
        on_update: Optional[Callable] = None,
        on_delete: Optional[Callable] = None,
        wants_old: bool = False,
        raw: bool = False,
    ) -> None:
        """``wants_old``: pass the previous typed object as ``on_update``'s
        first argument. Off by default — materialising the old view is a
        deep copy + rehydrate per MODIFIED event, and no stock handler
        reads it (they get ``None``); at 10k-pod scale those copies were
        measurable GIL load on the watch-dispatch thread.

        ``raw``: handlers receive the STORED dict (shared, immutable —
        read-only by the same contract as ``peek_raw``) instead of a typed
        object; ``on_update`` receives ``(old_dict_or_None, new_dict)``
        (old only with ``wants_old``). Typed materialisation is then lazy:
        an event every registered handler consumes raw never builds a
        typed object at all — at 10k pods the watch-dispatch thread
        processes ~4 events per pod, and the per-event deep copy +
        rehydrate was its dominant cost."""
        self._handlers.append(
            {
                "add": on_add,
                "update": on_update,
                "delete": on_delete,
                "wants_old": wants_old,
                "raw": raw,
            }
        )
        self._rebuild_tables()

    def _rebuild_tables(self) -> None:
        """Pre-split handler dispatch tables: the per-event handler loop
        is the hottest code in the watch path (~4 events/pod at 10k-pod
        scale), and per-event dict lookups + raw/typed branching per
        handler were measurable GIL load. Published as ONE tuple
        attribute so a handler registered after start() swaps in
        atomically under the GIL — _fire reads the whole table set in a
        single attribute load, never a mix of old and new pieces."""
        raw_add = [h["add"] for h in self._handlers if h["raw"] and h["add"]]
        raw_update = [
            (h["update"], h["wants_old"])
            for h in self._handlers
            if h["raw"] and h["update"]
        ]
        raw_delete = [
            h["delete"] for h in self._handlers if h["raw"] and h["delete"]
        ]
        typed = [h for h in self._handlers if not h["raw"]]
        typed_wants_old = any(h["wants_old"] for h in typed)
        self._tables = (raw_add, raw_update, raw_delete, typed, typed_wants_old)

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    # -- loop --------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._events = self._api.watch(self.kind, replay=True)
        self._thread = threading.Thread(
            target=self._run, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._api.stop_watch(self.kind, self._events)

    def _run(self) -> None:
        # Drain the replayed ADDED events, then mark synced on first idle.
        # Bursts drain in micro-batches (utils.drain.drain_queue) and the
        # whole batch's store/index updates share ONE lock pass — at
        # 10k-pod scale the watch thread handles ~4 events per pod, and
        # per-event lock round trips were measurable GIL load beside the
        # scheduling thread.
        while not self._stop.is_set():
            batch = drain_queue(self._events, timeout=_POLL_SECONDS)
            if batch is None:
                self._synced.set()
                continue
            updates = self._apply_batch(batch)
            for event, old in updates:
                self._fire(event, old)

    def _apply_batch(self, batch) -> list:
        """Store + label-index updates for a drained event batch under one
        lock hold; returns (event, old_stored_dict) pairs for handler
        dispatch outside the lock."""
        updates = []
        with self._lock:
            store = self._store
            for event in batch:
                meta = event.obj.get("metadata") or {}
                key = (meta.get("namespace", "default"), meta.get("name", ""))
                old = store.get(key)
                # label-index maintenance only when the label set changed:
                # status/spec patches (binds, phase flips — most MODIFIED
                # traffic) leave labels identical
                old_labels = (
                    ((old.get("metadata") or {}).get("labels") or {})
                    if old is not None
                    else None
                )
                new_labels = meta.get("labels") or {}
                labels_changed = (
                    event.type == WatchEvent.DELETED
                    or old_labels != new_labels
                )
                if old is not None and labels_changed:
                    for item in (old_labels or {}).items():
                        bucket = self._label_index.get(item)
                        if bucket is not None:
                            bucket.discard(key)
                            if not bucket:
                                del self._label_index[item]
                if event.type == WatchEvent.DELETED:
                    store.pop(key, None)
                    # drop the typed view too, or deleted-and-never-
                    # requeried keys leak one (dict, typed) pair each
                    # (ADVICE r2)
                    self._typed_cache.pop(key, None)
                else:
                    store[key] = event.obj
                    if old is None or labels_changed:
                        for item in new_labels.items():
                            self._label_index.setdefault(item, set()).add(key)
                updates.append((event, old))
        return updates

    def _dispatch(self, event: WatchEvent) -> None:
        """Single-event form (tests and small paths); the watch loop uses
        _apply_batch + _fire."""
        (pair,) = self._apply_batch([event])
        self._fire(*pair)

    def _fire(self, event: WatchEvent, old: Optional[dict]) -> None:
        etype = event.type
        obj = event.obj
        # one atomic table read (see _rebuild_tables)
        raw_add, raw_update, raw_delete, typed_hs, typed_wants_old = (
            self._tables
        )
        # raw handlers first: pre-split per-type tables, no typed
        # materialisation at all on the pure-raw path
        if etype == WatchEvent.ADDED:
            for cb in raw_add:
                try:
                    cb(obj)
                except Exception:
                    pass  # a bad handler must not stall the watch stream
        elif etype == WatchEvent.MODIFIED:
            for cb, wants_old in raw_update:
                try:
                    cb(old if wants_old else None, obj)
                except Exception:
                    pass
        else:
            for cb in raw_delete:
                try:
                    cb(obj)
                except Exception:
                    pass
        if not typed_hs:
            return
        typed = None
        old_typed = (
            object_from_dict(self.kind, old)
            if old and typed_wants_old
            else None
        )
        for h in typed_hs:
            try:
                if etype == WatchEvent.ADDED and h["add"]:
                    typed = typed if typed is not None else event.object()
                    h["add"](typed)
                elif etype == WatchEvent.MODIFIED and h["update"]:
                    typed = typed if typed is not None else event.object()
                    h["update"](old_typed if h["wants_old"] else None, typed)
                elif etype == WatchEvent.DELETED and h["delete"]:
                    typed = typed if typed is not None else event.object()
                    h["delete"](typed)
            except Exception:
                pass

    # -- lister reads ------------------------------------------------------

    def get(self, namespace: str, name: str):
        with self._lock:
            d = self._store.get((namespace, name))
            return object_from_dict(self.kind, d) if d else None

    def peek_raw(self, namespace: str, name: str) -> Optional[dict]:
        """The stored raw dict — NOT a copy, read-only. The scheduler's
        per-cycle liveness check (uid/node_name) reads this instead of a
        deep-copying API-server GET (reference reads its queued copy; the
        GET was our addition and cost ~100µs/cycle at 10k-pod scale)."""
        with self._lock:
            return self._store.get((namespace, name))

    def peek_raw_many(self, namespace: str, names) -> list:
        """One lock pass over many keys — the gang transaction's batch
        liveness check (per-member ``peek_raw`` calls contend this lock
        against the watch-dispatch thread ~10x per gang). Same read-only
        contract as ``peek_raw``; missing keys yield None."""
        with self._lock:
            return [self._store.get((namespace, n)) for n in names]

    def list_raw_by_label(
        self, namespace: Optional[str], selector: Dict[str, str]
    ) -> List[dict]:
        """Label-indexed raw reads: the stored dicts, NOT copies — read-only.
        O(matches) via the (label, value) index maintained by _dispatch. The
        controller's member-pod scans read phase/uid through this instead of
        a deep-copying API list per sync (client-go controllers are
        lister-backed the same way; reference controller.go:148-176 reads
        its informer cache)."""
        if not selector:
            raise ValueError("empty selector")
        first, *rest = selector.items()
        out = []
        with self._lock:
            for key in self._label_index.get(first, ()):
                d = self._store.get(key)
                if d is None or (namespace is not None and key[0] != namespace):
                    continue
                labels = (d.get("metadata") or {}).get("labels") or {}
                if any(labels.get(k) != v for k, v in rest):
                    continue
                out.append(d)
        return out

    def get_typed(self, namespace: str, name: str):
        """READ-ONLY cached typed view: one construction per store update,
        shared across callers — never mutate the result (use ``get`` for a
        private copy)."""
        key = (namespace, name)
        with self._lock:
            d = self._store.get(key)
            if d is None:
                self._typed_cache.pop(key, None)
                return None
            cached = self._typed_cache.get(key)
            if cached is not None and cached[0] is d:
                return cached[1]
            obj = object_from_dict(self.kind, d)
            self._typed_cache[key] = (d, obj)
            return obj

    def list(self, namespace: Optional[str] = None) -> list:
        with self._lock:
            return [
                object_from_dict(self.kind, d)
                for (ns, _), d in self._store.items()
                if namespace is None or ns == namespace
            ]

    def list_raw(self, namespace: Optional[str] = None) -> List[dict]:
        """Every stored raw dict — NOT copies, read-only (the ``peek_raw``
        contract)."""
        with self._lock:
            return [
                d
                for (ns, _), d in self._store.items()
                if namespace is None or ns == namespace
            ]


class PodGroupLister:
    """Namespace-scoped cache reads (reference listers/podgroup/v1)."""

    def __init__(self, informer: SharedInformer):
        self._informer = informer

    def pod_groups(self, namespace: str) -> "_NamespacedLister":
        return _NamespacedLister(self._informer, namespace)

    def list(self) -> list:
        return self._informer.list()


class _NamespacedLister:
    def __init__(self, informer: SharedInformer, namespace: str):
        self._informer = informer
        self._ns = namespace

    def get(self, name: str):
        return self._informer.get(self._ns, name)

    def list(self) -> list:
        return self._informer.list(self._ns)


class SharedInformerFactory:
    """Builds and starts one informer per kind
    (reference informers/externalversions/factory.go)."""

    def __init__(self, api: APIServer):
        self._api = api
        self._informers: Dict[str, SharedInformer] = {}

    def informer(self, kind: str) -> SharedInformer:
        if kind not in self._informers:
            self._informers[kind] = SharedInformer(self._api, kind)
        return self._informers[kind]

    def pod_groups(self) -> SharedInformer:
        return self.informer("PodGroup")

    def pod_group_lister(self) -> PodGroupLister:
        return PodGroupLister(self.pod_groups())

    def start(self) -> None:
        for informer in self._informers.values():
            informer.start()

    def stop(self) -> None:
        for informer in self._informers.values():
            informer.stop()

    def wait_for_cache_sync(self, timeout: float = 10.0) -> bool:
        return all(
            informer.wait_for_sync(timeout)
            for informer in self._informers.values()
        )
