"""HTTP gateway: expose an APIServer over Kubernetes-shaped REST.

The reference talks to a real API server over HTTPS via client-go
(reference pkg/generated/clientset/versioned/clientset.go:58-97); this
module is the transport-parity piece for the owned control plane: any
APIServer can be served on a socket with k8s-style resource paths and the
k8s watch protocol (streamed ``{"type": ..., "object": ...}`` JSON lines),
and ``client.http_apiserver.HTTPAPIServer`` connects Clientset/informers to
such an endpoint — ours, or any server speaking the same dialect (KWOK-style
simulated clusters serve exactly these paths).

Routes:
  /api/v1/namespaces/{ns}/pods[/{name}]
  /api/v1/namespaces/{ns}/pods:bindmany  (POST: batched bind custom verb)
  /api/v1/nodes[/{name}]
  /apis/batch.scheduler.tpu/v1/namespaces/{ns}/podgroups[/{name}]
  /apis/apiextensions.k8s.io/v1/customresourcedefinitions
  collection GET with ?watch=1[&replay=1] streams watch events
  collection GET with ?labelSelector=k%3Dv,... filters server-side
"""

from __future__ import annotations

import json
import queue
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from .apiserver import AlreadyExistsError, APIServer, ConflictError, NotFoundError

__all__ = ["KIND_ROUTES", "CRD_PATH", "serve_gateway", "GatewayServer"]

# kind -> (api prefix, plural, namespaced)
KIND_ROUTES = {
    "Pod": ("/api/v1", "pods", True),
    "Node": ("/api/v1", "nodes", False),
    "PodGroup": ("/apis/batch.scheduler.tpu/v1", "podgroups", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
}
_PLURALS = {v[1]: k for k, v in KIND_ROUTES.items()}
CRD_PATH = "/apis/apiextensions.k8s.io/v1/customresourcedefinitions"


def _parse_resource(path: str) -> Optional[Tuple[str, Optional[str], Optional[str]]]:
    """path -> (kind, namespace or None, name or None), or None."""
    parts = [p for p in path.split("/") if p]
    # {prefix...}/namespaces/{ns}/{plural}[/{name}]
    if "namespaces" in parts:
        i = parts.index("namespaces")
        if len(parts) < i + 3:
            return None
        ns, plural = parts[i + 1], parts[i + 2]
        kind = _PLURALS.get(plural)
        if kind is None:
            return None
        name = parts[i + 3] if len(parts) > i + 3 else None
        return kind, ns, name
    # cluster-scoped or all-namespaces: {prefix...}/{plural}[/{name}]
    for j, part in enumerate(parts):
        kind = _PLURALS.get(part)
        if kind is not None:
            name = parts[j + 1] if len(parts) > j + 1 else None
            return kind, None, name
    return None


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1: persistent connections for the request/response verbs
    # (client-go parity — one TCP handshake per client, not per request;
    # per-request connections flooded the kernel with TIME_WAIT sockets
    # at e2e scale). Responses carry Content-Length, so keep-alive works;
    # the watch stream opts out with Connection: close below.
    protocol_version = "HTTP/1.1"
    # persistent connections make the Nagle/delayed-ACK interaction
    # visible (~40ms per small request/response exchange): disable Nagle
    # like every production HTTP server does
    disable_nagle_algorithm = True
    api: APIServer = None  # set by serve_gateway subclass

    def log_message(self, *args) -> None:  # quiet
        pass

    def parse_request(self) -> bool:
        # per-request state on a persistent connection: the handler
        # instance is reused across keep-alive requests
        self._body_read = False
        return super().parse_request()

    # -- helpers -----------------------------------------------------------

    def _send_json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_json(
        self, code: int, message: str, reason: str = ""
    ) -> None:
        # keep-alive hygiene: an error response sent before the request
        # body was read leaves the body bytes in the stream, and the next
        # request on this persistent connection would parse them as its
        # request line — drain them first
        if not getattr(self, "_body_read", False):
            length = int(self.headers.get("Content-Length") or 0)
            if length > 0:
                self.rfile.read(length)
                self._body_read = True
        self._send_json(
            code,
            {"kind": "Status", "code": code, "message": message, "reason": reason},
        )

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        self._body_read = True
        return json.loads(self.rfile.read(length) or b"{}")

    def _selector(self, qs) -> Optional[dict]:
        raw = qs.get("labelSelector", [None])[0]
        if not raw:
            return None
        out = {}
        for term in unquote(raw).split(","):
            if "=" in term:
                k, v = term.split("=", 1)
                out[k] = v
        return out or None

    # -- verbs -------------------------------------------------------------

    def do_GET(self) -> None:
        url = urlparse(self.path)
        parsed = _parse_resource(url.path)
        if parsed is None:
            if url.path == CRD_PATH:
                self._send_json(200, {"items": self.api.crds()})
                return
            self._send_error_json(404, f"unknown path {url.path}")
            return
        kind, ns, name = parsed
        qs = parse_qs(url.query)
        try:
            if name is not None:
                self._send_json(200, self.api.get(kind, ns or "", name))
            elif qs.get("watch", ["0"])[0] in ("1", "true"):
                self._stream_watch(kind, ns, qs)
            else:
                items = self.api.list(kind, ns, self._selector(qs))
                self._send_json(200, {"items": items})
        except NotFoundError as e:
            self._send_error_json(404, str(e))

    def _stream_watch(self, kind: str, ns: Optional[str], qs) -> None:
        """k8s-dialect watch stream, scoped to the URL's namespace and
        labelSelector (a watch on /namespaces/ns/pods streams only ns —
        ADVICE r2). Replay is served from a LIST taken after subscribing
        (no missed-event window) and terminated by a BOOKMARK line, the
        reflector's resync point: a reconnecting client diffs the replayed
        state at the BOOKMARK against what it knew and synthesizes DELETED
        events for objects that vanished while it was away (client-go's
        relist, informers factory.go:117-133)."""
        replay = qs.get("replay", ["1"])[0] in ("1", "true")
        selector = self._selector(qs)

        def in_scope(obj: dict) -> bool:
            meta = obj.get("metadata") or {}
            if ns is not None and meta.get("namespace", "default") != ns:
                return False
            if selector:
                labels = meta.get("labels") or {}
                return all(labels.get(k) == v for k, v in selector.items())
            return True

        def key_of(obj: dict) -> tuple:
            meta = obj.get("metadata") or {}
            return (meta.get("namespace", "default"), meta.get("name", ""))

        # subscribe FIRST, then list: anything created between the two
        # shows up twice (replay + live ADDED) — level-based consumers
        # overwrite; nothing is missed
        events = self.api.watch(kind, replay=False)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "identity")
        # a watch stream has no length and ends only when a side closes:
        # it cannot ride a keep-alive connection
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        sent: set = set()  # keys this stream has delivered as in-scope
        try:
            if replay:
                for obj in self.api.list(kind, ns, selector):
                    sent.add(key_of(obj))
                    line = json.dumps({"type": "ADDED", "object": obj}) + "\n"
                    self.wfile.write(line.encode())
                self.wfile.write(b'{"type": "BOOKMARK"}\n')
                self.wfile.flush()
            while True:
                try:
                    item = events.get(timeout=0.2)
                except queue.Empty:
                    # heartbeat keeps half-open disconnects detectable
                    self.wfile.write(b"\n")
                    self.wfile.flush()
                    continue
                # the API server's bulk verbs fan out one LIST per chunk
                out = []
                for ev in item if isinstance(item, list) else (item,):
                    key = key_of(ev.obj)
                    etype = ev.type
                    if in_scope(ev.obj):
                        if etype == "DELETED":
                            sent.discard(key)
                        else:
                            # scope ENTRY (e.g. relabeled into the
                            # selector) must read as ADDED to a scoped
                            # watcher
                            if key not in sent:
                                etype = "ADDED"
                            sent.add(key)
                    elif key in sent:
                        # scope EXIT: to this watcher the object is gone —
                        # k8s scoped watches emit DELETED here, not
                        # silence
                        sent.discard(key)
                        etype = "DELETED"
                    else:
                        continue  # never in scope for this stream
                    out.append(
                        json.dumps({"type": etype, "object": ev.obj}) + "\n"
                    )
                if out:
                    # one write + flush per batch: fewer syscalls under
                    # the bind storm
                    self.wfile.write("".join(out).encode())
                    self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            self.api.stop_watch(kind, events)

    def do_POST(self) -> None:
        url = urlparse(self.path)
        if url.path.endswith("/pods:bindmany"):
            # batched bind subresource: one request binds a whole released
            # gang (the k8s custom-verb path shape). Body
            # {"binds": [[name, node], ...]} -> {"bound": [names]}; missing
            # pods are skipped, matching APIServer.bind_pods. Without this
            # route the cross-gang commit flush's one-API-pass amortization
            # evaporates over the wire into per-pod PATCHes.
            parsed = _parse_resource(url.path[: -len(":bindmany")])
            if parsed is None or parsed[0] != "Pod":
                self._send_error_json(404, f"unknown path {url.path}")
                return
            ns = parsed[1] or "default"
            body = self._read_body()
            pairs = [(b[0], b[1]) for b in body.get("binds", [])]
            bind_pods = getattr(self.api, "bind_pods", None)
            if bind_pods is None:
                self._send_error_json(404, "bind batch unsupported")
                return
            # fencing: stamp the bind with this gateway generation's
            # epoch so a handler thread outliving a "restart" (severed
            # socket, thread already past the read) cannot apply a stale
            # bind against the shared backing store after a newer
            # gateway took over (the zombie-bind over-commit)
            epoch = getattr(self, "bind_epoch", None)
            if epoch is not None:
                self._send_json(
                    200, {"bound": bind_pods(ns, pairs, epoch=epoch)}
                )
            else:
                self._send_json(200, {"bound": bind_pods(ns, pairs)})
            return
        if url.path == CRD_PATH:
            body = self._read_body()
            created = self.api.ensure_crd(
                body.get("metadata", {}).get("name", ""), body.get("spec")
            )
            self._send_json(201 if created else 409, body)
            return
        parsed = _parse_resource(url.path)
        if parsed is None:
            self._send_error_json(404, f"unknown path {url.path}")
            return
        kind, ns, _ = parsed
        obj = self._read_body()
        if ns is not None:
            obj.setdefault("metadata", {}).setdefault("namespace", ns)
        try:
            self._send_json(201, self.api.create(kind, obj))
        except AlreadyExistsError as e:
            self._send_error_json(409, str(e), reason="AlreadyExists")

    def do_PUT(self) -> None:
        parsed = _parse_resource(urlparse(self.path).path)
        if parsed is None:
            self._send_error_json(404, "unknown path")
            return
        kind, _, _ = parsed
        try:
            self._send_json(200, self.api.update(kind, self._read_body()))
        except ConflictError as e:
            self._send_error_json(409, str(e), reason="Conflict")
        except NotFoundError as e:
            self._send_error_json(404, str(e))

    def do_PATCH(self) -> None:
        parsed = _parse_resource(urlparse(self.path).path)
        if parsed is None or parsed[2] is None:
            self._send_error_json(404, "unknown path")
            return
        kind, ns, name = parsed
        try:
            self._send_json(
                200, self.api.patch(kind, ns or "", name, self._read_body())
            )
        except NotFoundError as e:
            self._send_error_json(404, str(e))

    def do_DELETE(self) -> None:
        parsed = _parse_resource(urlparse(self.path).path)
        if parsed is None:
            self._send_error_json(404, "unknown path")
            return
        kind, ns, name = parsed
        try:
            if name is not None:
                self.api.delete(kind, ns or "", name)
                self._send_json(200, {"kind": "Status", "status": "Success"})
            else:
                n = self.api.delete_collection(kind, ns)
                self._send_json(200, {"kind": "Status", "deleted": n})
        except NotFoundError as e:
            self._send_error_json(404, str(e))


class GatewayServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    # With HTTP/1.1 keep-alive, shutdown()+server_close() only stop the
    # ACCEPT loop — daemon handler threads would keep serving persistent
    # connections straight through a "restart", silently defeating outage
    # tests (and leaking zombie handlers). Track live connections and
    # sever them at close, like a real server death would.
    def __init__(self, *args, **kwargs):
        # before super().__init__: a failed bind (busy port on a restart
        # attempt) makes the base class call self.server_close(), which
        # needs these — assigning after would turn the OSError into an
        # AttributeError
        self._live_conns: set = set()  # guarded-by: _conn_lock
        self._conn_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._live_conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conn_lock:
            conns = list(self._live_conns)
            self._live_conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


def serve_gateway(
    api: APIServer, host: str = "127.0.0.1", port: int = 0
) -> GatewayServer:
    """Serve ``api`` on (host, port) in a background thread; returns the
    server (``server.server_address`` has the bound port; ``shutdown()`` +
    ``server_close()`` stops it).

    Each gateway generation advances the backing store's bind epoch at
    startup and stamps its binds with it: handler threads from a PREVIOUS
    generation (zombies a severed socket could not kill) are fenced out
    of the shared store, so a liveness read served by this generation is
    conclusive about lost binds (APIServer.bind_pods)."""
    handler = type(
        "BoundHandler", (_Handler,), {"api": api, "bind_epoch": None}
    )
    # bind the listening socket FIRST, then advance the fence: if the
    # port is still held (failed restart) the constructor raises before
    # the epoch moves, so the surviving previous generation keeps
    # binding — advancing first would silently fence a gateway that
    # never got replaced. Handlers only run once serve_forever starts,
    # after the epoch is stamped below.
    server = GatewayServer((host, port), handler)
    advance = getattr(api, "advance_bind_epoch", None)
    handler.bind_epoch = advance() if advance is not None else None
    threading.Thread(
        target=server.serve_forever, name="apiserver-gateway", daemon=True
    ).start()
    return server
