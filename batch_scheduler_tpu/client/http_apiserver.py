"""HTTPAPIServer: point the control plane at a REAL (remote) API server.

Implements the same interface as ``client.apiserver.APIServer`` — create /
get / list / update / patch / delete / delete_collection / watch /
stop_watch / ensure_crd — over Kubernetes-shaped HTTP (the dialect served by
``client.http_gateway``, which is the k8s resource-path + watch-stream
protocol shape a KWOK-simulated cluster speaks). ``Clientset`` and
``SharedInformerFactory`` take it unchanged, so the whole scheduler stack
can run against an external endpoint — the capability the reference gets
from client-go (reference pkg/generated/clientset/versioned/
clientset.go:58-97, informers list+watch factory.go:79-180). The in-memory
path is untouched.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
from typing import Dict, List, Optional
from urllib.parse import quote

from .apiserver import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from .http_gateway import CRD_PATH, KIND_ROUTES
from ..api.types import to_dict

__all__ = ["HTTPAPIServer"]


class HTTPAPIServer:
    """APIServer-interface client over HTTP (one connection per request;
    watches hold a streaming connection + reader thread per subscription)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._watches: Dict[int, tuple] = {}  # id(queue) -> (conn, resp, thread, stop)
        self._lock = threading.Lock()

    # -- request plumbing --------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        conn = http.client.HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            payload = None if body is None else json.dumps(body)
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = json.loads(resp.read() or b"{}")
            if resp.status == 404:
                raise NotFoundError(data.get("message", path))
            if resp.status == 409:
                if data.get("reason") == "Conflict":
                    raise ConflictError(data.get("message", path))
                raise AlreadyExistsError(data.get("message", path))
            if resp.status >= 400:
                raise RuntimeError(f"{method} {path}: {resp.status} {data}")
            return data
        finally:
            conn.close()

    @staticmethod
    def _collection_path(kind: str, namespace: Optional[str]) -> str:
        prefix, plural, namespaced = KIND_ROUTES[kind]
        if namespaced and namespace:
            return f"{prefix}/namespaces/{quote(namespace)}/{plural}"
        return f"{prefix}/{plural}"

    def _object_path(self, kind: str, namespace: str, name: str) -> str:
        return f"{self._collection_path(kind, namespace)}/{quote(name)}"

    @staticmethod
    def _as_dict(obj) -> dict:
        return obj if isinstance(obj, dict) else to_dict(obj)

    # -- APIServer interface ----------------------------------------------

    def ensure_crd(self, name: str, spec: Optional[dict] = None) -> bool:
        try:
            self._request(
                "POST", CRD_PATH, {"metadata": {"name": name}, "spec": spec or {}}
            )
            return True
        except AlreadyExistsError:
            return False

    def crds(self) -> List[str]:
        return self._request("GET", CRD_PATH)["items"]

    def create(self, kind: str, obj) -> dict:
        d = self._as_dict(obj)
        ns = (d.get("metadata") or {}).get("namespace", "default")
        return self._request("POST", self._collection_path(kind, ns), d)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._request("GET", self._object_path(kind, namespace, name))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[dict]:
        path = self._collection_path(kind, namespace)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += f"?labelSelector={quote(sel)}"
        return self._request("GET", path)["items"]

    def update(self, kind: str, obj) -> dict:
        d = self._as_dict(obj)
        meta = d.get("metadata") or {}
        path = self._object_path(
            kind, meta.get("namespace", "default"), meta.get("name", "")
        )
        return self._request("PUT", path, d)

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._request(
            "PATCH", self._object_path(kind, namespace, name), patch
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._object_path(kind, namespace, name))

    def delete_collection(self, kind: str, namespace: Optional[str] = None) -> int:
        return self._request(
            "DELETE", self._collection_path(kind, namespace)
        ).get("deleted", 0)

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, *, replay: bool = True) -> "queue.Queue[WatchEvent]":
        """Open a streaming watch; events arrive on the returned queue
        (same contract as APIServer.watch)."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        conn = http.client.HTTPConnection(self.host, self.port)
        path = (
            self._collection_path(kind, None)
            + f"?watch=1&replay={'1' if replay else '0'}"
        )
        conn.request("GET", path)
        resp = conn.getresponse()
        stop = threading.Event()

        def reader() -> None:
            try:
                while not stop.is_set():
                    line = resp.fp.readline()
                    if not line or stop.is_set():
                        return  # stream closed or unsubscribed
                    line = line.strip()
                    if not line:
                        continue  # heartbeat
                    ev = json.loads(line)
                    q.put(WatchEvent(ev["type"], kind, ev["object"]))
            except (OSError, ValueError):
                pass  # connection torn down by stop_watch or server exit

        t = threading.Thread(
            target=reader, name=f"http-watch-{kind}", daemon=True
        )
        t.start()
        with self._lock:
            self._watches[id(q)] = (conn, resp, t, stop)
        return q

    def stop_watch(self, kind: str, q: queue.Queue) -> None:
        with self._lock:
            entry = self._watches.pop(id(q), None)
        if entry is None:
            return
        conn, resp, _, stop = entry
        stop.set()
        # resp holds its own buffered socket file — closing the connection
        # alone leaves the reader consuming buffered events
        try:
            resp.close()
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            entries = list(self._watches.values())
            self._watches.clear()
        for conn, resp, _, stop in entries:
            stop.set()
            for c in (resp, conn):
                try:
                    c.close()
                except OSError:
                    pass
