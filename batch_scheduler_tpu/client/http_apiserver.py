"""HTTPAPIServer: point the control plane at a REAL (remote) API server.

Implements the same interface as ``client.apiserver.APIServer`` — create /
get / list / update / patch / delete / delete_collection / watch /
stop_watch / ensure_crd — over Kubernetes-shaped HTTP (the dialect served by
``client.http_gateway``, which is the k8s resource-path + watch-stream
protocol shape a KWOK-simulated cluster speaks). ``Clientset`` and
``SharedInformerFactory`` take it unchanged, so the whole scheduler stack
can run against an external endpoint — the capability the reference gets
from client-go (reference pkg/generated/clientset/versioned/
clientset.go:58-97, informers list+watch factory.go:79-180). The in-memory
path is untouched.
"""

from __future__ import annotations

import http.client
import json
import queue
import socket
import threading
from typing import Dict, List, Optional
from urllib.parse import quote

from .apiserver import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    WatchEvent,
)
from ..utils.throttle import TokenBucket
from .http_gateway import CRD_PATH, KIND_ROUTES
from ..api.types import to_dict

__all__ = ["HTTPAPIServer"]


class HTTPAPIServer:
    """APIServer-interface client over HTTP (persistent per-thread
    request connections, client-go style; watches hold a streaming
    connection + reader thread per subscription)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 10.0,
        qps: float = 10.0,
        burst: int = 20,
        pg_qps: Optional[float] = None,
        pg_burst: int = 20,
        batch_bind: bool = True,
    ):
        self.host = host
        self.port = port
        self.timeout = timeout
        # Client-side flow control at the reference's defaults (QPS=10 /
        # Burst=20, batchscheduler.go:391-392): every request verb takes a
        # token first, so the controller's resync across all groups cannot
        # stampede a real API server. Watch streams pace themselves via
        # the reflector's reconnect backoff instead. qps<=0 disables.
        #
        # ``pg_qps``/``pg_burst`` carve out a SEPARATE bucket for PodGroup
        # verbs, mirroring the reference deployment where the PG clientset
        # has its own rest.Config throttle (10/20) while the embedding
        # kube-scheduler's client runs at its own limits (50/100 defaults)
        # — one shared bucket would let pod traffic starve gang status
        # writes and vice versa.
        self._limiter = TokenBucket(qps, burst)
        # pg_burst applies only when pg_qps enables the separate bucket
        self._pg_limiter = (
            TokenBucket(pg_qps, pg_burst) if pg_qps is not None else None
        )
        # ``batch_bind=False`` forces per-pod PATCH binds (measurement
        # control: quantifies what the pods:bindmany verb buys at a fixed
        # client QPS — benchmarks/http_e2e.py)
        self._batch_bind = batch_bind
        # id(queue) -> {"conn", "resp", "thread", "stop"} (see watch())
        self._watches: Dict[int, dict] = {}
        self._lock = threading.Lock()
        # per-thread persistent connection for request/response verbs
        # (client-go keeps connections alive the same way); watches use
        # their own streaming connections
        self._local = threading.local()

    # -- request plumbing --------------------------------------------------

    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            conn.connect()
            # small request/response exchanges on a kept-alive connection
            # hit the Nagle/delayed-ACK stall (~40ms each) without this
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self._local.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except OSError:
                pass

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        kind: Optional[str] = None,
    ) -> dict:
        limiter = self._limiter
        if kind == "PodGroup" and self._pg_limiter is not None:
            limiter = self._pg_limiter
        limiter.acquire()
        payload = None if body is None else json.dumps(body)
        headers = {"Content-Type": "application/json"} if payload else {}
        # one reconnect retry: a kept-alive connection the server closed
        # between requests (restart, idle timeout) surfaces as a
        # connection-level error; a fresh connection disambiguates a real
        # outage from a stale socket. A failure AFTER the request bytes
        # went out may mean the server applied it with only the response
        # lost, so post-send retries are limited to verbs safe to
        # double-apply — a re-sent POST could turn a lost create response
        # into a spurious AlreadyExists. PATCH qualifies ONLY because
        # every patch through this client is an RFC 7386 merge patch
        # (absolute field values, idempotent); a future read-modify-write
        # or JSON-patch verb must come off this list.
        idempotent = method in ("GET", "PUT", "PATCH", "DELETE")
        for attempt in (0, 1):
            conn = self._conn()
            sent = False
            try:
                conn.request(method, path, body=payload, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = json.loads(resp.read() or b"{}")
                break
            except (OSError, http.client.HTTPException, ValueError):
                self._drop_conn()
                if attempt or (sent and not idempotent):
                    raise
        if resp.status == 404:
            raise NotFoundError(data.get("message", path))
        if resp.status == 409:
            if data.get("reason") == "Conflict":
                raise ConflictError(data.get("message", path))
            raise AlreadyExistsError(data.get("message", path))
        if resp.status >= 400:
            raise RuntimeError(f"{method} {path}: {resp.status} {data}")
        return data

    @staticmethod
    def _collection_path(kind: str, namespace: Optional[str]) -> str:
        prefix, plural, namespaced = KIND_ROUTES[kind]
        if namespaced and namespace:
            return f"{prefix}/namespaces/{quote(namespace)}/{plural}"
        return f"{prefix}/{plural}"

    def _object_path(self, kind: str, namespace: str, name: str) -> str:
        return f"{self._collection_path(kind, namespace)}/{quote(name)}"

    @staticmethod
    def _as_dict(obj) -> dict:
        return obj if isinstance(obj, dict) else to_dict(obj)

    # -- APIServer interface ----------------------------------------------

    def ensure_crd(self, name: str, spec: Optional[dict] = None) -> bool:
        try:
            self._request(
                "POST", CRD_PATH, {"metadata": {"name": name}, "spec": spec or {}}
            )
            return True
        except AlreadyExistsError:
            return False

    def crds(self) -> List[str]:
        return self._request("GET", CRD_PATH)["items"]

    def create(self, kind: str, obj) -> dict:
        d = self._as_dict(obj)
        ns = (d.get("metadata") or {}).get("namespace", "default")
        return self._request("POST", self._collection_path(kind, ns), d, kind=kind)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._request(
            "GET", self._object_path(kind, namespace, name), kind=kind
        )

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[dict]:
        path = self._collection_path(kind, namespace)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += f"?labelSelector={quote(sel)}"
        return self._request("GET", path, kind=kind)["items"]

    def update(self, kind: str, obj) -> dict:
        d = self._as_dict(obj)
        meta = d.get("metadata") or {}
        path = self._object_path(
            kind, meta.get("namespace", "default"), meta.get("name", "")
        )
        return self._request("PUT", path, d, kind=kind)

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        return self._request(
            "PATCH", self._object_path(kind, namespace, name), patch, kind=kind
        )

    def bind_pods(self, namespace: str, pairs) -> List[str]:
        """Batched bind over the wire: ONE request (one throttle token)
        for a whole released gang, via the gateway's ``pods:bindmany``
        custom verb — the cross-gang commit flush's per-gang API-pass
        amortization carried over HTTP (Clientset.bind_many dispatches
        here via the ``bind_pods`` duck type). Falls back to per-pod
        PATCH binds against a gateway without the route (404), keeping
        the bind_many contract: returns names bound, skips missing."""
        if self._batch_bind:
            path = self._collection_path("Pod", namespace) + ":bindmany"
            try:
                return self._request(
                    "POST", path, {"binds": [[n, node] for n, node in pairs]}
                )["bound"]
            except NotFoundError:
                # gateway without the batch verb: remember (capability
                # discovered once, client-go style) so later flushes skip
                # the deterministic 404 round trip + throttle token
                self._batch_bind = False
        bound = []
        for name, node in pairs:
            try:
                self.patch(
                    "Pod", namespace, name, {"spec": {"node_name": node}}
                )
            except NotFoundError:
                continue
            bound.append(name)
        return bound

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request(
            "DELETE", self._object_path(kind, namespace, name), kind=kind
        )

    def delete_collection(self, kind: str, namespace: Optional[str] = None) -> int:
        return self._request(
            "DELETE", self._collection_path(kind, namespace), kind=kind
        ).get("deleted", 0)

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, *, replay: bool = True) -> "queue.Queue[WatchEvent]":
        """Open a streaming watch; events arrive on the returned queue
        (same contract as APIServer.watch).

        Reflector semantics (client-go's relist, reference informers
        factory.go:117-133 -> NewSharedIndexInformer): if the stream drops
        for any reason other than stop_watch — gateway restart, LB blip,
        half-open timeout — the reader reconnects with backoff and
        ``replay=1``. The gateway replays current state terminated by a
        BOOKMARK line; the reader forwards the replay (level-based
        consumers overwrite) and, at the BOOKMARK, synthesizes DELETED
        events for every object it had delivered that no longer exists —
        so informers converge instead of freezing on a stale cache."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        stop = threading.Event()
        entry = {"conn": None, "resp": None, "stop": stop}
        # last-delivered object per key: the source for synthesized DELETEDs
        known: Dict[tuple, dict] = {}

        def connect(replay_flag: bool):
            # Read timeout >> the gateway's 0.2s heartbeat: a half-open
            # connection (no FIN/RST — host power loss, NAT drop) surfaces
            # as socket.timeout instead of blocking readline forever
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=max(self.timeout, 5.0)
            )
            path = (
                self._collection_path(kind, None)
                + f"?watch=1&replay={'1' if replay_flag else '0'}"
            )
            conn.request("GET", path)
            resp = conn.getresponse()
            with self._lock:
                entry["conn"], entry["resp"] = conn, resp
            if stop.is_set():  # lost the race with stop_watch
                raise OSError("watch stopped")
            return resp

        def key_of(obj: dict) -> tuple:
            meta = obj.get("metadata") or {}
            return (meta.get("namespace", "default"), meta.get("name", ""))

        def consume(resp, resyncing: bool) -> None:
            """Forward events until the stream ends. ``resyncing``: treat
            the leading replay (up to the BOOKMARK) as a relist to diff
            against ``known`` — and, when the subscription was opened with
            ``replay=False``, use it for that bookkeeping WITHOUT
            forwarding (the caller opted out of replays)."""
            replay_seen: set = set()
            while not stop.is_set():
                line = resp.fp.readline()
                if not line or stop.is_set():
                    return
                line = line.strip()
                if not line:
                    continue  # heartbeat
                ev = json.loads(line)
                etype = ev.get("type")
                if etype == "BOOKMARK":
                    if resyncing:
                        for gone_key in set(known) - replay_seen:
                            q.put(
                                WatchEvent(
                                    WatchEvent.DELETED,
                                    kind,
                                    known.pop(gone_key),
                                )
                            )
                        resyncing = False
                    continue
                obj = ev["object"]
                k = key_of(obj)
                in_replay = resyncing
                if etype == WatchEvent.DELETED:
                    known.pop(k, None)
                else:
                    known[k] = obj
                    if resyncing:
                        replay_seen.add(k)
                if in_replay and not replay:
                    continue  # resync bookkeeping only; caller opted out
                q.put(WatchEvent(etype, kind, obj))

        def reader() -> None:
            backoff = 0.2
            first = True
            while not stop.is_set():
                established = False
                try:
                    # reconnects always replay: the relist is what resyncs
                    resp = connect(replay if first else True)
                    established = True
                    consume(resp, resyncing=not first)
                except (OSError, ValueError, http.client.HTTPException):
                    pass  # fall through to reconnect (or exit if stopped)
                if first:
                    first = False
                if stop.is_set():
                    return
                stop.wait(backoff)
                # a stream that actually established resets the backoff
                # (client-go behavior); repeated connect failures keep
                # growing it toward the cap
                backoff = 0.2 if established else min(backoff * 2, 5.0)

        t = threading.Thread(
            target=reader, name=f"http-watch-{kind}", daemon=True
        )
        t.start()
        entry["thread"] = t
        with self._lock:
            self._watches[id(q)] = entry
        return q

    @staticmethod
    def _close_entry(entry: dict) -> None:
        entry["stop"].set()
        # resp holds its own buffered socket file — closing the connection
        # alone leaves the reader consuming buffered events
        for field in ("resp", "conn"):
            c = entry.get(field)
            if c is not None:
                try:
                    c.close()
                except OSError:
                    pass

    def stop_watch(self, kind: str, q: queue.Queue) -> None:
        with self._lock:
            entry = self._watches.pop(id(q), None)
        if entry is None:
            return
        self._close_entry(entry)

    def close(self) -> None:
        with self._lock:
            entries = list(self._watches.values())
            self._watches.clear()
        for entry in entries:
            self._close_entry(entry)
        # persistent request connections are per-thread; only the calling
        # thread's can be closed here (the others close when their threads
        # exit), but that covers the common single-threaded-client case
        self._drop_conn()
