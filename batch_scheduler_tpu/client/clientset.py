"""Typed clientset over the API server.

Mirror of the reference's generated clientset surface
(reference pkg/generated/clientset/versioned/typed/podgroup/v1/
podgroup.go:67-191: Get/List/Watch/Create/Update/UpdateStatus/Delete/
DeleteCollection/Patch) plus the core/v1 slices the controller consumes
(pods by label selector, nodes — reference controller.go:206,240).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api.serde import node_from_dict, pod_from_dict, pod_group_from_dict
from ..api.types import Node, Pod, PodGroup, to_dict
from .apiserver import APIServer, NotFoundError

__all__ = ["Clientset", "PodGroupInterface", "PodInterface", "NodeInterface"]


class _TypedInterface:
    KIND = ""

    def __init__(self, api: APIServer, namespace: Optional[str]):
        self._api = api
        self._ns = namespace

    def _decode(self, d: dict):
        raise NotImplementedError

    def create(self, obj):
        return self._decode(self._api.create(self.KIND, to_dict(obj)))

    def get(self, name: str):
        return self._decode(self._api.get(self.KIND, self._ns, name))

    def list(self, label_selector: Optional[Dict[str, str]] = None):
        return [
            self._decode(d)
            for d in self._api.list(self.KIND, self._ns, label_selector)
        ]

    def update(self, obj):
        return self._decode(self._api.update(self.KIND, to_dict(obj)))

    def update_status(self, obj):
        """Status-subresource update: merge only the status stanza, like the
        reference's UpdateStatus verb."""
        d = to_dict(obj)
        return self._decode(
            self._api.patch(
                self.KIND,
                self._ns,
                d["metadata"]["name"],
                {"status": d["status"]},
            )
        )

    def patch(self, name: str, patch: dict):
        return self._decode(self._api.patch(self.KIND, self._ns, name, patch))

    def patch_many(self, pairs) -> List[str]:
        """Bulk merge patch: one API pass where the backend supports it,
        per-object patches otherwise. Missing objects are skipped; returns
        the names actually patched (no response decode — callers that
        need the updated objects patch individually)."""
        api_patch_many = getattr(self._api, "patch_many", None)
        if api_patch_many is not None:
            return api_patch_many(self.KIND, self._ns, pairs)
        patched = []
        for name, patch in pairs:
            try:
                self._api.patch(self.KIND, self._ns, name, patch)
            except NotFoundError:
                continue
            patched.append(name)
        return patched

    def delete(self, name: str) -> None:
        self._api.delete(self.KIND, self._ns, name)

    def delete_collection(self) -> int:
        return self._api.delete_collection(self.KIND, self._ns)

    def watch(self, replay: bool = True):
        return self._api.watch(self.KIND, replay=replay)


class PodGroupInterface(_TypedInterface):
    KIND = "PodGroup"

    def _decode(self, d: dict) -> PodGroup:
        return pod_group_from_dict(d)


class PodInterface(_TypedInterface):
    KIND = "Pod"

    def _decode(self, d: dict) -> Pod:
        return pod_from_dict(d)

    def bind(self, name: str, node_name: str) -> Pod:
        """The bind subresource: commit a pod to a node."""
        return self.patch(name, {"spec": {"node_name": node_name}})

    def bind_many(self, pairs: List[Tuple[str, str]]) -> List[str]:
        """Batched bind: one API round trip for a whole released gang
        (gang-granular choreography; reference precedent for whole-gang
        release sweeps is StartBatchSchedule, batchscheduler.go:254-344).
        Falls back to per-pod binds when the backing API lacks the batched
        verb (e.g. the HTTP gateway). Returns the names actually bound;
        missing pods are skipped."""
        bind_pods = getattr(self._api, "bind_pods", None)
        if bind_pods is not None:
            return bind_pods(self._ns, pairs)
        bound = []
        for name, node_name in pairs:
            try:
                self.patch(name, {"spec": {"node_name": node_name}})
            except NotFoundError:
                continue
            bound.append(name)
        return bound


class NodeInterface(_TypedInterface):
    KIND = "Node"

    def _decode(self, d: dict) -> Node:
        return node_from_dict(d)

    def create(self, obj):
        d = to_dict(obj)
        d.setdefault("metadata", {})["namespace"] = ""  # cluster-scoped
        return self._decode(self._api.create(self.KIND, d))


class Clientset:
    """``clientset.podgroups(ns)`` / ``clientset.pods(ns)`` /
    ``clientset.nodes()`` — the typed CRUD surface."""

    def __init__(self, api: APIServer):
        self.api = api

    def podgroups(self, namespace: str = "default") -> PodGroupInterface:
        return PodGroupInterface(self.api, namespace)

    def pods(self, namespace: str = "default") -> PodInterface:
        return PodInterface(self.api, namespace)

    def nodes(self) -> NodeInterface:
        # nodes are cluster-scoped; stored under the "" namespace
        return NodeInterface(self.api, "")

    def all_pod_groups(self) -> List[PodGroup]:
        return [pod_group_from_dict(d) for d in self.api.list("PodGroup")]

    def all_pods(self) -> List[Pod]:
        return [pod_from_dict(d) for d in self.api.list("Pod")]
