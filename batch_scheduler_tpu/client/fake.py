"""Fake clientset for tests: a clientset over a fresh in-memory API server,
pre-seeded with objects (reference pkg/generated/clientset/versioned/fake/
clientset_generated.go:36-61)."""

from __future__ import annotations

from ..api.types import Node, Pod, PodGroup, to_dict
from .apiserver import APIServer
from .clientset import Clientset

__all__ = ["new_simple_clientset"]


def new_simple_clientset(*objects) -> Clientset:
    api = APIServer()
    cs = Clientset(api)
    for obj in objects:
        if isinstance(obj, PodGroup):
            cs.podgroups(obj.metadata.namespace).create(obj)
        elif isinstance(obj, Pod):
            cs.pods(obj.metadata.namespace).create(obj)
        elif isinstance(obj, Node):
            cs.nodes().create(obj)
        else:
            raise TypeError(f"unsupported seed object: {type(obj)!r}")
    return cs
