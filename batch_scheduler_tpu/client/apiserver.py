"""In-memory API server: the durable-state store and watch hub.

Plays the role the Kubernetes API server plays for the reference — the only
durable state in the system (reference keeps all persistent state in CRD
status patched over HTTPS; in-memory caches are rebuilt from informers,
SURVEY.md §5 "Checkpoint/resume"). Objects are stored as plain dicts keyed
by (kind, namespace, name); writers get JSON-merge-patch semantics; watchers
get ordered ADDED/MODIFIED/DELETED events over thread-safe queues.

The fake clientset for tests (reference pkg/generated/clientset/versioned/
fake) is this same store with no external transport — see client.fake.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api.serde import object_from_dict
from ..api.types import new_uid, to_dict
from ..utils.patch import apply_merge_patch, json_deepcopy

__all__ = ["APIServer", "WatchEvent", "NotFoundError", "ConflictError", "AlreadyExistsError"]


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    pass


class WatchEvent:
    __slots__ = ("type", "kind", "obj")

    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"

    def __init__(self, type_: str, kind: str, obj: dict):
        self.type = type_
        self.kind = kind
        self.obj = obj

    def object(self):
        """Rehydrate the typed API object (deep copy; safe to mutate)."""
        return object_from_dict(self.kind, json_deepcopy(self.obj))


class APIServer:
    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.RLock()
        self._clock = clock
        # kind -> (namespace, name) -> dict
        self._store: Dict[str, Dict[Tuple[str, str], dict]] = {}  # guarded-by: _lock
        self._rv = 0  # guarded-by: _lock
        self._watchers: Dict[str, List[queue.Queue]] = {}  # guarded-by: _lock
        self._crds: Dict[str, dict] = {}  # guarded-by: _lock
        # label index: kind -> (label_key, label_value) -> object keys —
        # keeps selector lists (the controller's per-group member listing,
        # reference controller.go:235-241) O(matches), not O(all objects)
        self._label_idx: Dict[str, Dict[Tuple[str, str], Set[Tuple[str, str]]]] = {}  # guarded-by: _lock
        # bind fencing token: each gateway generation advances it at
        # startup (serve_gateway) and stamps its binds with the epoch it
        # was born under. A handler thread that outlives its gateway's
        # death (shutdown/server_close stop the accept loop and sever
        # sockets, but cannot kill a thread already past the read) would
        # otherwise apply its bind against this shared store AFTER a
        # restarted gateway served the scheduler a fresh liveness read —
        # the zombie-bind over-commit (test_fuzz_combo_selector_churn_
        # outage). Fenced binds are dropped, making "unbound on a read
        # through the NEW gateway" conclusive evidence the lost request
        # will never apply.
        self._bind_epoch = 0

    # -- helpers -----------------------------------------------------------

    def _kind_store(self, kind: str) -> Dict[Tuple[str, str], dict]:  # lock-held: _lock
        return self._store.setdefault(kind, {})

    @staticmethod
    def _labels_of(obj: dict) -> dict:
        return (obj.get("metadata") or {}).get("labels") or {}

    def _index_add(self, kind: str, key: Tuple[str, str], obj: dict) -> None:  # lock-held: _lock
        idx = self._label_idx.setdefault(kind, {})
        for kv in self._labels_of(obj).items():
            idx.setdefault(kv, set()).add(key)

    def _index_remove(self, kind: str, key: Tuple[str, str], obj: dict) -> None:  # lock-held: _lock
        idx = self._label_idx.get(kind, {})
        for kv in self._labels_of(obj).items():
            bucket = idx.get(kv)
            if bucket is not None:
                bucket.discard(key)

    def _notify(self, kind: str, event: WatchEvent) -> None:  # lock-held: _lock
        """Fan an event out to every watcher.

        ``event.obj`` is the STORED dict itself, shared by all watchers and
        informer stores — never a per-event copy. Safe because stored dicts
        are immutable once stored: every write verb replaces the store entry
        with a new document (patch copy-on-writes via apply_merge_patch and
        re-dicts metadata before stamping resource_version), and all raw
        readers (informer stores/peek_raw/list_raw_by_label, the HTTP
        gateway's serializer) are read-only by contract. GET/LIST responses
        at the API boundary still deep-copy."""
        for q in self._watchers.get(kind, []):
            q.put(event)

    def _notify_many(self, kind: str, events: List[WatchEvent]) -> None:  # lock-held: _lock
        """Batched fanout: ONE queue put per watcher for a whole chunk of
        events (same shared-stored-dict contract as _notify). The put/get
        machinery costs ~2µs a side, so per-object puts across a 30k-event
        flood were measurable GIL load on every writer thread. Consumers
        receive the list as one queue item; utils.drain.drain_queue
        flattens transparently, and direct q.get() readers (the HTTP
        gateway stream) normalise with `isinstance(item, list)`."""
        if not events:
            return
        for q in self._watchers.get(kind, []):
            q.put(events)

    @staticmethod
    def _as_dict(obj) -> dict:
        return obj if isinstance(obj, dict) else to_dict(obj)

    # -- CRD registration (reference batchscheduler.go:416-436) -----------

    def ensure_crd(self, name: str, spec: Optional[dict] = None) -> bool:
        """Idempotent CRD create; returns True if newly created."""
        with self._lock:
            if name in self._crds:
                return False
            self._crds[name] = spec or {}
            return True

    def crds(self) -> List[str]:
        with self._lock:
            return list(self._crds)

    # -- CRUD --------------------------------------------------------------

    def create(self, kind: str, obj) -> dict:
        d = json_deepcopy(self._as_dict(obj))
        meta = d.setdefault("metadata", {})
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        with self._lock:
            store = self._kind_store(kind)
            if key in store:
                raise AlreadyExistsError(f"{kind} {key[0]}/{key[1]} exists")
            self._rv += 1
            meta["resource_version"] = self._rv
            if not meta.get("creation_timestamp"):
                meta["creation_timestamp"] = self._clock()
            # the real API server always stamps a UID at admission; gang
            # accounting (MatchedPodNodes/PodNameUIDs) is keyed by it
            if not meta.get("uid"):
                meta["uid"] = new_uid(kind.lower())
            store[key] = d
            self._index_add(kind, key, d)
            self._notify(kind, WatchEvent(WatchEvent.ADDED, kind, d))
            return json_deepcopy(d)

    def create_many(
        self, kind: str, objs: List[dict], assume_fresh: bool = False
    ) -> int:
        """Bulk create: one lock pass, one ADDED event per object, and no
        per-object response copies (callers ingesting load — the sim
        harness feeding 10k pods — never read the responses; the per-call
        ``create`` pays two deep copies per object). Name conflicts
        present at call time raise before anything commits; the lock is
        then released between commit chunks (a 10k-object ingest must not
        block every concurrent patch/bind for its whole duration), so an
        object racing a concurrent ``create`` of the same name is skipped
        — the returned count is the number ACTUALLY created.

        ``assume_fresh``: skip the defensive deep copy when every dict was
        freshly built for this call and never retained by the caller (the
        sim harness's to_dict output) — the store takes ownership."""
        docs = []
        for obj in objs:
            d = self._as_dict(obj)
            if not assume_fresh:
                d = json_deepcopy(d)
            d.setdefault("metadata", {})
            docs.append(d)
        keys = [
            (
                d["metadata"].get("namespace", "default"),
                d["metadata"].get("name", ""),
            )
            for d in docs
        ]
        if len(set(keys)) != len(keys):
            raise AlreadyExistsError("duplicate names in create_many batch")
        with self._lock:
            store = self._kind_store(kind)
            for key in keys:
                if key in store:
                    raise AlreadyExistsError(f"{kind} {key[0]}/{key[1]} exists")
        chunk = 256
        created = 0
        for start in range(0, len(docs), chunk):
            with self._lock:
                store = self._kind_store(kind)
                events = []
                for d, key in zip(
                    docs[start : start + chunk], keys[start : start + chunk]
                ):
                    if key in store:  # raced a concurrent create: skip
                        continue
                    meta = d["metadata"]
                    self._rv += 1
                    meta["resource_version"] = self._rv
                    if not meta.get("creation_timestamp"):
                        meta["creation_timestamp"] = self._clock()
                    if not meta.get("uid"):
                        meta["uid"] = new_uid(kind.lower())
                    store[key] = d
                    self._index_add(kind, key, d)
                    events.append(WatchEvent(WatchEvent.ADDED, kind, d))
                    created += 1
                self._notify_many(kind, events)
        return created

    def patch_many(
        self, kind: str, namespace: str, patches: List[Tuple[str, dict]]
    ) -> List[str]:
        """Bulk merge patch: one lock pass, one patch + MODIFIED event per
        object, no response copies. Missing objects are skipped. Returns
        the names patched. (The sim kubelet drives thousands of
        Pending->Running transitions per run; per-call ``patch`` pays a
        response deep copy and a lock round trip each.)"""
        patched: List[str] = []
        chunk = 64  # bounded lock hold, like bind_pods
        for start in range(0, len(patches), chunk):
            with self._lock:
                store = self._kind_store(kind)
                events = []
                for name, patch in patches[start : start + chunk]:
                    key = (namespace, name)
                    old = store.get(key)
                    if old is None:
                        continue
                    merged = apply_merge_patch(old, patch)
                    self._rv += 1
                    merged["metadata"] = dict(merged.get("metadata") or {})
                    merged["metadata"]["resource_version"] = self._rv
                    self._index_remove(kind, key, old)
                    store[key] = merged
                    self._index_add(kind, key, merged)
                    events.append(WatchEvent(WatchEvent.MODIFIED, kind, merged))
                    patched.append(name)
                self._notify_many(kind, events)
        return patched

    def get(self, kind: str, namespace: str, name: str) -> dict:
        with self._lock:
            obj = self._kind_store(kind).get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return json_deepcopy(obj)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[dict]:
        with self._lock:
            store = self._kind_store(kind)
            if label_selector:
                # candidate set from the index on the first selector term,
                # verified against the rest — O(matches), not O(objects)
                idx = self._label_idx.get(kind, {})
                first, *rest = label_selector.items()
                keys = idx.get(first, set())
                out = []
                for key in keys:
                    obj = store.get(key)
                    if obj is None:
                        continue
                    if namespace is not None and key[0] != namespace:
                        continue
                    labels = self._labels_of(obj)
                    if any(labels.get(k) != v for k, v in rest):
                        continue
                    out.append(json_deepcopy(obj))
                return out
            return [
                json_deepcopy(obj)
                for (ns, _), obj in store.items()
                if namespace is None or ns == namespace
            ]

    def update(self, kind: str, obj) -> dict:
        """Replace an object. When the incoming object carries a nonzero
        ``metadata.resource_version``, it is an optimistic-concurrency
        precondition (Kubernetes update semantics): a mismatch with the
        stored version raises ConflictError — the compare-and-swap that
        makes API-server-backed leases race-free."""
        d = json_deepcopy(self._as_dict(obj))
        meta = d.setdefault("metadata", {})
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        with self._lock:
            store = self._kind_store(kind)
            if key not in store:
                raise NotFoundError(f"{kind} {key[0]}/{key[1]} not found")
            expect = meta.get("resource_version")
            have = (store[key].get("metadata") or {}).get("resource_version")
            if expect and have and expect != have:
                raise ConflictError(
                    f"{kind} {key[0]}/{key[1]}: resource_version {expect} "
                    f"is stale (have {have})"
                )
            self._rv += 1
            meta["resource_version"] = self._rv
            self._index_remove(kind, key, store[key])
            store[key] = d
            self._index_add(kind, key, d)
            self._notify(kind, WatchEvent(WatchEvent.MODIFIED, kind, d))
            return json_deepcopy(d)

    def patch(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        """RFC 7386 merge patch (the reference's only write verb for status,
        e.g. core.go:351, controller.go:300)."""
        with self._lock:
            store = self._kind_store(kind)
            key = (namespace, name)
            if key not in store:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            old = store[key]
            merged = apply_merge_patch(old, patch)
            self._rv += 1
            # apply_merge_patch shares untouched sub-trees with ``old``:
            # give merged its OWN metadata dict before stamping the new
            # resource_version, or the stamp would mutate the previous
            # object (and every shared watch event holding it) in place
            merged["metadata"] = dict(merged.get("metadata") or {})
            merged["metadata"]["resource_version"] = self._rv
            self._index_remove(kind, key, old)
            store[key] = merged
            self._index_add(kind, key, merged)
            self._notify(kind, WatchEvent(WatchEvent.MODIFIED, kind, merged))
            return json_deepcopy(merged)

    def advance_bind_epoch(self) -> int:
        """Advance the bind fencing token and return the new epoch (see
        ``_bind_epoch``). Called by each gateway generation at startup;
        binds stamped with an older epoch are dropped from then on."""
        with self._lock:
            self._bind_epoch += 1
            return self._bind_epoch

    def bind_pods(self, namespace: str, pairs: List[Tuple[str, str]],
                  epoch: int | None = None) -> List[str]:
        """Batched bind subresource: one lock pass, one merge patch + one
        MODIFIED event per pod. The whole-gang choreography binds a
        released gang as a unit (reference StartBatchSchedule releases a
        complete gang in one sweep, batchscheduler.go:254-344; here the
        bind itself is batched too). Missing pods are skipped — the caller
        forgets their assumed capacity. A bind patch touches only
        ``spec.node_name``, so the label index needs no maintenance.
        Returns the names actually bound.

        ``epoch`` (gateway binds) fences zombie writers: a request born
        under an epoch older than the store's current one applies NOTHING
        (checked per chunk, so a fence racing a long bind stops it at the
        next chunk boundary). In-process callers pass no epoch and are
        never fenced."""
        bound: List[str] = []
        chunk = 64  # bounded lock hold: a whole-flush bind (10s of pods)
        for start in range(0, len(pairs), chunk):
            with self._lock:
                if epoch is not None and epoch < self._bind_epoch:
                    # fenced: a newer gateway generation owns binding now
                    return bound
                store = self._kind_store("Pod")
                events = []
                for name, node_name in pairs[start : start + chunk]:
                    key = (namespace, name)
                    old = store.get(key)
                    if old is None:
                        continue
                    # hand-rolled single-field merge: same copy-on-write
                    # shape apply_merge_patch produces for this patch,
                    # without the generic merge walk (the bind storm is
                    # the hottest write path in the system)
                    merged = dict(old)
                    merged["spec"] = dict(old.get("spec") or {})
                    merged["spec"]["node_name"] = node_name
                    self._rv += 1
                    merged["metadata"] = dict(merged.get("metadata") or {})
                    merged["metadata"]["resource_version"] = self._rv
                    store[key] = merged
                    events.append(WatchEvent(WatchEvent.MODIFIED, "Pod", merged))
                    bound.append(name)
                self._notify_many("Pod", events)
        return bound

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            store = self._kind_store(kind)
            obj = store.pop((namespace, name), None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            self._index_remove(kind, (namespace, name), obj)
            self._notify(kind, WatchEvent(WatchEvent.DELETED, kind, obj))

    def delete_collection(
        self, kind: str, namespace: Optional[str] = None
    ) -> int:
        with self._lock:
            store = self._kind_store(kind)
            keys = [k for k in store if namespace is None or k[0] == namespace]
            for k in keys:
                obj = store.pop(k)
                self._index_remove(kind, k, obj)
                self._notify(kind, WatchEvent(WatchEvent.DELETED, kind, obj))
            return len(keys)

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, *, replay: bool = True) -> "queue.Queue[WatchEvent]":
        """Subscribe to a kind's event stream. With ``replay``, current
        objects are delivered first as ADDED events (informer list+watch)."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            if replay:
                # stored dicts are immutable once stored (see _notify):
                # replayed events share them like live events do
                for obj in self._kind_store(kind).values():
                    q.put(WatchEvent(WatchEvent.ADDED, kind, obj))
            self._watchers.setdefault(kind, []).append(q)
        return q

    def stop_watch(self, kind: str, q: queue.Queue) -> None:
        with self._lock:
            watchers = self._watchers.get(kind, [])
            if q in watchers:
                watchers.remove(q)
