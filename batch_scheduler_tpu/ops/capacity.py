"""Continuous cluster capacity analytics, computed where they are free.

The observability stack answers per-batch ("where did the nanoseconds
go", utils.profiler) and per-gang ("why is my gang pending",
core.explain) questions; this module answers the FLEET question an
operator asks first: how full is the cluster, how fragmented is the
remaining capacity, which tenant is consuming it, and can the pending
work actually land. It is one jit'd kernel (``capacity_summary``) run
against the committed batch inputs — the same device-resident buffers
ops.device_state already keeps in HBM — after a published batch, emitting
an **O(lanes) summary**:

- **per-lane utilization/headroom spectra** — lane totals plus a
  ``[R, _BINS]`` histogram of per-node headroom measured in units of the
  pending work's mean member demand, bucketed with the SAME
  ``min(cap, _BINS-1)`` clamp the assignment scan's ``_select_best_fit``
  / ``_hist_select`` ranking uses, so the spectrum agrees with what the
  scan can actually place;
- **fragmentation index** — the largest gang (vectorized power-of-two
  size sweep over the carried leftover) that could still place as one
  all-or-nothing unit, per priority tier and globally, vs the need-
  clipped total: lots of total headroom with a small largest-placeable
  is exactly "fragmented";
- **stranded capacity** — per-lane headroom sitting on nodes where NO
  pending gang shape fits even one member (capacity no queued work can
  consume);
- **seat-tightness distribution** — the stamped plan's seats histogrammed
  by the tightness bucket of their node at batch entry (how best-fit the
  placement actually was);
- **per-tenant dominant-resource shares** — namespace-derived
  (utils.tenancy), cardinality-capped attribution of consumed lanes and
  pending seats.

Cost discipline: the kernel is one scoring-pass equivalent
(``O(G·N·R)`` elementwise + scatters — the same class as the batch's own
``group_capacity``), and :class:`CapacitySampler` budget-gates it: after
a sample costing ``k`` seconds, the next is allowed no sooner than
``k / BST_CAPACITY_BUDGET_FRAC`` later, so the amortized hook cost is
``<= BST_CAPACITY_BUDGET_FRAC`` (default 2%) of wall-clock by
construction — the audit-hook discipline, enforced by ``make
bench-capacity``.

Determinism: the summary is derived from the batch inputs + result with
fixed arithmetic, keyed by lane/tier/tenant INDEX (names only decorate
display surfaces), and the per-batch tenant mapping is computed from the
batch's own names — so the offline ``capacity`` subcommand can replay a
recorded audit ring through this same kernel and reproduce the live
series bit-identically (the replay-gate discipline applied to analytics).
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .oracle import (
    _BIG,
    _BINS,
    GANG_MAX,
    _exact_floordiv,
    _member_capacity,
    group_capacity,
)

__all__ = [
    "capacity_summary",
    "annotate_summary",
    "format_capacity_verdict",
    "CapacitySampler",
    "capacity_enabled",
    "capacity_budget_frac",
    "set_active_sampler",
    "active_sampler",
    "capacity_debug_view",
    "TIERS",
]

# Priority tiers the fragmentation sweep reports on: gang priorities clip
# into [0, TIERS) — deterministic from the recorded priority column, so
# live and replayed summaries agree (tier 0 = the no-policy default).
TIERS = 4

# Power-of-two gang-size ladder for the largest-placeable sweep; 2**18 is
# GANG_MAX, the largest admissible gang (ops.oracle).
_SIZE_LADDER = tuple(2 ** p for p in range(19))


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------


def capacity_enabled() -> bool:
    """Parse-guarded BST_CAPACITY read: default ON; 0/off/false disables
    the sampler (the BST_DEVICE_STATE idiom)."""
    raw = os.environ.get("BST_CAPACITY", "").strip().lower()
    if raw in ("0", "off", "false", "no"):
        return False
    return True


def capacity_budget_frac() -> float:
    """Parse-guarded BST_CAPACITY_BUDGET_FRAC: the fraction of wall-clock
    the analytics hook may consume amortized (default 0.02). Clamped to
    [1e-4, 1.0]; 1.0 effectively samples every batch (gates/tests)."""
    raw = os.environ.get("BST_CAPACITY_BUDGET_FRAC", "").strip()
    if raw:
        try:
            return min(max(float(raw), 1e-4), 1.0)
        except ValueError:
            pass
    return 0.02


# ---------------------------------------------------------------------------
# the jit'd analytics kernel
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("tenants",))
def _capacity_kernel(
    alloc, requested, group_req, remaining, fit_mask, group_valid,
    placed, a_nodes, a_counts, scheduled, matched, tenant_id, tier,
    tenants: int,
):
    """The whole observatory in one traced function. Inputs are the
    padded batch args (ops.bucketing order), the batch result's plan
    fields, the progress counts, and the per-batch tenant/tier columns;
    every output is O(lanes)-small (the [R, _BINS] histogram is the
    largest). Pure: no env reads, no clocks (the jit-purity contract)."""
    f32 = jnp.float32
    n, r = alloc.shape
    placed_b = placed.astype(bool)
    valid_b = group_valid.astype(bool)
    left0 = alloc - requested
    # the stamped plan applied to the entry leftover: zero-count slots
    # carry arbitrary backfill node indexes, but their contribution is
    # zero, so the clip + scatter-add is correct without masking them
    counts = jnp.clip(a_counts, 0, GANG_MAX) * placed_b.astype(
        jnp.int32
    )[:, None]
    nodes_idx = jnp.clip(a_nodes, 0, n - 1)
    seats = jnp.sum(counts, axis=1)
    contrib = counts[:, :, None] * group_req[:, None, :]
    used_by_plan = jnp.zeros_like(alloc).at[nodes_idx.reshape(-1)].add(
        contrib.reshape(-1, r)
    )
    left_after = left0 - used_by_plan

    node_real = jnp.any(alloc > 0, axis=1)
    real_i = node_real.astype(jnp.int32)
    lf = jnp.clip(left_after, 0, _BIG).astype(f32) * real_i.astype(
        f32
    )[:, None]
    lane_alloc = jnp.sum(
        jnp.clip(alloc, 0, _BIG).astype(f32) * real_i.astype(f32)[:, None],
        axis=0,
    )
    lane_free = jnp.sum(lf, axis=0)
    lane_max_free = jnp.max(
        jnp.clip(left_after, 0, _BIG) * real_i[:, None], axis=0
    )

    # pending work and its mean member demand (the headroom yardstick)
    pend = valid_b & (~placed_b) & (remaining > 0)
    pend_members = remaining * pend.astype(jnp.int32)
    tot_pend = jnp.sum(pend_members)
    ref_num = jnp.sum(
        group_req.astype(f32) * pend_members.astype(f32)[:, None], axis=0
    )
    ref = jnp.where(
        tot_pend > 0,
        jnp.round(ref_num / jnp.maximum(tot_pend, 1).astype(f32)),
        0.0,
    ).astype(jnp.int32)

    # per-lane headroom spectrum, bucketed exactly like the scan ranks
    # nodes: min(capacity-in-members, _BINS-1); ref==0 lanes (no pending
    # demand touches them) park every real node in the top bucket
    per_lane_cap = jnp.where(
        ref[None, :] > 0,
        _exact_floordiv(
            jnp.clip(left_after, 0, _BIG), jnp.clip(ref[None, :], 1, _BIG)
        ),
        _BIG,
    )
    key_lane = jnp.minimum(per_lane_cap, _BINS - 1)
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (n, r), 1)
    headroom_hist = jnp.zeros((r, _BINS), jnp.int32).at[
        lane_iota.reshape(-1), key_lane.reshape(-1)
    ].add(jnp.broadcast_to(real_i[:, None], (n, r)).reshape(-1))

    # what the pending work can still consume of the carried leftover
    cap_after = group_capacity(left_after, group_req, fit_mask)
    capc = jnp.minimum(cap_after, remaining[:, None]) * pend.astype(
        jnp.int32
    )[:, None]
    feasible_after = (jnp.sum(capc, axis=1) >= remaining) & pend
    unplaceable = jnp.sum((pend & ~feasible_after).astype(jnp.int32))

    consumable = jnp.any((cap_after > 0) & pend[:, None], axis=0)
    has_head = jnp.any(left_after > 0, axis=1) & node_real
    stranded = has_head & (~consumable) & (tot_pend > 0)
    stranded_f = stranded.astype(f32)
    stranded_lane = jnp.sum(lf * stranded_f[:, None], axis=0)
    stranded_nodes = jnp.sum(stranded.astype(jnp.int32))

    # seat tightness: the plan's seats by their node's entry bucket
    cap0 = group_capacity(left0, group_req, fit_mask)
    key0 = jnp.minimum(cap0, _BINS - 1)
    seat_keys = jnp.take_along_axis(key0, nodes_idx, axis=1)
    seat_hist = jnp.zeros((_BINS,), jnp.int32).at[
        seat_keys.reshape(-1)
    ].add(counts.reshape(-1))

    # largest-placeable sweep (fragmentation), per tier + global: for a
    # reference member demand, the biggest ladder size s whose
    # need-clipped pooled capacity covers s — the all-or-nothing gang
    # admission rule applied to hypothetical sizes
    sizes = jnp.asarray(_SIZE_LADDER, jnp.int32)
    tiers_col = jnp.clip(tier, 0, TIERS - 1)

    def _largest(ref_row, active):
        cap_ref = _member_capacity(
            left_after, ref_row[None, :]
        ) * real_i
        cap_ref = jnp.clip(cap_ref, 0, GANG_MAX)
        tot_s = jnp.sum(
            jnp.minimum(cap_ref[None, :], sizes[:, None]).astype(f32),
            axis=1,
        )
        largest = jnp.max(
            sizes * (tot_s >= sizes.astype(f32)).astype(jnp.int32)
        )
        total_ref = jnp.sum(cap_ref.astype(f32))
        act = active.astype(jnp.int32)
        return largest * act, total_ref * active.astype(f32)

    tier_largest = []
    tier_pending = []
    for t in range(TIERS):
        tmask = pend & (tiers_col == t)
        tm = remaining * tmask.astype(jnp.int32)
        tt = jnp.sum(tm)
        ref_t = jnp.where(
            tt > 0,
            jnp.round(
                jnp.sum(group_req.astype(f32) * tm.astype(f32)[:, None],
                        axis=0)
                / jnp.maximum(tt, 1).astype(f32)
            ),
            0.0,
        ).astype(jnp.int32)
        lt, _ = _largest(ref_t, tt > 0)
        tier_largest.append(lt)
        tier_pending.append(tt)
    frag_largest, frag_total = _largest(ref, tot_pend > 0)

    # per-tenant attribution: members already on nodes (scheduled +
    # matched) plus this plan's seats, times the member demand row
    members_active = (
        jnp.clip(scheduled, 0, GANG_MAX)
        + jnp.clip(matched, 0, GANG_MAX)
        + seats
    )
    demand = members_active.astype(f32)[:, None] * group_req.astype(f32)
    tid = jnp.clip(tenant_id, 0, tenants - 1)
    tenant_used = jnp.zeros((tenants, r), f32).at[tid].add(
        demand * valid_b.astype(f32)[:, None]
    )
    tenant_pending = jnp.zeros((tenants,), jnp.int32).at[tid].add(
        pend_members
    )

    return {
        "lane_alloc": lane_alloc,
        "lane_free": lane_free,
        "lane_max_free": lane_max_free,
        "ref_demand": ref,
        "headroom_hist": headroom_hist,
        "stranded_lane": stranded_lane,
        "stranded_nodes": stranded_nodes,
        "seat_hist": seat_hist,
        "tier_largest": jnp.stack(tier_largest),
        "tier_pending": jnp.stack(tier_pending),
        "frag_largest": frag_largest,
        "frag_total": frag_total,
        "tenant_used": tenant_used,
        "tenant_pending": tenant_pending,
        "pending_gangs": jnp.sum(pend.astype(jnp.int32)),
        "pending_seats": tot_pend,
        "unplaceable_gangs": unplaceable,
        "placed_gangs": jnp.sum(placed_b.astype(jnp.int32)),
        "placed_seats": jnp.sum(seats),
        "nodes_real": jnp.sum(real_i),
    }


def _f(x) -> float:
    return round(float(x), 6)


def capacity_summary(
    batch_args: tuple,
    result: dict,
    *,
    group_names: Optional[List[str]] = None,
    scheduled=None,
    matched=None,
    policy_prio=None,
) -> dict:
    """One canonical capacity summary for a published batch.

    ``batch_args`` is the padded 7-tuple (host numpy or device-resident
    jax arrays — ops.bucketing order); ``result`` the batch's host plan
    dict (or an AuditReader record's ``result_arrays``). The summary is
    keyed by lane/tier/tenant INDEX and derived deterministically, so a
    recorded batch replayed through this function reproduces the live
    sample bit-identically on the same backend. ``policy_prio`` (the
    packed priority column) feeds the tier sweep; absent = every gang
    tier 0 — the same rule live and offline."""
    from ..utils.tenancy import batch_tenants, tenant_cap

    (alloc, requested, group_req, remaining, fit_mask, group_valid,
     _order) = batch_args
    g_bucket = int(np.asarray(remaining).shape[0])
    names = list(group_names or [])
    tenant_id, labels = batch_tenants(names, g_bucket)
    tenants = tenant_cap() + 1  # static width: labels pad into "other"
    zeros_g = np.zeros(g_bucket, dtype=np.int32)
    sched = zeros_g if scheduled is None else np.asarray(
        scheduled, dtype=np.int32
    )
    mat = zeros_g if matched is None else np.asarray(matched, dtype=np.int32)
    tier = zeros_g if policy_prio is None else np.asarray(
        policy_prio, dtype=np.int32
    )
    out = _capacity_kernel(
        alloc, requested, group_req, remaining, fit_mask, group_valid,
        np.asarray(result["placed"]).astype(np.int32),
        np.asarray(result["assignment_nodes"]).astype(np.int32),
        np.asarray(result["assignment_counts"]).astype(np.int32),
        sched, mat, tenant_id, tier,
        tenants=int(tenants),
    )
    out = {k: np.asarray(jax.device_get(v)) for k, v in out.items()}

    lanes = []
    r = out["lane_alloc"].shape[0]
    for i in range(r):
        alloc_i = _f(out["lane_alloc"][i])
        free_i = _f(out["lane_free"][i])
        used_i = _f(max(alloc_i - free_i, 0.0))
        lanes.append({
            "lane": i,
            "alloc": alloc_i,
            "free": free_i,
            "utilization": _f(used_i / max(alloc_i, 1.0)),
            "max_node_free": int(out["lane_max_free"][i]),
            "ref_member_demand": int(out["ref_demand"][i]),
            "stranded_free": _f(out["stranded_lane"][i]),
            "headroom_hist": [int(c) for c in out["headroom_hist"][i]],
        })

    frag_total = _f(out["frag_total"])
    frag_largest = int(out["frag_largest"])
    frag_index = _f(
        1.0 - frag_largest / frag_total if frag_total > 0 else 0.0
    )
    stranded_lane = out["stranded_lane"]
    top_stranded = int(np.argmax(stranded_lane)) if r else 0

    tenants_out = []
    for t, label in enumerate(labels):
        shares = {}
        dominant, dom_lane = 0.0, 0
        for i in range(r):
            s = _f(
                float(out["tenant_used"][t, i])
                / max(float(out["lane_alloc"][i]), 1.0)
            )
            shares[str(i)] = s
            if s > dominant:
                dominant, dom_lane = s, i
        pending_t = int(out["tenant_pending"][t])
        if dominant <= 0.0 and pending_t == 0 and label == "other":
            continue  # an empty overflow bucket is noise
        tenants_out.append({
            "tenant": label,
            "dominant_share": _f(dominant),
            "dominant_lane": dom_lane,
            "shares": shares,
            "pending_seats": pending_t,
        })
    top = max(
        tenants_out, key=lambda d: d["dominant_share"], default=None
    )

    return {
        "schema": "bst-capacity/v1",
        "nodes": int(out["nodes_real"]),
        "gangs": len(names) if names else g_bucket,
        "lanes": lanes,
        "fragmentation_index": frag_index,
        "largest_placeable_gang": frag_largest,
        "largest_placeable_by_tier": [
            int(x) for x in out["tier_largest"]
        ],
        "pending_seats_by_tier": [int(x) for x in out["tier_pending"]],
        "stranded": {
            "nodes": int(out["stranded_nodes"]),
            "top_lane": top_stranded,
            "top_lane_free": _f(stranded_lane[top_stranded]) if r else 0.0,
        },
        "seat_tightness_hist": [int(c) for c in out["seat_hist"]],
        "pending": {
            "gangs": int(out["pending_gangs"]),
            "seats": int(out["pending_seats"]),
            "unplaceable_gangs": int(out["unplaceable_gangs"]),
        },
        "placed": {
            "gangs": int(out["placed_gangs"]),
            "seats": int(out["placed_seats"]),
        },
        "tenants": tenants_out,
        "top_tenant": top["tenant"] if top else "",
        "top_tenant_share": top["dominant_share"] if top else 0.0,
    }


def annotate_summary(
    summary: dict, lane_names: Optional[List[str]] = None
) -> dict:
    """A display copy of a canonical summary with lane indices resolved
    to schema names (``lane<i>`` when unknown). The CANONICAL summary
    stays index-keyed — names never enter the bit-compared series."""
    names = list(lane_names or [])

    def lname(i: int) -> str:
        return names[i] if 0 <= i < len(names) else f"lane{i}"

    out = dict(summary)
    out["lanes"] = [
        {**lane, "name": lname(lane["lane"])} for lane in summary["lanes"]
    ]
    stranded = dict(summary["stranded"])
    stranded["top_lane_name"] = lname(stranded["top_lane"])
    out["stranded"] = stranded
    return out


def format_capacity_verdict(
    summary: dict, lane_names: Optional[List[str]] = None
) -> str:
    """The one-line exit-verdict form (cmd sim prints it beside the
    ``slo health:`` line)."""
    view = annotate_summary(summary, lane_names)
    util = {
        lane["name"]: lane["utilization"] for lane in view["lanes"]
        if lane["alloc"] > 0
    }
    busiest = max(util.items(), key=lambda kv: kv[1], default=("-", 0.0))
    pend = summary["pending"]
    parts = [
        f"frag {summary['fragmentation_index']:.2f}",
        f"largest placeable {summary['largest_placeable_gang']}",
        f"busiest lane {busiest[0]} {busiest[1] * 100:.0f}%",
    ]
    if summary["stranded"]["nodes"]:
        parts.append(
            f"stranded {summary['stranded']['nodes']} nodes "
            f"(top {view['stranded']['top_lane_name']})"
        )
    if summary["top_tenant"]:
        parts.append(
            f"top tenant {summary['top_tenant']} "
            f"{summary['top_tenant_share'] * 100:.0f}%"
        )
    if pend["unplaceable_gangs"]:
        parts.append(f"UNPLACEABLE {pend['unplaceable_gangs']} gangs")
    return "capacity: " + ", ".join(parts)


# ---------------------------------------------------------------------------
# the budget-gated sampler
# ---------------------------------------------------------------------------


class CapacitySampler:
    """Per-scorer (or per-sidecar) capacity sampling with the amortized
    cost bound built in: a sample costing ``k`` seconds schedules the
    next no sooner than ``k / budget_frac`` later. Samples land in a
    bounded downsampling ring (utils.timeseries), the Prometheus gauges,
    and — when an audit log is attached — a ``capacity_sample`` event in
    the audit ring keyed by the batch's audit ID (the offline replay's
    comparison anchor)."""

    def __init__(self, label: str = "scorer", registry=None):
        from ..utils.metrics import DEFAULT_REGISTRY
        from ..utils.timeseries import DownsamplingRing

        self.label = label
        self._reg = registry or DEFAULT_REGISTRY
        self._lock = threading.Lock()
        self._ring = DownsamplingRing()  # internally locked
        self._next_allowed = 0.0  # guarded-by: _lock
        self.samples = 0  # guarded-by: _lock
        self.skipped = 0  # guarded-by: _lock
        self.last_kernel_s = 0.0  # guarded-by: _lock
        self._last: Optional[dict] = None  # guarded-by: _lock
        self._lane_names: Optional[List[str]] = None  # guarded-by: _lock
        self._counter = self._reg.counter(
            "bst_capacity_samples_total",
            "Capacity-observatory kernel runs by outcome (sampled / "
            "budget-skipped / error)",
        )
        self._kernel_hist = self._reg.histogram(
            "bst_capacity_kernel_seconds",
            "Wall-clock of one capacity-analytics kernel run (the "
            "budget-gated hook cost)",
        )

    def note_batch(
        self,
        batch_args: tuple,
        result: dict,
        *,
        group_names: Optional[List[str]] = None,
        lane_names: Optional[List[str]] = None,
        scheduled=None,
        matched=None,
        policy_prio=None,
        audit_log=None,
        audit_id: Optional[str] = None,
    ) -> Optional[dict]:
        """Hot-path entry: run the kernel iff the budget allows, record
        the sample everywhere, return the summary (None when skipped).
        Never raises — analytics must not fail the decision path."""
        now = time.monotonic()
        with self._lock:
            if now < self._next_allowed:
                self.skipped += 1
                skipped = True
            else:
                skipped = False
                # reserve the slot INSIDE the gate check: the sidecar's
                # connection threads share one sampler, and a
                # check-then-act gate would let N concurrent publishers
                # all pass an open gate and pay the kernel in parallel —
                # N times the documented budget. The infinite sentinel
                # cannot expire mid-run (a >60s cold compile would reopen
                # a timed one); it is ALWAYS overwritten before anything
                # else can fail — by the error path (+5s) or by the real
                # spacing, both set before the ring/gauge exports run.
                self._next_allowed = float("inf")
        if skipped:
            self._counter.inc(outcome="skipped")
            return None
        try:
            t0 = time.perf_counter()
            summary = capacity_summary(
                batch_args, result, group_names=group_names,
                scheduled=scheduled, matched=matched,
                policy_prio=policy_prio,
            )
            kernel_s = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — analytics never break serving
            self._counter.inc(outcome="error")
            with self._lock:
                # an erroring kernel must not retry at line rate
                self._next_allowed = time.monotonic() + 5.0
            return None
        frac = capacity_budget_frac()
        with self._lock:
            self.samples += 1
            self.last_kernel_s = kernel_s
            # frac >= 1.0 means "every batch" (gates/tests); below it,
            # the spacing IS the amortized-cost bound
            self._next_allowed = (
                0.0 if frac >= 1.0
                else time.monotonic() + kernel_s / frac
            )
            self._last = summary
            if lane_names:
                self._lane_names = list(lane_names)
            ring = self._ring
        # the ring copy carries a 0/1 violation indicator the burn-rate
        # model consumes: downsampling AVERAGES it, so a merged entry's
        # value is exactly the fraction of violating raw samples it
        # folded (utils.health burn:capacity). A shallow copy — the
        # canonical summary recorded to the audit ring stays untouched
        # (the offline bit-compare contract).
        ring.append(
            time.time(),
            dict(
                summary,
                capacity_violation=(
                    1.0
                    if summary["pending"]["unplaceable_gangs"] > 0
                    else 0.0
                ),
            ),
        )
        self._counter.inc(outcome="sampled")
        self._kernel_hist.observe(kernel_s)
        self._export_gauges(summary)
        if audit_log is not None:
            try:
                audit_log.record_event(
                    "capacity_sample", audit_id=audit_id, summary=summary
                )
            except Exception:  # noqa: BLE001 — evidence best-effort
                pass
        return summary

    def _export_gauges(self, summary: dict) -> None:
        reg = self._reg
        reg.gauge(
            "bst_capacity_fragmentation_index",
            "1 - largest-placeable-gang / need-clipped total capacity "
            "(0 = one gang could take everything, ~1 = crumbs)",
        ).set(summary["fragmentation_index"])
        reg.gauge(
            "bst_capacity_largest_placeable_gang",
            "Largest power-of-two gang of the pending mean member demand "
            "still placeable as one unit, by priority tier",
        ).set(float(summary["largest_placeable_gang"]), tier="all")
        for t, v in enumerate(summary["largest_placeable_by_tier"]):
            if summary["pending_seats_by_tier"][t]:
                reg.gauge(
                    "bst_capacity_largest_placeable_gang", ""
                ).set(float(v), tier=str(t))
        util = reg.gauge(
            "bst_capacity_lane_utilization",
            "Per-lane cluster utilization (used / allocatable), lane-"
            "indexed per the snapshot schema",
        )
        stranded = reg.gauge(
            "bst_capacity_stranded_free",
            "Per-lane headroom on nodes no pending gang shape can "
            "consume (device units)",
        )
        with self._lock:
            names = list(self._lane_names or [])
        for lane in summary["lanes"]:
            i = lane["lane"]
            label = names[i] if i < len(names) else f"lane{i}"
            util.set(lane["utilization"], lane=label)
            stranded.set(lane["stranded_free"], lane=label)
        reg.gauge(
            "bst_capacity_stranded_nodes",
            "Nodes holding headroom that no pending gang shape can "
            "consume",
        ).set(float(summary["stranded"]["nodes"]))
        reg.gauge(
            "bst_capacity_pending_unplaceable_gangs",
            "Pending gangs the carried leftover cannot place even with "
            "every reserved seat released (capacity-infeasible now)",
        ).set(float(summary["pending"]["unplaceable_gangs"]))
        share = reg.gauge(
            "bst_capacity_tenant_share",
            "Per-tenant dominant-resource share of allocatable capacity "
            "(namespace-derived, cardinality-capped via "
            "BST_TENANT_LABEL_MAX)",
        )
        from ..utils.tenancy import OTHER_TENANT, tenant_label

        for t in summary["tenants"]:
            # the summary's labels are capped PER BATCH; the gauge's
            # label set must be capped PER PROCESS (the first-seen
            # registry) or namespace churn grows /metrics series without
            # bound over the process lifetime — the label-explosion
            # outage the cap exists to prevent
            label = (
                t["tenant"]
                if t["tenant"] == OTHER_TENANT
                else tenant_label(t["tenant"])
            )
            share.set(t["dominant_share"], tenant=label)

    # -- reporting -----------------------------------------------------------

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._last

    def lane_names(self) -> Optional[List[str]]:
        with self._lock:
            return list(self._lane_names) if self._lane_names else None

    def series(self, max_points: Optional[int] = None) -> List[dict]:
        return self._ring.series(max_points)

    def report(self, series_points: int = 512) -> dict:
        with self._lock:
            last = self._last
            names = list(self._lane_names or [])
            samples, skipped = self.samples, self.skipped
            kernel_s = self.last_kernel_s
        return {
            "label": self.label,
            "samples": samples,
            "skipped": skipped,
            "last_kernel_s": round(kernel_s, 6),
            "budget_frac": capacity_budget_frac(),
            "lane_names": names,
            "last": annotate_summary(last, names) if last else None,
            "ring": self._ring.stats(),
            "series": self.series(max_points=series_points),
        }


# ---------------------------------------------------------------------------
# the active-sampler registry (the set_active_pending pattern)
# ---------------------------------------------------------------------------

_active: list = [None]


def set_active_sampler(sampler: Optional[CapacitySampler]) -> None:
    """Each OracleScorer registers its sampler at construction so
    /debug/capacity (and the sim harness) answer for the LIVE scorer —
    a torn-down harness's ring must not answer a later one's query."""
    _active[0] = sampler


def active_sampler() -> Optional[CapacitySampler]:
    return _active[0]


def capacity_debug_view(params: Optional[dict] = None) -> tuple:
    """The /debug/capacity payload: (payload, http status). Bare GETs are
    self-describing 200s (the /debug/ index probe's contract)."""
    sampler = _active[0]
    if sampler is None:
        return (
            {
                "enabled": capacity_enabled(),
                "sampler": None,
                "hint": "no capacity sampler registered (oracle mode "
                        "with BST_CAPACITY on required)",
            },
            200,
        )
    params = params or {}
    points = 512
    raw = params.get("points")
    if raw is not None:
        # parse BEFORE building the report: the series copy is the
        # expensive part and must be taken exactly once, at the
        # requested trim
        try:
            points = max(1, int(raw))
        except ValueError:
            return {"error": f"malformed points={raw!r}"}, 400
    report = sampler.report(series_points=points)
    report["enabled"] = capacity_enabled()
    return report, 200
