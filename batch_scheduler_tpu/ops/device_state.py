"""Device-resident cluster state: jit'd scatter-update deltas end-to-end.

The pipelined oracle batch runs ~10ms at TPU speed while the host-side
snapshot path costs 3-4x that per refresh (BENCH_r05_late) — the host
became the bottleneck. This module keeps the packed ``[N, R]`` / ``[G, R]``
lane buffers (and the node-side policy columns) RESIDENT on device across
batches and applies each refresh's churned rows as one jit'd scatter-update
(donated where the backend supports it, per the PR-4 donation discipline),
instead of re-uploading a freshly host-packed snapshot every batch — the
inference-server pattern of keeping hot state device-resident and shipping
only deltas.

``DeviceStateHolder`` is the state owner, used in two places:

- the in-process scorer (core.oracle_scorer.OracleScorer) syncs it from
  every ``DeltaSnapshotPacker`` pack under the refresh lock and dispatches
  batches from the resident buffers;
- the sidecar (service.server) keeps one per connection as its mirror of
  the client's state, fed by DELTA_SCHEDULE_REQ wire frames
  (service/protocol.py) so ``RemoteScorer`` ships only churned rows +
  generation.

Residency invalidation (docs/pipelining.md "Device-resident state"): any
generation gap, schema change, node-list change, group-set change, bucket
change, or layout flip resyncs from a full keyframe — the audit-log
keyframe+delta discipline applied to live state. Bit-identity of
delta-applied state against a full repack is gated by ``make bench-delta``
and re-verified in production by the identity auditor.

Donation interaction: a batch dispatched FROM resident buffers must never
donate them (``donate_argnums`` would consume the state the next delta
scatters into), so the scorer and executor force ``donate=False`` on this
path — the donation moves into the scatter-update itself, whose input
buffer is superseded by its output by construction.
"""

from __future__ import annotations

import threading
import weakref
from functools import lru_cache
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DeviceStateHolder",
    "device_state_enabled",
    "device_derive_enabled",
    "device_state_report",
]


# ---------------------------------------------------------------------------
# env knob
# ---------------------------------------------------------------------------

_ENV = "BST_DEVICE_STATE"
_env_warned = [False]


def device_state_enabled() -> bool:
    """Parse-guarded BST_DEVICE_STATE read: default ON; ``0``/``off``/
    ``false`` disables, anything unrecognised warns once and keeps the
    default (a typo'd knob must never crash — the BST_SCAN_WAVE idiom)."""
    import os

    raw = os.environ.get(_ENV, "").strip().lower()
    if raw in ("", "1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no"):
        return False
    if not _env_warned[0]:
        _env_warned[0] = True
        import sys

        print(
            f"ignoring unrecognised {_ENV}={raw!r}; device-resident "
            "state stays enabled",
            file=sys.stderr,
        )
    return True


_DERIVE_ENV = "BST_DEVICE_DERIVE"
_derive_warned = [False]


def device_derive_enabled() -> bool:
    """Parse-guarded BST_DEVICE_DERIVE read: default ON; ``0``/``off``/
    ``false`` keeps the fit-mask/queue-order columns host-uploaded per
    batch instead of device-derived from the resident meta columns
    (docs/pipelining.md "Snapshot-lite & event ingest"). Unrecognised
    values warn once and keep the default."""
    import os

    raw = os.environ.get(_DERIVE_ENV, "").strip().lower()
    if raw in ("", "1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no"):
        return False
    if not _derive_warned[0]:
        _derive_warned[0] = True
        import sys

        print(
            f"ignoring unrecognised {_DERIVE_ENV}={raw!r}; device-derived "
            "columns stay enabled",
            file=sys.stderr,
        )
    return True


# ---------------------------------------------------------------------------
# the jit'd scatter-update
# ---------------------------------------------------------------------------

_ROWS_BUCKET_MIN = 8


def _rows_bucket(m: int) -> int:
    """Power-of-two bucket for the churned-row count so scatter jit
    signatures stay bounded (same rationale as ops.bucketing)."""
    return max(_ROWS_BUCKET_MIN, 1 << max(m - 1, 0).bit_length())


def _scatter_impl(buf, idx, rows):
    """THE row-application formula: resident buffer rows at ``idx`` become
    ``rows`` — it must mirror exactly the host-side rewrites of
    ops.snapshot.DeltaSnapshotPacker._delta_rows / _group_rows (the
    analysis/coupling.py "delta-row-scatter" group): same indices, same
    packed values, or delta-applied state diverges from a full repack."""
    return buf.at[idx].set(rows)


@lru_cache(maxsize=None)
def _scatter_fn(donated: bool, sharding):
    """Jitted scatter variant per (donation, output sharding). The donated
    form hands the resident buffer to XLA for in-place reuse — the caller
    rebinds the holder's reference to the returned array and never touches
    the donated handle again. ``sharding`` (a NamedSharding, hashable)
    pins the output layout so sharded resident buffers stay node-sharded
    across scatters instead of drifting to whatever GSPMD infers."""
    if sharding is not None:
        if donated:
            return jax.jit(
                _scatter_impl, donate_argnums=(0,), out_shardings=sharding
            )
        return jax.jit(_scatter_impl, out_shardings=sharding)
    if donated:
        return jax.jit(_scatter_impl, donate_argnums=(0,))
    return jax.jit(_scatter_impl)


def _pad_update(idx: np.ndarray, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Bucket-pad a scatter update by REPEATING the last (index, row) pair:
    duplicate indices all write the same value, so the result is
    deterministic under any scatter ordering and no padding sentinel can
    alias a real row (an out-of-range pad index would need masking; a
    repeated real one needs nothing)."""
    m = int(idx.shape[0])
    b = _rows_bucket(m)
    if b == m:
        return idx, rows
    pad = b - m
    idx = np.concatenate([idx, np.repeat(idx[-1:], pad)])
    rows = np.concatenate([rows, np.repeat(rows[-1:], pad, axis=0)])
    return idx, rows


def _derive_impl(inv_prio, ts_hi, ts_lo, name_rank, node_valid):
    """THE device-side column derivation (docs/pipelining.md
    "Snapshot-lite & event ingest"): reproduce, from the resident
    queue-order meta columns, exactly what the host precomputes —

    - ``order``: the queue permutation. Host sorts by ``(-priority,
      creation_ts, full_name)``; the meta columns encode that as int32
      lexsort keys (``inv_prio = ~priority``; ``(ts_hi, ts_lo)`` the
      order-preserving split of the float64 timestamp, ops.snapshot
      ._ts_sort_keys; ``name_rank`` the host's name order). jnp.lexsort
      takes the PRIMARY key last. Pad sentinels (INT32_MAX / row index)
      sort strictly after every real row, so the full-[Gb] static sort
      matches pad_oracle_batch's padded order column bit-for-bit.
    - ``fit``: the uniform-fit broadcast row IS the padded node-valid
      row (ops.snapshot._fit_mask fast path — the lite capture only
      stamps meta_cols when that fast path held).

    Byte-identity against the host columns is gated by
    tests/test_snapshot_lite.py and ``make bench-delta``."""
    order = jnp.lexsort((name_rank, ts_lo, ts_hi, inv_prio)).astype(jnp.int32)
    fit = node_valid[None, :]
    return fit, order


@lru_cache(maxsize=None)
def _derive_fn():
    return jax.jit(_derive_impl)


# ---------------------------------------------------------------------------
# holder registry (the /debug/perf device-state section)
# ---------------------------------------------------------------------------

_holders_lock = threading.Lock()
_holders: "weakref.WeakSet" = weakref.WeakSet()  # guarded-by: _holders_lock


def device_state_report() -> list:
    """Per-holder state summary for /debug/perf (utils.profiler)."""
    with _holders_lock:
        live = list(_holders)
    return [h.stats() for h in live]


class DeviceStateHolder:
    """Owner of one set of device-resident oracle buffers.

    Thread contract: every method that touches resident state takes
    ``_lock``. In the scorer the callers already serialize under the
    refresh lock (the dispatch-ahead thread packs/executes inside it), and
    on the sidecar the per-connection worker serializes requests while the
    DeviceExecutor thread runs the closures — the holder's own lock makes
    the object safe regardless of which of those threads touches it.
    """

    def __init__(self, mesh=None, label: str = "local"):
        self.mesh = mesh
        self.label = label
        self._lock = threading.Lock()
        # A forked holder (the what-if observatory's copy-on-write view,
        # core.explain) shares the live holder's resident device arrays.
        # Jax arrays are immutable and every scatter produces a NEW array
        # bound only on the fork, so sharing is safe — EXCEPT donation,
        # which consumes the input buffer in place: _donate() is pinned
        # False on forks (docs/pipelining.md "Fork semantics").
        self._forked = False
        self.generation = 0  # guarded-by: _lock
        # resident device arrays; None until the first keyframe
        self._alloc = None  # guarded-by: _lock
        self._requested = None  # guarded-by: _lock
        self._group_req = None  # guarded-by: _lock
        self._shardings: Optional[dict] = None  # guarded-by: _lock
        self._flat_nodes = False  # guarded-by: _lock
        # node-side policy columns (docs/policy.md), single-device only
        self._policy_hash = None  # guarded-by: _lock
        self._policy_dom = None  # guarded-by: _lock
        self.rows_scattered = 0  # guarded-by: _lock
        self.keyframes: Dict[str, int] = {}  # guarded-by: _lock
        self.deltas_applied = 0  # guarded-by: _lock
        # device-derived column state (single-device only, BST_DEVICE_DERIVE):
        # resident queue-order meta columns (inv_prio, ts_hi, ts_lo,
        # name_rank), the padded node-valid row they derive fit from, the
        # (fit, order) derivation cache, and the generation the meta
        # mirrors — None / -1 whenever the sync'd snapshot carries no
        # meta_cols (derive then leaves the host columns untouched)
        self._meta = None  # guarded-by: _lock
        self._meta_nv = None  # guarded-by: _lock
        self._derived = None  # guarded-by: _lock
        self._meta_gen = -1  # guarded-by: _lock
        self.derived_batches = 0  # guarded-by: _lock
        with _holders_lock:
            _holders.add(self)

    # -- internals ----------------------------------------------------------

    def _donate(self) -> bool:
        if self._forked:
            # a donated scatter would consume a buffer the live holder
            # (or a sibling fork) still reads — copy-on-write means the
            # fork always pays the copy
            return False
        from .oracle import donation_supported

        return donation_supported()

    def _place(self, name: str, host: np.ndarray):  # lock-held: _lock
        if self._shardings is not None and name in self._shardings:
            return jax.device_put(host, self._shardings[name])
        return jax.device_put(host)

    def _note_keyframe(self, reason: str) -> None:  # lock-held: _lock
        self.keyframes[reason] = self.keyframes.get(reason, 0) + 1
        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_device_keyframe_resyncs_total",
            "Device-resident state resyncs from a full keyframe, by reason",
        ).inc(reason=reason)
        DEFAULT_REGISTRY.gauge(
            "bst_device_state_generation",
            "Generation of the device-resident cluster state (per holder)",
        ).set(float(self.generation), holder=self.label)

    def _scatter(self, buf, idx: np.ndarray, rows: np.ndarray):  # lock-held: _lock
        idx = np.ascontiguousarray(idx, dtype=np.int32)
        rows = np.ascontiguousarray(rows)
        idx, rows = _pad_update(idx, rows)
        sharding = None
        if self._shardings is not None:
            try:
                sharding = buf.sharding
            except AttributeError:
                sharding = None
        return _scatter_fn(self._donate(), sharding)(buf, idx, rows)

    # -- state transitions --------------------------------------------------

    def current_generation(self) -> int:
        """Locked read for cross-thread reporting (the sidecar handler
        reads it while the executor thread applies deltas)."""
        with self._lock:
            return self.generation

    def reset(self) -> None:
        """Drop residency (the next sync/apply keyframes)."""
        with self._lock:
            self._alloc = self._requested = self._group_req = None
            self._policy_hash = self._policy_dom = None
            self._meta = self._meta_nv = self._derived = None
            self._meta_gen = -1
            self.generation = 0

    def keyframe(self, batch_args: tuple, generation: int, reason: str) -> tuple:
        """Install a full snapshot as the resident state and return the
        device-ready batch args. ``batch_args`` is the canonical padded
        7-tuple (ops.bucketing.pad_oracle_batch order); the big [N,R] /
        [G,R] buffers are committed to device (node-sharded on a mesh, per
        parallel.mesh.snapshot_specs), the O(G) tail stays host — it is
        refresh-fresh by definition and tiny."""
        (alloc, requested, group_req, remaining, fit_mask, group_valid,
         order) = batch_args
        with self._lock:
            if self.mesh is not None:
                from ..parallel.mesh import snapshot_shardings
                from .oracle import scan_sharded_active

                self._flat_nodes = scan_sharded_active()
                self._shardings = snapshot_shardings(
                    self.mesh,
                    broadcast_mask=np.asarray(fit_mask).shape[0] == 1,
                    flat_nodes=self._flat_nodes,
                )
            self._alloc = self._place("alloc", np.asarray(alloc))
            self._requested = self._place("requested", np.asarray(requested))
            self._group_req = self._place("group_req", np.asarray(group_req))
            self._policy_hash = self._policy_dom = None
            self.generation = int(generation)
            self._note_keyframe(reason)
            return (
                self._alloc, self._requested, self._group_req,
                remaining, fit_mask, group_valid, order,
            )

    def apply_rows(
        self,
        base_generation: int,
        generation: int,
        node_update: Optional[Tuple[np.ndarray, np.ndarray]],
        group_update: Optional[Tuple[np.ndarray, np.ndarray]],
        small_args: tuple,
    ) -> Optional[tuple]:
        """Scatter churned rows into the resident buffers and return the
        device-ready batch args, or None when the delta is NOT applicable —
        no resident state, a generation gap (a dropped/duplicated delta
        must resync, never silently score stale rows), or a padded-shape
        mismatch (bucket growth). ``small_args`` is the padded
        ``(remaining, fit_mask, group_valid, order)`` tail."""
        remaining, fit_mask, group_valid, order = small_args
        with self._lock:
            if self._requested is None or self._group_req is None:
                return None
            if int(base_generation) != self.generation:
                return None
            node_shape = tuple(self._requested.shape)
            group_shape = tuple(self._group_req.shape)
            scattered = 0
            if node_update is not None and len(node_update[0]):
                idx, rows = node_update
                # both bounds: a negative index would WRAP in .at[].set and
                # silently corrupt an unrelated resident row — refuse with
                # a resync instead, exactly like an out-of-range one
                if (
                    rows.shape[1:] != node_shape[1:]
                    or int(np.max(idx)) >= node_shape[0]
                    or int(np.min(idx)) < 0
                ):
                    return None
                self._requested = self._scatter(self._requested, idx, rows)
                scattered += int(len(idx))
            if group_update is not None and len(group_update[0]):
                idx, rows = group_update
                if (
                    rows.shape[1:] != group_shape[1:]
                    or int(np.max(idx)) >= group_shape[0]
                    or int(np.min(idx)) < 0
                ):
                    return None
                self._group_req = self._scatter(self._group_req, idx, rows)
                scattered += int(len(idx))
            self.generation = int(generation)
            self.deltas_applied += 1
            self.rows_scattered += scattered
            from ..utils.metrics import DEFAULT_REGISTRY

            if scattered:
                DEFAULT_REGISTRY.counter(
                    "bst_device_rows_scattered_total",
                    "Churned rows applied to device-resident state via "
                    "jit'd scatter-updates (vs a full re-upload)",
                ).inc(scattered)
            DEFAULT_REGISTRY.gauge(
                "bst_device_state_generation",
                "Generation of the device-resident cluster state (per "
                "holder)",
            ).set(float(self.generation), holder=self.label)
            return (
                self._alloc, self._requested, self._group_req,
                remaining, fit_mask, group_valid, order,
            )

    # -- the scorer-side entry point ---------------------------------------

    def sync(self, snap) -> tuple:
        """Bring the resident state up to ``snap`` (a DeltaSnapshotPacker
        product) and return device-ready batch args. Scatter-applies the
        pack's churned rows when the delta record is contiguous with the
        resident generation; otherwise resyncs from a keyframe with the
        reason counted (bst_device_keyframe_resyncs_total). When the
        snapshot carries queue-order meta columns (the snapshot-lite
        capture), the fit-mask and order columns are swapped for
        device-DERIVED ones (_maybe_derive) — the host columns stay
        authoritative for audit/explain and byte-equal by construction."""
        return self._maybe_derive(snap, self._sync_base(snap))

    def _sync_base(self, snap) -> tuple:
        batch_args = snap.device_args()
        delta = getattr(snap, "delta", None)
        if delta is None:
            return self.keyframe(batch_args, 0, "untracked")
        if delta.kind != "delta":
            return self.keyframe(batch_args, delta.generation, delta.reason)
        with self._lock:
            resident = self._requested is not None
            gen = self.generation
            shape_ok = resident and (
                tuple(self._requested.shape) == snap.requested.shape
                and tuple(self._group_req.shape) == snap.group_req.shape
            )
            layout_ok = True
            if resident and self.mesh is not None:
                from .oracle import scan_sharded_active

                layout_ok = self._flat_nodes == scan_sharded_active()
        if not resident:
            return self.keyframe(batch_args, delta.generation, "first")
        if delta.generation != gen + 1:
            return self.keyframe(batch_args, delta.generation, "generation")
        if not shape_ok:
            return self.keyframe(batch_args, delta.generation, "bucket")
        if not layout_ok:
            return self.keyframe(batch_args, delta.generation, "layout")
        out = self.apply_rows(
            gen,
            delta.generation,
            (delta.node_rows, np.asarray(snap.requested)[delta.node_rows]),
            (delta.group_rows, np.asarray(snap.group_req)[delta.group_rows]),
            (snap.remaining, snap.fit_mask, snap.group_valid, snap.order),
        )
        if out is None:  # raced invalidation: resync, never stale rows
            return self.keyframe(batch_args, delta.generation, "generation")
        return out

    def _maybe_derive(self, snap, out: tuple) -> tuple:
        """Swap ``out``'s fit-mask (index 4) and order (index 6) for
        device-derived arrays when the snapshot carries meta columns.

        Residency rule: the meta columns mirror generation ``_meta_gen``;
        a contiguous ``"delta"`` pack with matching padded shapes scatters
        only ``delta.meta_rows`` (empty → the cached derivation is reused
        outright — the zero-churn steady state runs no device work here);
        anything else re-uploads the meta wholesale. Snapshots without
        meta_cols (lite ineligible: policy on, selectors/taints, direct
        construction), mesh layouts, and BST_DEVICE_DERIVE=0 drop the
        meta state and return the host columns untouched — every bail is
        the exact pre-derive path."""
        meta = getattr(snap, "meta_cols", None)
        if meta is None or self.mesh is not None or not device_derive_enabled():
            with self._lock:
                self._meta = self._meta_nv = self._derived = None
                self._meta_gen = -1
            return out
        delta = getattr(snap, "delta", None)
        gen = 0 if delta is None else int(delta.generation)
        with self._lock:
            contiguous = (
                delta is not None
                and delta.kind == "delta"
                and self._meta is not None
                and self._meta_gen == gen - 1
                and tuple(self._meta[0].shape) == np.asarray(meta[0]).shape
                and tuple(self._meta_nv.shape)
                == np.asarray(snap.node_valid).shape
            )
            if not contiguous:
                self._meta = tuple(
                    jax.device_put(np.ascontiguousarray(c)) for c in meta
                )
                self._meta_nv = jax.device_put(
                    np.ascontiguousarray(snap.node_valid)
                )
                self._derived = None
            elif len(delta.meta_rows):
                idx = delta.meta_rows
                # node_valid never scatters: it is immutable while the
                # lite capture is valid (any node change keyframes)
                self._meta = tuple(
                    self._scatter(buf, idx, np.asarray(host)[idx])
                    for buf, host in zip(self._meta, meta)
                )
                self._derived = None
            if self._derived is None:
                self._derived = _derive_fn()(*self._meta, self._meta_nv)
            self._meta_gen = gen
            fit, order = self._derived
            self.derived_batches += 1
            from ..utils.metrics import DEFAULT_REGISTRY

            DEFAULT_REGISTRY.counter(
                "bst_refresh_derived_batches_total",
                "Batches whose fit-mask/queue-order columns were derived "
                "on device from resident meta columns instead of host "
                "precompute + upload",
            ).inc()
            return out[:4] + (fit, out[5], order)

    def sync_policy_cols(self, snap) -> Optional[tuple]:
        """Device-resident node policy columns (single-device only — the
        policy rung demotes the mesh layouts anyway, docs/policy.md): the
        [N,H] label-hash and [N] spread-domain columns ride the same
        generation stream; the O(G) group columns rebuild per pack and
        stay host. Returns the snapshot's policy_cols tuple with the node
        arrays swapped for resident device buffers, or the host tuple
        untouched when residency does not apply."""
        cols = snap.policy_cols
        if cols is None:
            with self._lock:
                self._policy_hash = self._policy_dom = None
            return None
        if self.mesh is not None:
            return cols
        prio, aff, anti, gang_dom, node_hash, node_dom = cols
        delta = getattr(snap, "delta", None)
        with self._lock:
            resident = (
                self._policy_hash is not None
                and tuple(self._policy_hash.shape) == node_hash.shape
                and tuple(self._policy_dom.shape) == node_dom.shape
            )
            if (
                not resident
                or delta is None
                or delta.kind != "delta"
            ):
                self._policy_hash = jax.device_put(np.asarray(node_hash))
                self._policy_dom = jax.device_put(np.asarray(node_dom))
            elif len(delta.policy_node_rows):
                idx = delta.policy_node_rows
                self._policy_hash = self._scatter(
                    self._policy_hash, idx, np.asarray(node_hash)[idx]
                )
                self._policy_dom = self._scatter(
                    self._policy_dom, idx, np.asarray(node_dom)[idx]
                )
                self.rows_scattered += int(len(idx))
                from ..utils.metrics import DEFAULT_REGISTRY

                DEFAULT_REGISTRY.counter(
                    "bst_device_rows_scattered_total",
                    "Churned rows applied to device-resident state via "
                    "jit'd scatter-updates (vs a full re-upload)",
                ).inc(int(len(idx)))
            return (
                prio, aff, anti, gang_dom, self._policy_hash,
                self._policy_dom,
            )

    # -- copy-on-write forks (core.explain what-if, docs/pipelining.md) -----

    def fork(self, label: Optional[str] = None) -> "DeviceStateHolder":
        """A copy-on-write fork of this holder: the fork STARTS from the
        same resident device arrays (zero-copy — jax arrays are
        immutable), and every subsequent scatter/keyframe binds NEW arrays
        on the fork only. The live holder's buffers, generation, and
        counters are never touched through a fork; a fork never donates
        (see _donate). This is the what-if engine's state container: apply
        a counterfactual to the fork, score it, throw the fork away."""
        out = DeviceStateHolder(
            mesh=self.mesh, label=label or f"{self.label}~fork"
        )
        out._forked = True
        with self._lock:
            out._alloc = self._alloc
            out._requested = self._requested
            out._group_req = self._group_req
            out._shardings = self._shardings
            out._flat_nodes = self._flat_nodes
            out._policy_hash = self._policy_hash
            out._policy_dom = self._policy_dom
            out.generation = self.generation
        return out

    def apply_batch(self, batch_args: tuple, base_args: tuple) -> tuple:
        """Counterfactual apply for a FORK: bring the resident buffers
        from ``base_args`` (the host arrays the residency currently
        mirrors) to ``batch_args`` by scattering only the rows that
        differ — the copy-on-write fast path — falling back to a full
        keyframe when the padded shapes changed (added nodes grow the
        bucket) or nothing is resident. Returns device-ready batch args
        like ``sync``; refuses on a non-fork (the live holder's state
        transitions are ``sync``/``apply_rows`` only, generation-checked)."""
        if not self._forked:
            raise RuntimeError(
                "apply_batch is fork-only; the live holder syncs from the "
                "packer's generation stream"
            )
        (alloc, requested, group_req, remaining, fit_mask, group_valid,
         order) = batch_args
        with self._lock:
            resident = (
                self._alloc is not None
                and tuple(self._alloc.shape) == np.asarray(alloc).shape
                and tuple(self._requested.shape)
                == np.asarray(requested).shape
                and tuple(self._group_req.shape)
                == np.asarray(group_req).shape
            )
        if not resident:
            return self.keyframe(batch_args, self.current_generation(),
                                 "fork-shape")
        with self._lock:
            scattered = 0
            for i, (new, base) in enumerate(
                ((alloc, base_args[0]), (requested, base_args[1]),
                 (group_req, base_args[2]))
            ):
                new = np.asarray(new)
                base = np.asarray(base)
                idx = np.nonzero((new != base).any(axis=1))[0].astype(
                    np.int32
                )
                if not len(idx):
                    continue
                buf = (self._alloc, self._requested, self._group_req)[i]
                buf = self._scatter(buf, idx, new[idx])
                if i == 0:
                    self._alloc = buf
                elif i == 1:
                    self._requested = buf
                else:
                    self._group_req = buf
                scattered += int(len(idx))
            self.deltas_applied += 1
            self.rows_scattered += scattered
            return (
                self._alloc, self._requested, self._group_req,
                remaining, fit_mask, group_valid, order,
            )

    # -- observability ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {
                "label": self.label,
                "forked": self._forked,
                "generation": self.generation,
                "resident": self._requested is not None,
                "deltas_applied": self.deltas_applied,
                "rows_scattered": self.rows_scattered,
                "keyframes": dict(self.keyframes),
                "derived_batches": self.derived_batches,
                "meta_resident": self._meta is not None,
            }
            if self._requested is not None:
                out["n_bucket"] = int(self._requested.shape[0])
                out["g_bucket"] = int(self._group_req.shape[0])
            if self.mesh is not None:
                out["mesh"] = True
                out["flat_nodes"] = self._flat_nodes
        return out
