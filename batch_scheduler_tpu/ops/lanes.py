"""Resource lanes: the fixed vector layout resources are packed into on device.

The reference models resources as a Go struct of int64 fields plus a scalar
map (``nodeinfo.Resource`` handled at reference pkg/scheduler/core/core.go:
656-668). The TPU-native equivalent is a dense ``int32[R]`` lane vector so a
whole cluster becomes one ``int32[N, R]`` array the oracle can stream through
the VPU.

Lane units are chosen so exact integer comparison semantics survive int32.
Every lane value is bounded by ``LANE_MAX = 2**30`` — the domain on which the
oracle's float32 reciprocal division (ops.oracle._exact_floordiv) is provably
exact and its int32 residuals provably overflow-free:

- ``cpu``                millicores   (max ~1.07M cores/node)
- ``memory``             KiB          (max 1 TiB/node at shift 0)
- ``ephemeral-storage``  KiB          (max 1 TiB/node at shift 0)
- ``pods``               count
- extended resources     raw integer counts

Values larger than the base unit allows (the reference carries int64
quantities with no cap) do NOT abort packing. Two mechanisms keep big
clusters schedulable:

1. **Per-lane auto-scaling**: ``LaneSchema.collect`` inspects every value in
   the snapshot and gives each lane a power-of-two ``shift`` so the largest
   observed value fits below ``LANE_MAX``. A 2 TiB-memory node simply packs
   in 2 KiB units for that snapshot. Capacities round **down** and requests
   round **up** in the shifted unit, so ``capacity >= request`` can never
   pass due to rounding.
2. **Safe saturation**: with a caller-pinned schema (churn re-scoring pins
   the schema so shapes stay jit-stable), a later value may still exceed the
   shifted domain. ``pack`` then clamps instead of raising: capacities clamp
   to ``LANE_MAX - 1`` (a conservative *underestimate* — the node still
   schedules, it just looks no larger than the domain bound) and requests
   clamp to ``LANE_MAX`` (strictly above any clamped capacity, so an
   unrepresentable request can never be falsely admitted).

Gang feasibility on device is computed in *member counts* (small integers),
never in raw byte sums, which is what keeps 5k-node clusters inside int32
(see ops.oracle).
"""

from __future__ import annotations

import warnings
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["LaneSchema", "CORE_LANES", "INT32_MAX", "LANE_MAX"]

CORE_LANES: Tuple[str, ...] = ("cpu", "memory", "ephemeral-storage", "pods")
# Lanes stored as KiB on device (canonical host unit is bytes).
_KIB_LANES = frozenset({"memory", "ephemeral-storage"})

INT32_MAX = np.int32(2**31 - 1)
# Hard per-value bound: the exact-float-division domain (see module doc).
LANE_MAX = np.int32(2**30)


def _to_device_unit(name: str, value: int, *, capacity: bool) -> int:
    if name in _KIB_LANES:
        if capacity:
            return value // 1024
        return -((-value) // 1024)  # ceil
    return value


def _apply_shift(value: int, shift: int, *, capacity: bool) -> int:
    if shift == 0:
        return value
    if capacity:
        return value >> shift  # floor (arithmetic shift: floor for negatives too)
    return -((-value) >> shift)  # ceil


class LaneSchema:
    """Maps resource names <-> lane indices (+ per-lane unit shifts) for one
    cluster snapshot."""

    def __init__(
        self,
        extended: Sequence[str] = (),
        shifts: Optional[Dict[str, int]] = None,
    ):
        self.names: Tuple[str, ...] = CORE_LANES + tuple(extended)
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        # Per-lane power-of-two unit coarsening (see module doc, mechanism 1).
        self.shifts: Tuple[int, ...] = tuple(
            int((shifts or {}).get(n, 0)) for n in self.names
        )
        self._warned_clamp = False

    @property
    def num_lanes(self) -> int:
        return len(self.names)

    @classmethod
    def collect(cls, resource_dicts: Iterable[Dict[str, int]]) -> "LaneSchema":
        """Build a schema covering every resource name seen in the snapshot,
        with per-lane shifts sized so every observed value packs exactly."""
        extended = set()
        max_seen: Dict[str, int] = {}
        for d in resource_dicts:
            for name, value in d.items():
                if name not in CORE_LANES:
                    extended.add(name)
                # Conservative bound: the ceil-rounded request conversion is
                # the larger of the two unit conversions by at most 1.
                dev = abs(_to_device_unit(name, int(value), capacity=False))
                if dev > max_seen.get(name, 0):
                    max_seen[name] = dev
        shifts = {}
        for name, peak in max_seen.items():
            shift = 0
            while (peak >> shift) >= int(LANE_MAX):
                shift += 1
            if shift:
                shifts[name] = shift
        return cls(sorted(extended), shifts=shifts)

    def pack(self, resources: Dict[str, int], *, capacity: bool = False) -> np.ndarray:
        """Pack one canonical resource dict into an int32[R] lane vector.

        Unknown resource names are an error: schemas are built with
        ``collect`` over the full snapshot, so a miss is a caller bug — and
        silently dropping a lane would break the reference's rule that a
        request for a resource the node lacks must fail feasibility
        (reference pkg/scheduler/core/core.go:686-696).

        Values outside the shifted domain saturate safely instead of
        raising (see module doc, mechanism 2).
        """
        vec = np.zeros(self.num_lanes, dtype=np.int64)
        for name, value in resources.items():
            i = self.index.get(name)
            if i is None:
                raise KeyError(f"resource {name!r} not in lane schema {self.names}")
            vec[i] = self._lane_value(i, name, value, capacity)
        cap_bound = self._domain_bound(capacity)
        if (vec > cap_bound).any() or (vec < -cap_bound).any():
            if not self._warned_clamp:
                self._warned_clamp = True
                warnings.warn(
                    f"resource vector exceeds the shifted lane domain and was "
                    f"clamped ({'capacity floor' if capacity else 'request'} "
                    f"bound {cap_bound}): {dict(zip(self.names, vec))}; "
                    "re-collect the schema to restore exact packing"
                )
            np.clip(vec, -cap_bound, cap_bound, out=vec)
        return vec.astype(np.int32)

    def _lane_value(self, i: int, name: str, value: int, capacity: bool) -> int:
        """The shifted device-unit value lane ``i`` would store for
        ``value`` — THE conversion, shared by pack() and covers() so the
        cache-validity predicate can never diverge from actual packing."""
        dev = _to_device_unit(name, int(value), capacity=capacity)
        return _apply_shift(dev, self.shifts[i], capacity=capacity)

    @staticmethod
    def _domain_bound(capacity: bool) -> int:
        return int(LANE_MAX) - 1 if capacity else int(LANE_MAX)

    def covers(self, resource_dicts: Sequence[Dict[str, int]]) -> bool:
        """True iff every name is in the schema AND every (request-side)
        value packs exactly (no clamp) — the validity check for reusing a
        cached schema across snapshots (core.oracle_scorer) instead of
        re-collecting."""
        bound = self._domain_bound(capacity=False)
        for d in resource_dicts:
            for name, value in d.items():
                i = self.index.get(name)
                if i is None:
                    return False
                v = self._lane_value(i, name, value, capacity=False)
                if v > bound or v < -bound:
                    return False
        return True

    def covers_names(self, resource_dicts: Sequence[Dict[str, int]]) -> bool:
        """Names-only coverage (no value-domain check): the cheap guard for
        dicts whose values are bounded by already-covered capacities (a
        node's requested sum never exceeds its allocatable)."""
        index = self.index
        return all(
            name in index for d in resource_dicts for name in d
        )

    def pack_many(
        self, dicts: Sequence[Dict[str, int]], *, capacity: bool = False
    ) -> np.ndarray:
        """Pack a sequence of resource dicts into int32[len, R].

        Identical dicts (the overwhelmingly common case: homogeneous node
        pools, uniform gang members) are packed once and memoized — this is
        the 5k-node snapshot hot loop on the host."""
        if not dicts:
            return np.zeros((0, self.num_lanes), dtype=np.int32)
        out = np.empty((len(dicts), self.num_lanes), dtype=np.int32)
        memo = {}
        for i, d in enumerate(dicts):
            key = tuple(sorted(d.items()))
            row = memo.get(key)
            if row is None:
                row = self.pack(d, capacity=capacity)
                memo[key] = row
            out[i] = row
        return out

    def unpack(self, vec: np.ndarray) -> Dict[str, int]:
        """Inverse of pack (device units x 2**shift, for debugging/logging)."""
        return {
            n: int(vec[i]) << self.shifts[i]
            for n, i in self.index.items()
            if vec[i]
        }
