"""Resource lanes: the fixed vector layout resources are packed into on device.

The reference models resources as a Go struct of int64 fields plus a scalar
map (``nodeinfo.Resource`` handled at reference pkg/scheduler/core/core.go:
656-668). The TPU-native equivalent is a dense ``int32[R]`` lane vector so a
whole cluster becomes one ``int32[N, R]`` array the oracle can stream through
the VPU.

Lane units are chosen so exact integer comparison semantics survive int32.
Every lane value is bounded by ``LANE_MAX = 2**30`` — the domain on which the
oracle's float32 reciprocal division (ops.oracle._exact_floordiv) is provably
exact and its int32 residuals provably overflow-free:

- ``cpu``                millicores   (max ~1.07M cores/node)
- ``memory``             KiB          (max 1 TiB/node)
- ``ephemeral-storage``  KiB          (max 1 TiB/node)
- ``pods``               count
- extended resources     raw integer counts

Requests round **up** and capacities round **down** during unit conversion,
so ``capacity >= request`` can never pass due to rounding. Gang feasibility
on device is computed in *member counts* (small integers), never in raw byte
sums, which is what keeps 5k-node clusters inside int32 (see ops.oracle).
"""

from __future__ import annotations

from typing import Dict, Iterable, Sequence, Tuple

import numpy as np

__all__ = ["LaneSchema", "CORE_LANES", "INT32_MAX", "LANE_MAX"]

CORE_LANES: Tuple[str, ...] = ("cpu", "memory", "ephemeral-storage", "pods")
# Lanes stored as KiB on device (canonical host unit is bytes).
_KIB_LANES = frozenset({"memory", "ephemeral-storage"})

INT32_MAX = np.int32(2**31 - 1)
# Hard per-value bound: the exact-float-division domain (see module doc).
LANE_MAX = np.int32(2**30)


def _to_device_unit(name: str, value: int, *, capacity: bool) -> int:
    if name in _KIB_LANES:
        if capacity:
            return value // 1024
        return -((-value) // 1024)  # ceil
    return value


class LaneSchema:
    """Maps resource names <-> lane indices for one cluster snapshot."""

    def __init__(self, extended: Sequence[str] = ()):
        self.names: Tuple[str, ...] = CORE_LANES + tuple(extended)
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    @property
    def num_lanes(self) -> int:
        return len(self.names)

    @classmethod
    def collect(cls, resource_dicts: Iterable[Dict[str, int]]) -> "LaneSchema":
        """Build a schema covering every resource name seen in the snapshot."""
        extended = set()
        for d in resource_dicts:
            for name in d:
                if name not in CORE_LANES:
                    extended.add(name)
        return cls(sorted(extended))

    def pack(self, resources: Dict[str, int], *, capacity: bool = False) -> np.ndarray:
        """Pack one canonical resource dict into an int32[R] lane vector.

        Unknown resource names are an error: schemas are built with
        ``collect`` over the full snapshot, so a miss is a caller bug — and
        silently dropping a lane would break the reference's rule that a
        request for a resource the node lacks must fail feasibility
        (reference pkg/scheduler/core/core.go:686-696).
        """
        vec = np.zeros(self.num_lanes, dtype=np.int64)
        for name, value in resources.items():
            i = self.index.get(name)
            if i is None:
                raise KeyError(f"resource {name!r} not in lane schema {self.names}")
            vec[i] = _to_device_unit(name, int(value), capacity=capacity)
        if (vec > LANE_MAX).any() or (vec < -LANE_MAX).any():
            raise OverflowError(
                f"resource vector exceeds LANE_MAX (2**30) lanes: "
                f"{dict(zip(self.names, vec))}; for >1TiB-per-lane nodes use "
                f"a coarser unit schema"
            )
        return vec.astype(np.int32)

    def pack_many(
        self, dicts: Sequence[Dict[str, int]], *, capacity: bool = False
    ) -> np.ndarray:
        """Pack a sequence of resource dicts into int32[len, R].

        Identical dicts (the overwhelmingly common case: homogeneous node
        pools, uniform gang members) are packed once and memoized — this is
        the 5k-node snapshot hot loop on the host."""
        if not dicts:
            return np.zeros((0, self.num_lanes), dtype=np.int32)
        out = np.empty((len(dicts), self.num_lanes), dtype=np.int32)
        memo = {}
        for i, d in enumerate(dicts):
            key = tuple(sorted(d.items()))
            row = memo.get(key)
            if row is None:
                row = self.pack(d, capacity=capacity)
                memo[key] = row
            out[i] = row
        return out

    def unpack(self, vec: np.ndarray) -> Dict[str, int]:
        """Inverse of pack (device units, for debugging/logging)."""
        return {n: int(vec[i]) for n, i in self.index.items() if vec[i]}
