"""Pallas TPU kernel for the gang-placement scan.

``ops.oracle.assign_gangs`` expresses the greedy whole-batch placement as a
``lax.scan`` over groups: ~G sequential XLA steps, each re-reading the live
leftover lanes from HBM and writing them back. This kernel fuses the whole
scan into ONE ``pallas_call``:

- the leftover lanes live in a VMEM scratch buffer for the entire sweep
  (transposed to ``[R, N]`` so the big node axis sits on the 128-wide lane
  dimension — ``[N, R]`` would use 5 of 128 lanes);
- the scan order and per-group remaining counts are scalar-prefetched to
  SMEM, and drive the *index maps*: step ``s`` DMAs exactly group
  ``order[s]``'s request row in and its take row out;
- per-step selection is the same sortless histogram threshold as the scan
  path (see assign_gangs' docstring) — the two implementations are asserted
  equivalent in tests/test_pallas.py.

Used for the single-device batch when the fit mask is the broadcast ``[1,N]``
fast path (no selectors/taints — the common case and the bench shape); the
``lax.scan`` path remains the general fallback and the GSPMD-sharded path
(a pallas_call is a black box to the partitioner).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .oracle import _BIG, _exact_floordiv, _select_best_fit

__all__ = ["assign_gangs_pallas"]


def _kernel(order_ref, remaining_ref, left0_ref, group_req_ref, mask_ref,
            takes_ref, placed_ref, left_after_ref, left_scratch):
    s = pl.program_id(0)
    num_steps = pl.num_programs(0)

    @pl.when(s == 0)
    def _():
        left_scratch[:] = left0_ref[:]

    g = order_ref[s]
    need = remaining_ref[g]

    left = left_scratch[:]  # [R, N]
    req = group_req_ref[0]  # [1, R] (this step's group row via index map)
    req_col = req.reshape(-1, 1)  # [R, 1]

    # ops.oracle._member_capacity in the kernel's transposed [R, N] layout
    # (lanes on axis 0 so the node axis rides the 128-wide lane dimension)
    safe_req = jnp.clip(req_col, 1, _BIG)
    lpos = jnp.clip(left, 0, _BIG)
    per_lane = jnp.where(req_col > 0, _exact_floordiv(lpos, safe_req), _BIG)
    cap = jnp.min(per_lane, axis=0, keepdims=True)  # [1, N]
    cap = cap * mask_ref[:].astype(jnp.int32)

    capc = jnp.minimum(cap, need)
    take, _feasible = _select_best_fit(cap, capc, need)
    feasible = _feasible.astype(jnp.int32)

    left_scratch[:] = left - take * req_col
    takes_ref[0] = take
    placed_ref[:] = jnp.full((1, 1, 1), feasible, jnp.int32)

    @pl.when(s == num_steps - 1)
    def _():
        left_after_ref[:] = left_scratch[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def assign_gangs_pallas(left0, group_req, remaining, fit_mask, order,
                        *, interpret: bool = False):
    """Drop-in for ``ops.oracle.assign_gangs`` (same signature/returns) with
    the restriction fit_mask.shape[0] == 1 (broadcast fast path).

    Returns (alloc[G,N] i32, placed[G] bool, left_after[N,R] i32).
    """
    if fit_mask.shape[0] != 1:
        raise ValueError(
            "assign_gangs_pallas requires the broadcast [1,N] fit mask; "
            "use ops.oracle.assign_gangs for per-group masks"
        )
    n, r = left0.shape
    g = group_req.shape[0]

    # Per-group arrays carry their blocked axis as a leading rank-3 dim so the
    # Mosaic (sublane, lane) tiling constraint falls on the trailing (1, r) /
    # (1, n) dims, which equal the array dims — a (1, r) block on a rank-2
    # [G, r] array is rejected by the TPU lowering (sublane block 1 vs G).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # order, remaining
        grid=(g,),
        in_specs=[
            pl.BlockSpec((r, n), lambda s, order, rem: (0, 0)),  # left0^T
            # step s sees exactly group order[s]'s request row
            pl.BlockSpec((1, 1, r), lambda s, order, rem: (order[s], 0, 0)),
            pl.BlockSpec((1, n), lambda s, order, rem: (0, 0)),  # mask
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, n), lambda s, order, rem: (order[s], 0, 0)
            ),  # takes
            pl.BlockSpec(
                (1, 1, 1), lambda s, order, rem: (order[s], 0, 0)
            ),  # placed
            pl.BlockSpec((r, n), lambda s, order, rem: (0, 0)),  # left_after^T
        ],
        scratch_shapes=[pltpu.VMEM((r, n), jnp.int32)],
    )
    takes, placed, left_after_t = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((g, 1, n), jnp.int32),
            jax.ShapeDtypeStruct((g, 1, 1), jnp.int32),
            jax.ShapeDtypeStruct((r, n), jnp.int32),
        ],
        interpret=interpret,
    )(
        order,
        remaining,
        left0.T,
        group_req.reshape(g, 1, r),
        fit_mask.astype(jnp.int32),
    )
    return (
        takes.reshape(g, n),
        placed[:, 0, 0].astype(bool),
        left_after_t.T,
    )
