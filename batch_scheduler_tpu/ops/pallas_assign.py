"""Pallas TPU kernel for the gang-placement scan.

``ops.oracle.assign_gangs`` expresses the greedy whole-batch placement as a
``lax.scan`` over groups: ~G sequential XLA steps, each re-reading the live
leftover lanes from HBM and writing them back. This kernel fuses the whole
scan into ONE ``pallas_call``:

- the leftover lanes live in a VMEM scratch buffer for the entire sweep
  (transposed to ``[R, N]`` so the big node axis sits on the 128-wide lane
  dimension — ``[N, R]`` would use 5 of 128 lanes);
- groups are pre-permuted into scan order (an XLA gather outside the
  kernel), so grid step ``s`` handles the contiguous chunk
  ``[s*CHUNK, (s+1)*CHUNK)`` with an UNROLLED inner loop — amortizing the
  per-step grid/DMA overhead that dominates at one group per step (the
  per-step compute is ~40k int32 elements; measured ~65us/step fixed cost)
  — and writes one contiguous ``(CHUNK, N)`` takes block;
- per-group remaining counts are scalar-prefetched to SMEM; outputs are
  un-permuted back to group order after the call (``argsort(order)``);
- per-step selection is the same sortless histogram threshold as the scan
  path (see assign_gangs' docstring) — the two implementations are asserted
  equivalent in tests/test_pallas.py and on hardware by
  benchmarks/tpu_smoke.py.

Used for the single-device batch. The fit mask may be the broadcast
``[1,N]`` row (no selectors/taints — the common case and the bench shape,
kept grid-resident) or the per-group ``[G,N]`` mask (selector/taint
workloads), whose rows are pre-permuted and DMA'd chunk-by-chunk like the
request rows. A group bucket that doesn't divide by CHUNK is padded with
inert rows. The ``lax.scan`` path remains the fallback and the
GSPMD-sharded path (a pallas_call is a black box to the partitioner).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .oracle import _BIG, _exact_floordiv, _select_best_fit

__all__ = ["assign_gangs_pallas", "CHUNK"]

# Groups per grid step. 8 matches the int32 sublane tile (the (CHUNK, N)
# output block is exactly one tile row-group) and amortizes the fixed
# per-step cost ~8x; group counts that don't divide are padded with inert
# rows (see assign_gangs_pallas).
CHUNK = 8


def _kernel(remaining_ref, left0_ref, group_req_ref, mask_ref,
            takes_ref, placed_ref, left_after_ref, left_scratch,
            *, per_group_mask: bool):
    s = pl.program_id(0)
    num_steps = pl.num_programs(0)

    @pl.when(s == 0)
    def _():
        left_scratch[:] = left0_ref[:]

    if not per_group_mask:
        mask = mask_ref[:].astype(jnp.int32)  # [1, N] broadcast row
    placed_rows = []
    # groups arrive pre-permuted into scan order: this step's chunk is rows
    # [s*CHUNK, (s+1)*CHUNK) of the sorted arrays; j is static (unrolled)
    for j in range(CHUNK):
        if per_group_mask:
            # this chunk's mask rows arrived pre-permuted like the request
            # rows; j is static, so this is a static row read
            mask = mask_ref[j].reshape(1, -1).astype(jnp.int32)
        need = remaining_ref[s * CHUNK + j]
        left = left_scratch[:]  # [R, N]
        req = group_req_ref[j]  # [R] (this chunk's block, static row)
        req_col = req.reshape(-1, 1)  # [R, 1]

        # ops.oracle._member_capacity in the kernel's transposed [R, N]
        # layout (lanes on axis 0 so the node axis rides the 128-wide lane
        # dimension)
        safe_req = jnp.clip(req_col, 1, _BIG)
        lpos = jnp.clip(left, 0, _BIG)
        per_lane = jnp.where(req_col > 0, _exact_floordiv(lpos, safe_req), _BIG)
        cap = jnp.min(per_lane, axis=0, keepdims=True)  # [1, N]
        cap = cap * mask

        capc = jnp.minimum(cap, need)
        take, _feasible = _select_best_fit(cap, capc, need)

        left_scratch[:] = left - take * req_col
        takes_ref[j] = take[0]
        placed_rows.append(_feasible.astype(jnp.int32))

    placed_ref[:] = jnp.stack(placed_rows).reshape(CHUNK, 1)

    @pl.when(s == num_steps - 1)
    def _():
        left_after_ref[:] = left_scratch[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def assign_gangs_pallas(left0, group_req, remaining, fit_mask, order,
                        *, interpret: bool = False):
    """Drop-in for ``ops.oracle.assign_gangs`` (same signature/returns).

    ``fit_mask`` may be the broadcast ``[1,N]`` row (kept resident in the
    grid, the common no-selector case) or the full ``[G,N]`` per-group
    mask (selector/taint workloads): mask rows are pre-permuted into scan
    order alongside the request rows and DMA'd per chunk.

    Returns (alloc[G,N] i32, placed[G] bool, left_after[N,R] i32).
    """
    n, r = left0.shape
    g = group_req.shape[0]
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )

    # pre-permute groups into scan order so each grid step reads/writes
    # contiguous chunk blocks; outputs are scattered back below. Pad the
    # group axis to a CHUNK multiple — pad rows carry remaining=0, take
    # nothing, and run AFTER every real group, so the leftover evolution is
    # untouched (their rows are sliced off below).
    group_req_sorted = jnp.take(group_req, order, axis=0)
    remaining_sorted = jnp.take(remaining, order, axis=0)
    mask_in = fit_mask.astype(jnp.int32)
    if per_group_mask:
        mask_in = jnp.take(mask_in, order, axis=0)
    g_pad = -(-g // CHUNK) * CHUNK
    if g_pad != g:
        group_req_sorted = jnp.pad(group_req_sorted, ((0, g_pad - g), (0, 0)))
        remaining_sorted = jnp.pad(remaining_sorted, ((0, g_pad - g),))
        if per_group_mask:
            mask_in = jnp.pad(mask_in, ((0, g_pad - g), (0, 0)))

    mask_spec = (
        pl.BlockSpec((CHUNK, n), lambda s, rem: (s, 0))  # chunk's mask rows
        if per_group_mask
        else pl.BlockSpec((1, n), lambda s, rem: (0, 0))  # broadcast row
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # remaining (sorted)
        grid=(g_pad // CHUNK,),
        in_specs=[
            pl.BlockSpec((r, n), lambda s, rem: (0, 0)),  # left0^T
            # step s sees its chunk of the sorted request rows
            pl.BlockSpec((CHUNK, r), lambda s, rem: (s, 0)),
            mask_spec,
        ],
        out_specs=[
            pl.BlockSpec((CHUNK, n), lambda s, rem: (s, 0)),  # takes
            pl.BlockSpec((CHUNK, 1), lambda s, rem: (s, 0)),  # placed
            pl.BlockSpec((r, n), lambda s, rem: (0, 0)),  # left_after^T
        ],
        scratch_shapes=[pltpu.VMEM((r, n), jnp.int32)],
    )
    takes_sorted, placed_sorted, left_after_t = pl.pallas_call(
        functools.partial(_kernel, per_group_mask=per_group_mask),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((g_pad, n), jnp.int32),
            jax.ShapeDtypeStruct((g_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((r, n), jnp.int32),
        ],
        interpret=interpret,
    )(
        remaining_sorted,
        left0.T,
        group_req_sorted,
        mask_in,
    )
    # scatter back to group order (the scan path's un-permute idiom)
    takes = jnp.zeros((g, n), jnp.int32).at[order].set(takes_sorted[:g])
    placed = (
        jnp.zeros((g,), jnp.int32).at[order].set(placed_sorted[:g, 0])
    ).astype(bool)
    return takes, placed, left_after_t.T
