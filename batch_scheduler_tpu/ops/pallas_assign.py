"""Pallas TPU kernel for the gang-placement scan.

``ops.oracle.assign_gangs`` expresses the greedy whole-batch placement as a
``lax.scan`` over groups: ~G sequential XLA steps, each re-reading the live
leftover lanes from HBM and writing them back. This kernel fuses the whole
scan into ONE ``pallas_call``:

- the leftover lanes live in a VMEM scratch buffer for the entire sweep
  (transposed to ``[R, N]`` so the big node axis sits on the 128-wide lane
  dimension — ``[N, R]`` would use 5 of 128 lanes);
- groups are pre-permuted into scan order (an XLA gather outside the
  kernel), so grid step ``s`` handles the contiguous chunk
  ``[s*CHUNK, (s+1)*CHUNK)`` with an UNROLLED inner loop — amortizing the
  per-step grid/DMA overhead that dominates at one group per step (the
  per-step compute is ~40k int32 elements; measured ~65us/step fixed cost)
  — and writes one contiguous ``(CHUNK, N)`` takes block;
- per-group remaining counts are scalar-prefetched to SMEM; outputs are
  un-permuted back to group order after the call (``argsort(order)``);
- per-step selection is the same sortless histogram threshold as the scan
  path (see assign_gangs' docstring) — the two implementations are asserted
  equivalent in tests/test_pallas.py and on hardware by
  benchmarks/tpu_smoke.py.

Used for the single-device batch. The fit mask may be the broadcast
``[1,N]`` row (no selectors/taints — the common case and the bench shape,
kept grid-resident) or the per-group ``[G,N]`` mask (selector/taint
workloads), whose rows are pre-permuted and DMA'd chunk-by-chunk like the
request rows. A group bucket that doesn't divide by CHUNK is padded with
inert rows. The ``lax.scan`` path remains the fallback and the
GSPMD-sharded path (a pallas_call is a black box to the partitioner).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .oracle import _BIG, _BINS, _cumsum, _exact_floordiv, _select_best_fit

__all__ = ["assign_gangs_pallas", "CHUNK"]

# Groups per grid step. 8 matches the int32 sublane tile (the (CHUNK, N)
# output block is exactly one tile row-group) and amortizes the fixed
# per-step cost ~8x; group counts that don't divide are padded with inert
# rows (see assign_gangs_pallas).
CHUNK = 8


def _cap_t(left, req_col):
    """ops.oracle._member_capacity in the kernel's transposed [R, N]
    layout (lanes on axis 0 so the node axis rides the 128-wide lane
    dimension). ``req_col`` is [R, 1]; returns cap [1, N]."""
    safe_req = jnp.clip(req_col, 1, _BIG)
    lpos = jnp.clip(left, 0, _BIG)
    per_lane = jnp.where(req_col > 0, _exact_floordiv(lpos, safe_req), _BIG)
    return jnp.min(per_lane, axis=0, keepdims=True)


def _kernel(remaining_ref, left0_ref, group_req_ref, mask_ref,
            takes_ref, placed_ref, left_after_ref, left_scratch,
            *, per_group_mask: bool):
    s = pl.program_id(0)
    num_steps = pl.num_programs(0)

    @pl.when(s == 0)
    def _():
        left_scratch[:] = left0_ref[:]

    if not per_group_mask:
        mask = mask_ref[:].astype(jnp.int32)  # [1, N] broadcast row
    placed_rows = []
    # groups arrive pre-permuted into scan order: this step's chunk is rows
    # [s*CHUNK, (s+1)*CHUNK) of the sorted arrays; j is static (unrolled)
    for j in range(CHUNK):
        if per_group_mask:
            # this chunk's mask rows arrived pre-permuted like the request
            # rows; j is static, so this is a static row read
            mask = mask_ref[j].reshape(1, -1).astype(jnp.int32)
        need = remaining_ref[s * CHUNK + j]
        left = left_scratch[:]  # [R, N]
        req = group_req_ref[j]  # [R] (this chunk's block, static row)
        req_col = req.reshape(-1, 1)  # [R, 1]

        cap = _cap_t(left, req_col) * mask  # [1, N]

        capc = jnp.minimum(cap, need)
        take, _feasible = _select_best_fit(cap, capc, need)

        left_scratch[:] = left - take * req_col
        takes_ref[j] = take[0]
        placed_rows.append(_feasible.astype(jnp.int32))

    placed_ref[:] = jnp.stack(placed_rows).reshape(CHUNK, 1)

    @pl.when(s == num_steps - 1)
    def _():
        left_after_ref[:] = left_scratch[:]


def _kernel_wave(remaining_ref, left0_ref, group_req_ref, mask_ref,
                 takes_ref, placed_ref, left_after_ref, left_scratch,
                 *, per_group_mask: bool, wave: int, mega_need_max: int):
    """Chunked-grid WAVEFRONT variant: grid step ``s`` places a whole wave
    of ``wave`` gangs. Mirrors ops.oracle.assign_gangs_wavefront inside
    the VMEM-resident sweep:

    - uniform path: a wave of identical demand/mask rows is placed with
      ONE aggregate tightest-first fill split at gang boundaries (the
      identical-req member-stream equivalence — see the oracle
      docstring), runtime-skipped otherwise;
    - speculative path: every gang computes its take against the
      wave-start leftover (the selections are independent, so Mosaic can
      overlap them, unlike the serial chain of ``_kernel``), then a
      conflict check recomputes each gang's capacity vector under the
      clamp-accumulated exclusive prefix of the wave's earlier takes —
      any mismatch means the fast takes are not provably the serial ones;
    - demotion: a conflicted wave replays serially under ``pl.when``
      (runtime-skipped when the wave commits), so results stay
      bit-identical to the serial kernel by construction.
    """
    s = pl.program_id(0)
    num_steps = pl.num_programs(0)

    @pl.when(s == 0)
    def _():
        left_scratch[:] = left0_ref[:]

    left = left_scratch[:]  # [R, N] wave-start leftover

    if not per_group_mask:
        mask_b = mask_ref[:].astype(jnp.int32)  # [1, N] broadcast row

    # cheap uniformity check for the aggregate path (blocks are VMEM
    # resident; these are elementwise compares + reductions)
    req_block = group_req_ref[:]  # [wave, R]
    uniform = jnp.all(req_block == req_block[0:1])
    if per_group_mask:
        mask_block = mask_ref[:].astype(jnp.int32)  # [wave, N]
        uniform = jnp.logical_and(
            uniform, jnp.all(mask_block == mask_block[0:1])
        )
    total_need = remaining_ref[s * wave]
    for j in range(1, wave):
        total_need = total_need + remaining_ref[s * wave + j]
    mega_ok = jnp.logical_and(uniform, total_need <= mega_need_max)

    @pl.when(mega_ok)
    def _():
        req0_col = group_req_ref[0].reshape(-1, 1)  # [R, 1]
        mask0 = (
            mask_ref[0].reshape(1, -1).astype(jnp.int32)
            if per_group_mask
            else mask_b
        )
        cap0 = _cap_t(left, req0_col) * mask0  # [1, N]
        key = jnp.minimum(cap0, _BINS - 1)
        capc_t = jnp.minimum(cap0, total_need)
        bins = jax.lax.broadcasted_iota(jnp.int32, (_BINS, 1), 0)
        bc = jnp.where(key == bins, capc_t, 0)  # [_BINS, N]
        bin_totals = jnp.sum(bc, axis=1, keepdims=True)
        cum_excl = _cumsum(bin_totals, axis=0) - bin_totals
        within = _cumsum(bc, axis=1) - bc
        pos_start = jnp.sum(
            jnp.where(key == bins, cum_excl + within, 0),
            axis=0,
            keepdims=True,
        )  # [1, N]
        pos_end = pos_start + capc_t
        a = jnp.int32(0)
        placed_rows = []
        total_take = jnp.zeros_like(cap0)
        for j in range(wave):
            need = remaining_ref[s * wave + j]
            taken = jnp.clip(a - pos_start, 0, capc_t)
            feas = jnp.sum(jnp.minimum(cap0 - taken, need)) >= need
            start = a
            end = a + need * feas.astype(jnp.int32)
            take = jnp.clip(
                jnp.minimum(end, pos_end) - jnp.maximum(start, pos_start),
                0,
                None,
            )
            takes_ref[j] = take[0]
            total_take = total_take + take
            placed_rows.append(feas.astype(jnp.int32))
            a = end
        left_scratch[:] = left - total_take * req0_col
        placed_ref[:] = jnp.stack(placed_rows).reshape(wave, 1)

    @pl.when(jnp.logical_not(mega_ok))
    def _():
        masks, req_cols, needs = [], [], []
        takes_fast, placed_fast = [], []
        acc = left  # clamp-accumulated prefix leftover (oracle docstring)
        conflict = jnp.bool_(False)
        for j in range(wave):
            mask = (
                mask_ref[j].reshape(1, -1).astype(jnp.int32)
                if per_group_mask
                else mask_b
            )
            need = remaining_ref[s * wave + j]
            req_col = group_req_ref[j].reshape(-1, 1)  # [R, 1]
            cap = _cap_t(left, req_col) * mask
            capc = jnp.minimum(cap, need)
            take, feas = _select_best_fit(cap, capc, need)
            # exclusive prefix: acc excludes this gang's own delta
            cap_pref = _cap_t(acc, req_col) * mask
            conflict = conflict | jnp.any(cap_pref != cap)
            acc = jnp.maximum(acc - take * req_col, -_BIG)
            masks.append(mask)
            req_cols.append(req_col)
            needs.append(need)
            takes_fast.append(take)
            placed_fast.append(feas.astype(jnp.int32))

        @pl.when(jnp.logical_not(conflict))
        def _():
            # no clamp fired on a conflict-free wave: acc IS the serial
            # leftover after the whole wave
            left_scratch[:] = acc
            for j in range(wave):
                takes_ref[j] = takes_fast[j][0]
            placed_ref[:] = jnp.stack(placed_fast).reshape(wave, 1)

        @pl.when(conflict)
        def _():
            live = left
            placed_rows = []
            for j in range(wave):
                cap = _cap_t(live, req_cols[j]) * masks[j]
                capc = jnp.minimum(cap, needs[j])
                take, feas = _select_best_fit(cap, capc, needs[j])
                live = live - take * req_cols[j]
                takes_ref[j] = take[0]
                placed_rows.append(feas.astype(jnp.int32))
            left_scratch[:] = live
            placed_ref[:] = jnp.stack(placed_rows).reshape(wave, 1)

    @pl.when(s == num_steps - 1)
    def _():
        left_after_ref[:] = left_scratch[:]


@functools.partial(jax.jit, static_argnames=("interpret", "wave"))
def assign_gangs_pallas(left0, group_req, remaining, fit_mask, order,
                        *, interpret: bool = False, wave: int = 0):
    """Drop-in for ``ops.oracle.assign_gangs`` (same signature/returns).

    ``fit_mask`` may be the broadcast ``[1,N]`` row (kept resident in the
    grid, the common no-selector case) or the full ``[G,N]`` per-group
    mask (selector/taint workloads): mask rows are pre-permuted into scan
    order alongside the request rows and DMA'd per chunk.

    ``wave`` >= 2 (static, bucketed by the caller —
    ops.bucketing.wave_width_bucket) selects the chunked-grid WAVEFRONT
    kernel variant: the chunk width becomes the wave width and each grid
    step places a whole conflict-checked wave (``_kernel_wave``),
    bit-identical to the serial kernel. 0/1 keeps the serial-in-chunk
    kernel. Both variants share the per-mask-mode fallback gating in
    ops.oracle (a failure on one mask mode's kernel never poisons the
    other).

    Returns (alloc[G,N] i32, placed[G] bool, left_after[N,R] i32).
    """
    n, r = left0.shape
    g = group_req.shape[0]
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )
    chunk = wave if wave >= 2 else CHUNK
    kernel = (
        functools.partial(
            _kernel_wave,
            per_group_mask=per_group_mask,
            wave=chunk,
            mega_need_max=(2**31 - 1) // max(n, 1),
        )
        if wave >= 2
        else functools.partial(_kernel, per_group_mask=per_group_mask)
    )

    # pre-permute groups into scan order so each grid step reads/writes
    # contiguous chunk blocks; outputs are scattered back below. Pad the
    # group axis to a CHUNK multiple — pad rows carry remaining=0, take
    # nothing, and run AFTER every real group, so the leftover evolution is
    # untouched (their rows are sliced off below).
    group_req_sorted = jnp.take(group_req, order, axis=0)
    remaining_sorted = jnp.take(remaining, order, axis=0)
    mask_in = fit_mask.astype(jnp.int32)
    if per_group_mask:
        mask_in = jnp.take(mask_in, order, axis=0)
    g_pad = -(-g // chunk) * chunk
    if g_pad != g:
        group_req_sorted = jnp.pad(group_req_sorted, ((0, g_pad - g), (0, 0)))
        remaining_sorted = jnp.pad(remaining_sorted, ((0, g_pad - g),))
        if per_group_mask:
            mask_in = jnp.pad(mask_in, ((0, g_pad - g), (0, 0)))

    mask_spec = (
        pl.BlockSpec((chunk, n), lambda s, rem: (s, 0))  # chunk's mask rows
        if per_group_mask
        else pl.BlockSpec((1, n), lambda s, rem: (0, 0))  # broadcast row
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # remaining (sorted)
        grid=(g_pad // chunk,),
        in_specs=[
            pl.BlockSpec((r, n), lambda s, rem: (0, 0)),  # left0^T
            # step s sees its chunk of the sorted request rows
            pl.BlockSpec((chunk, r), lambda s, rem: (s, 0)),
            mask_spec,
        ],
        out_specs=[
            pl.BlockSpec((chunk, n), lambda s, rem: (s, 0)),  # takes
            pl.BlockSpec((chunk, 1), lambda s, rem: (s, 0)),  # placed
            pl.BlockSpec((r, n), lambda s, rem: (0, 0)),  # left_after^T
        ],
        scratch_shapes=[pltpu.VMEM((r, n), jnp.int32)],
    )
    takes_sorted, placed_sorted, left_after_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((g_pad, n), jnp.int32),
            jax.ShapeDtypeStruct((g_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((r, n), jnp.int32),
        ],
        interpret=interpret,
    )(
        remaining_sorted,
        left0.T,
        group_req_sorted,
        mask_in,
    )
    # scatter back to group order (the scan path's un-permute idiom)
    takes = jnp.zeros((g, n), jnp.int32).at[order].set(takes_sorted[:g])
    placed = (
        jnp.zeros((g,), jnp.int32).at[order].set(placed_sorted[:g, 0])
    ).astype(bool)
    return takes, placed, left_after_t.T
