"""Sustained churn re-scoring: the 100ms backfill loop of BASELINE config 5.

The reference has no equivalent — its hot loops re-run serially per pod per
scheduling cycle (reference pkg/scheduler/core/core.go:595-632,701-739).
Here a churning cluster (gangs finishing and freeing capacity, new gangs
arriving) is re-scored as a whole every tick by re-running the fused oracle
batch. Three properties make the tick budget:

- **bucketed padding** (ops.bucketing): pod/node/group counts are padded to
  power-of-two buckets, so a tick only recompiles when the cluster crosses a
  bucket boundary — steady-state churn hits the jit cache every time;
- **pinned lane schema**: the resource-lane dimension R is fixed up front
  (superset of every resource the loop will see), so a new extended resource
  appearing mid-loop can't change array shapes;
- **O(G) host fetch** (ops.oracle.execute_batch_host): each tick pulls only
  the per-group vectors + compact top-K assignment; (G,N) tensors stay on
  device;
- **link-latency hiding** (tick_dispatch/tick_collect): a software
  pipeline overlaps the host<->device round-trip with one or more tick
  intervals (staleness contract on tick_dispatch; pipelines deeper than
  one tick commit through admit_verified's host-side re-check);
- **device-resident state**: the padded alloc and occupancy arrays stay on
  device across ticks; admit/release ship fixed-width scatter deltas, with
  the numpy mirror as ground truth and automatic resync on failure.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..api.types import Node
from .lanes import LaneSchema
from .oracle import batch_top_k, collect_batch, dispatch_batch
from .snapshot import ClusterSnapshot, GroupDemand

__all__ = [
    "ChurnRescorer",
    "TickResult",
    "PendingTick",
    "TickPipeline",
    "probe_link_depth",
]


@jax.jit
def _scatter_add_rows(req, rows, updates):
    """Apply admit/release deltas to the device-resident occupancy array:
    steady ticks move only the delta rows (KBs) over the host link, not the
    whole [N,R] array. NOT donated — in a pipelined loop the previous
    tick's batch can still hold the old buffer as a live input; the update
    allocates on device, where the [N,R] copy is effectively free."""
    return req.at[rows].add(updates)


# Fixed delta width: every scatter shares ONE jit signature per [N,R]
# shape (warmed at first upload), so no steady tick can hit a mid-loop
# compile. Bigger bursts fall back to a full mirror re-upload (counted in
# summary() as reupload_fallbacks — the path is ~100x costlier and an
# undersized bucket silently turns every burst tick into it, VERDICT r3
# item 5 postmortem). Sized for the worst admission tick at the deepest
# link-RTT pipeline (depth 4 x 32-gang window, whole-batch atomic admit):
# 128 admits x up to 10 assignment rows each (one per member at maximal
# fragmentation) plus a releases margin. The padded scatter payload at
# this width is ~2048 x R x 4B ≈ 64KB per drain — noise on any link.
_DELTA_BUCKET = 2048


@dataclass
class TickResult:
    """One re-score round: the oracle's O(G) answers + timing breakdown."""

    host: dict  # gang_feasible / placed / assignment_* / best / progress
    snapshot: ClusterSnapshot
    pack_seconds: float
    device_seconds: float
    bucket_shape: tuple  # (G_bucket, N_bucket, R, fit_mask_rows)

    @property
    def total_seconds(self) -> float:
        return self.pack_seconds + self.device_seconds

    def placed_groups(self) -> List[str]:
        placed = np.asarray(self.host["placed"])
        return [
            name
            for i, name in enumerate(self.snapshot.group_names)
            if placed[i]
        ]


@dataclass
class PendingTick:
    """A dispatched-but-uncollected tick (ChurnRescorer.tick_dispatch):
    holds the snapshot the batch was computed against and the in-flight
    device handle."""

    pending: object  # ops.oracle.PendingBatch
    snapshot: ClusterSnapshot
    pack_seconds: float
    dispatch_seconds: float
    bucket_shape: tuple


class ChurnRescorer:
    """Re-scores a churning cluster every tick against a pinned lane schema.

    Usage::

        r = ChurnRescorer(nodes, extra_resources=["nvidia.com/gpu"])
        while churning:
            tick = r.tick(node_requested, pending_groups)
            ... admit tick.placed_groups(), mutate cluster state ...

    ``recompiles`` counts ticks whose padded bucket shape was never seen
    before — the only ticks that can trigger an XLA compile. In steady-state
    churn it stays at its initial value.
    """

    def __init__(
        self,
        nodes: Sequence[Node],
        extra_resources: Sequence[str] = (),
        node_requested: Optional[Dict[str, Dict[str, int]]] = None,
        schema: Optional[LaneSchema] = None,
        sticky_buckets: bool = False,
    ):
        self.nodes = list(nodes)
        self.schema = schema or LaneSchema.collect(
            [n.status.allocatable for n in nodes]
            + list((node_requested or {}).values())
            + [{name: 0} for name in extra_resources]
        )
        # dense occupancy state in device units: committed gang usage lives
        # here, maintained by admit()/release() without any dict packing
        self.requested_lanes = np.zeros(
            (len(self.nodes), self.schema.num_lanes), dtype=np.int32
        )  # guarded-by: _state_lock
        self._running: Dict[str, tuple] = {}  # gang -> (node_idx, counts, lane_vec); guarded-by: _state_lock
        # the alloc side of the snapshot never changes tick-to-tick
        self._alloc_lanes = self.schema.pack_many(
            [n.status.allocatable for n in self.nodes], capacity=True
        )
        self.latencies: List[float] = []
        self.pack_times: List[float] = []
        self.device_times: List[float] = []
        # device_times split: dispatch-side block vs collect-side block —
        # the signal that says whether a pipelined loop actually overlapped
        # the link round-trip or just moved it
        self.dispatch_times: List[float] = []
        self.collect_times: List[float] = []
        self._shapes_seen: set = set()
        self.recompiles = 0
        self.reupload_fallbacks = 0
        # Sticky buckets pin the padded shape to the largest seen — ZERO
        # recompiles ever, at the cost of scanning the max gang count every
        # tick. Off by default: the jit cache already holds every bucket
        # shape it has visited, so oscillating across a boundary only
        # compiles once per shape, and small ticks stay small.
        self._sticky = sticky_buckets
        self._sticky_buckets = (0, 0)
        self._alloc_dev = None  # device-resident padded alloc (see tick)
        # Device-resident occupancy: the padded requested array stays on
        # device; admit/release append deltas here and the dispatch fast
        # path scatter-adds them instead of re-uploading [N,R] every tick.
        # Invariant: _req_dev == padded(mirror at last upload) + every delta
        # appended since; any failure drops _req_dev and the next tick
        # re-uploads the numpy mirror (the ground truth) and clears deltas.
        self._req_dev = None  # guarded-by: _state_lock
        self._req_deltas: List[tuple] = []  # (row_idx[int32], update[?,R]); guarded-by: _state_lock
        # True while a resync upload is in flight outside the lock: admits
        # in that window must still queue their deltas (the upload snapshot
        # predates them), even though _req_dev may read as None
        self._req_uploading = False  # guarded-by: _state_lock
        # Serializes admit/release (occupancy charge + delta enqueue)
        # against tick_dispatch's snapshot pack + delta drain. A pipeline
        # deeper than one tick runs dispatches on a helper thread that can
        # overlap the loop's admits — without this lock a delta appended
        # between _requested_device's concatenate and clear() is silently
        # dropped and the device occupancy understates committed load
        # forever after. The lock covers only host-side packing (~ms), not
        # the dispatch RPC, so admits never stall on a slow link.
        self._state_lock = threading.Lock()

    def tick(
        self,
        node_requested: Optional[Dict[str, Dict[str, int]]],
        groups: Sequence[GroupDemand],
        nodes: Optional[Sequence[Node]] = None,
    ) -> TickResult:
        """Pack the current cluster state and run one fused oracle batch.

        ``node_requested=None`` uses the internal dense occupancy state
        (admit()/release() bookkeeping — the fast path). Passing a dict
        packs it instead (one-off scoring against external state).

        ``nodes`` overrides the node set for this tick (node churn); by
        default the constructor's node list is used (pod/group churn only).
        """
        return self.tick_collect(
            self.tick_dispatch(node_requested, groups, nodes)
        )

    def tick_dispatch(
        self,
        node_requested: Optional[Dict[str, Dict[str, int]]],
        groups: Sequence[GroupDemand],
        nodes: Optional[Sequence[Node]] = None,
    ) -> "PendingTick":
        """The dispatch half of ``tick``: pack the snapshot and launch the
        fused batch WITHOUT waiting for its result (ops.oracle's
        dispatch_batch/collect_batch split). A one-tick-deep pipeline —
        dispatch the batch for the current state, do a tick's worth of host
        work (or sleep out the interval), collect at the next boundary —
        hides the host<->device link round-trip, which on a tunneled TPU is
        ~6x the device compute itself.

        Staleness contract: the collected result reflects occupancy AT
        DISPATCH. Admitting it later is safe exactly when capacity has not
        shrunk in between — releases and arrivals only add slack, so the
        churn loop qualifies; node removal or external placements would
        need a host-side re-verify before admit (``admit_verified``).

        Thread-safety: a pipelined loop may run this on a helper thread
        while admit/release run on the loop thread; the internal state
        lock makes the snapshot pack + delta drain atomic against them."""
        if nodes is not None and node_requested is None:
            # the dense occupancy state is indexed by the constructor's node
            # list; scoring a different node set against it would silently
            # drop committed usage (double-booking)
            raise ValueError(
                "tick(nodes=...) requires an explicit node_requested dict; "
                "the internal dense occupancy state is only valid for the "
                "constructor's node list"
            )
        use_nodes = self.nodes if nodes is None else list(nodes)
        # state lock: the pack reads the occupancy mirror, which must be
        # atomic vs a concurrent admit/release on another thread (depth-k
        # pipelines). Device RPCs stay OUTSIDE the lock (the alloc upload
        # below reads only constructor-immutable state; _requested_device
        # takes the lock internally for exactly the queue-drain part). t0
        # starts inside the lock so pack_seconds stays a pure pack
        # measurement — lock waits land in the loop's wall series, not here.
        with self._state_lock:
            t0 = time.perf_counter()
            dense = self.requested_lanes if node_requested is None else None
            snap = ClusterSnapshot(
                use_nodes,
                node_requested or {},
                groups,
                schema=self.schema,
                requested_lanes=dense,
                alloc_lanes=self._alloc_lanes if nodes is None else None,
                min_buckets=self._sticky_buckets,
            )
            t_pack = time.perf_counter() - t0

        args = snap.device_args()
        if nodes is None:
            # the alloc side never changes tick-to-tick: keep the padded
            # array resident on device so steady ticks skip its
            # host->device transfer (the largest per-tick input)
            if (
                self._alloc_dev is None
                or self._alloc_dev.shape != args[0].shape
            ):
                self._alloc_dev = jax.device_put(args[0])
            args = (self._alloc_dev,) + args[1:]
        if nodes is None and node_requested is None:
            # occupancy stays on device too: steady ticks ship only the
            # admit/release deltas accrued since the last dispatch
            args = (args[0], self._requested_device(args[1])) + args[2:]

        t1 = time.perf_counter()
        pending = dispatch_batch(args, snap.progress_args())
        t_dispatch = time.perf_counter() - t1

        bucket_shape = (
            snap.group_req.shape[0],
            snap.alloc.shape[0],
            snap.alloc.shape[1],
            # mask row rank: 1 (uniform broadcast) vs G (selectors/taints
            # present) is a distinct jit signature — count it as a recompile
            snap.fit_mask.shape[0],
            # top-K readback tier (static in the batch's jit signature): a
            # gang wider than any seen tier compiles — count it too
            batch_top_k(
                snap.alloc.shape[0], int(snap.remaining.max(initial=0))
            ),
        )
        if bucket_shape not in self._shapes_seen:
            self._shapes_seen.add(bucket_shape)
            self.recompiles += 1
        if self._sticky:
            self._sticky_buckets = (
                max(self._sticky_buckets[0], bucket_shape[0]),
                max(self._sticky_buckets[1], bucket_shape[1]),
            )
        return PendingTick(
            pending=pending,
            snapshot=snap,
            pack_seconds=t_pack,
            dispatch_seconds=t_dispatch,
            bucket_shape=bucket_shape,
        )

    def _requested_device(self, padded_requested: np.ndarray):
        """Return the device-resident padded occupancy array, synced to the
        numpy mirror: first use (or any post-failure resync) uploads the
        mirror whole and drops queued deltas; steady ticks scatter-add only
        the queued admit/release rows (bucketed so the jit signature is
        stable). On any failure the device copy is dropped — the next tick
        re-uploads ground truth.

        Locking: only the queue drain (and the resync's mirror re-read)
        holds the state lock; the device RPCs run outside it so a
        concurrent admit/release never stalls behind an h2d transfer on a
        slow link. ``_req_dev`` itself is helper-thread-owned. The resync
        path re-pads from the LIVE mirror under the lock rather than using
        the caller's (possibly pre-admit) pack: admit updates the mirror
        and queues its delta atomically, so dropping the queue is only
        consistent with an upload of the mirror as of the same instant."""
        try:
            with self._state_lock:
                deltas = self._req_deltas
                rows_total = sum(len(d[0]) for d in deltas)
                cur_dev = self._req_dev
                resync = (
                    cur_dev is None
                    or cur_dev.shape != padded_requested.shape
                    or rows_total > _DELTA_BUCKET  # burst: re-upload wins
                )
                drained = None
                if resync:
                    if cur_dev is not None:
                        # an established mirror falling back is the perf
                        # cliff the bucket sizing exists to avoid — count it
                        self.reupload_fallbacks += 1
                    deltas.clear()
                    upload = np.zeros_like(padded_requested)
                    upload[: len(self.requested_lanes)] = self.requested_lanes
                    self._req_uploading = True
                elif deltas:
                    rows = np.concatenate([d[0] for d in deltas])
                    ups = np.concatenate([d[1] for d in deltas])
                    deltas.clear()
                    pad = _DELTA_BUCKET - len(rows)
                    rows = np.concatenate(
                        [rows, np.zeros(pad, dtype=np.int32)]
                    )
                    ups = np.concatenate(
                        [ups, np.zeros((pad, ups.shape[1]), dtype=np.int32)]
                    )
                    drained = (rows, ups)
            if resync:
                dev = jax.device_put(upload)
                # compile the (sole) scatter signature now, outside any
                # tick budget — a zero delta is a numeric no-op
                dev = _scatter_add_rows(
                    dev,
                    np.zeros(_DELTA_BUCKET, dtype=np.int32),
                    np.zeros(
                        (_DELTA_BUCKET, padded_requested.shape[1]),
                        dtype=np.int32,
                    ),
                )
                with self._state_lock:
                    self._req_dev = dev
                    self._req_uploading = False
            elif drained is not None:
                # every None<->non-None transition of _req_dev happens
                # under _state_lock (admit/release read it there); the
                # scatter is already dispatched off ``cur_dev``, so the
                # critical section is a single store (ADVICE r5)
                dev = _scatter_add_rows(cur_dev, *drained)
                with self._state_lock:
                    self._req_dev = dev
            else:
                # no resync, no deltas: the locked read above is the value
                dev = cur_dev
            return dev
        except Exception:
            with self._state_lock:
                self._req_dev = None
                self._req_uploading = False
                self._req_deltas.clear()
            raise

    def tick_collect(self, pend: "PendingTick") -> TickResult:
        """The sync half of ``tick_dispatch``: wait for (or, pipelined, just
        pick up) the batch result and record the tick's host-blocking cost.
        ``device_seconds`` is dispatch + collect blocking time — in a
        pipelined loop the transfer rode the interval, so it measures only
        what the host actually stalled."""
        t0 = time.perf_counter()
        host, _device = collect_batch(pend.pending)
        t_collect = time.perf_counter() - t0
        result = TickResult(
            host=host,
            snapshot=pend.snapshot,
            pack_seconds=pend.pack_seconds,
            device_seconds=pend.dispatch_seconds + t_collect,
            bucket_shape=pend.bucket_shape,
        )
        self.latencies.append(result.total_seconds)
        self.pack_times.append(result.pack_seconds)
        self.device_times.append(result.device_seconds)
        self.dispatch_times.append(pend.dispatch_seconds)
        self.collect_times.append(t_collect)
        return result

    def warm(
        self,
        group_buckets: Sequence[int],
        with_selectors: bool = False,
        max_remaining: int = 16,
    ) -> None:
        """Precompile the oracle for the given gang-count buckets so no tick
        inside the churn loop ever pays a first-compile (~seconds on TPU).

        A uniform cluster compiles the broadcast ``[1,N]``-mask jit signature
        (ops.snapshot._fit_mask fast path); groups with node selectors (or
        tainted nodes) produce the full ``[G,N]`` signature — a distinct
        compile. Pass ``with_selectors=True`` if churn traffic can carry
        selectors, so both signatures are warm. ``max_remaining`` is the
        widest gang (members still needed) the loop will see: the batch's
        top-K readback tier is static in its jit signature
        (ops.oracle.batch_top_k), so a wider-than-warmed gang would compile
        mid-loop. Timing stats are reset afterwards."""
        for gb in group_buckets:
            variants = [{}]
            if with_selectors:
                variants.append({"node_selector": {"__warm__": "never"}})
            for extra in variants:
                dummies = [
                    GroupDemand(
                        full_name=f"__warm__/{i}",
                        min_member=max(1, max_remaining) if i == 0 else 1,
                        member_request={"cpu": 1},
                        has_pod=True,
                        **extra,
                    )
                    for i in range(gb)
                ]
                self.tick(None, dummies)
        self.clear_stats()

    # -- occupancy bookkeeping (dense fast path) ---------------------------

    def _member_lane_vec(self, group: GroupDemand) -> np.ndarray:
        req = dict(group.member_request)
        req["pods"] = max(req.get("pods", 0), 1)  # implicit pod slot
        return self.schema.pack(req).astype(np.int64)

    def admit(self, tick: TickResult, full_name: str) -> None:
        """Commit a placed gang: charge its assignment (from the tick's
        compact top-K) against the dense occupancy state.

        Valid for gangs assigned to <= ASSIGNMENT_TOP_K distinct nodes (the
        oracle's compact readback; 128 by default — far above any
        minMember in the BASELINE ladder).
        """
        gi = tick.snapshot.group_index(full_name)
        if gi is None:
            raise KeyError(full_name)
        group = tick.snapshot.groups[gi]
        nodes_idx = np.asarray(tick.host["assignment_nodes"])[gi]
        counts = np.asarray(tick.host["assignment_counts"])[gi]
        mask = counts > 0
        idx, cnt = nodes_idx[mask], counts[mask].astype(np.int64)
        vec = self._member_lane_vec(group)
        update = (cnt[:, None] * vec[None, :]).astype(np.int32)
        with self._state_lock:  # vs a concurrent dispatch's pack/drain
            # membership check inside the critical section: pre-analyzer it
            # ran lock-free before the charge, so two concurrent admits of
            # the same gang could both pass and double-charge
            if full_name in self._running:
                raise ValueError(f"{full_name} already admitted")
            self.requested_lanes[idx] += update
            # Staleness guard (ADVICE r3): charging a one-tick-stale
            # assignment is safe only under this class's contract that
            # capacity never SHRINKS between dispatch and admit (releases/
            # arrivals only add slack). A caller that interleaved node
            # removal or external placements would oversubscribe silently —
            # fail loudly instead.
            over = self.requested_lanes[idx] > self._alloc_lanes[idx]
            if over.any():
                self.requested_lanes[idx] -= update
                raise RuntimeError(
                    f"admit({full_name}): assignment oversubscribes "
                    f"{int(over.any(axis=1).sum())} node(s) — the tick's "
                    "snapshot is staler than the capacity-only-grows "
                    "contract allows (node removed or externally placed "
                    "load?)"
                )
            if self._req_dev is not None or self._req_uploading:
                # only queue while a device copy exists (or an upload that
                # predates this charge is in flight) to drain into — the
                # upload path rebuilds from the mirror and discards the queue
                self._req_deltas.append((idx.astype(np.int32), update))
            self._running[full_name] = (idx, update)

    def admit_verified(self, tick: TickResult, full_name: str) -> bool:
        """``admit`` for pipelines deeper than one tick: re-verify the
        stale assignment against CURRENT occupancy and skip instead of
        raising when it no longer fits.

        A depth-k software pipeline (k dispatches in flight) breaks the
        capacity-only-grows contract ``admit`` is allowed to assume: ticks
        N-1..N-k+1 admit their placements AFTER tick N was dispatched, so
        tick N's plan may seat gangs on capacity those admissions consumed,
        and the same still-pending gang may ride several in-flight batches
        at once. This host-side re-verify restores safety for any depth:

        - already admitted (an earlier in-flight batch won): skip, False;
        - charge would oversubscribe any node (plan staler than current
          occupancy): roll back cleanly (``admit``'s guard) and skip,
          False — the gang stays pending and re-rides the next dispatch;
        - otherwise charge and commit exactly like ``admit``: True.

        The caller must not re-offer a name it has released (a finished
        gang is indistinguishable from a fresh incarnation here) — track
        completion on the caller side, as benchmarks/ladder.py config 5
        does with its placed-ever set.
        """
        with self._state_lock:
            if full_name in self._running:
                return False
        try:
            # narrow TOCTOU window is safe: admit re-checks membership
            # inside its own critical section and raises ValueError
            self.admit(tick, full_name)
        except (RuntimeError, ValueError):
            return False
        return True

    def release(self, full_name: str) -> None:
        """A running gang finished: free its occupancy (the exact negation
        of the admit-time update, by construction)."""
        with self._state_lock:  # vs a concurrent dispatch's pack/drain
            idx, update = self._running.pop(full_name)
            self.requested_lanes[idx] -= update
            if self._req_dev is not None or self._req_uploading:
                self._req_deltas.append((idx.astype(np.int32), -update))

    @property
    def running(self) -> List[str]:
        with self._state_lock:
            return list(self._running)

    # -- stats -------------------------------------------------------------

    def _stat_series(self) -> tuple:
        return (
            self.latencies,
            self.pack_times,
            self.device_times,
            self.dispatch_times,
            self.collect_times,
        )

    def clear_stats(self) -> None:
        """Drop recorded tick timings (e.g. after a warmup or an admission
        burst that should not count toward the steady-state summary)."""
        for series in self._stat_series():
            series.clear()

    def drop_last_stats(self) -> None:
        """Un-record the most recent collected tick (e.g. an unmeasured
        pipeline-drain collect after a benchmark loop)."""
        for series in self._stat_series():
            if series:
                series.pop()

    def percentile(self, q: float) -> float:
        if not self.latencies:
            return 0.0
        return float(np.percentile(np.array(self.latencies), q))

    def summary(self) -> dict:
        return {
            "ticks": len(self.latencies),
            "p50_s": round(self.percentile(50), 5),
            "p95_s": round(self.percentile(95), 5),
            "max_s": round(max(self.latencies), 5) if self.latencies else 0.0,
            "p50_pack_s": round(float(np.median(self.pack_times)), 5) if self.pack_times else 0.0,
            "p50_device_s": round(float(np.median(self.device_times)), 5) if self.device_times else 0.0,
            "p50_dispatch_s": round(float(np.median(self.dispatch_times)), 5) if self.dispatch_times else 0.0,
            "p50_collect_s": round(float(np.median(self.collect_times)), 5) if self.collect_times else 0.0,
            "bucket_shapes": sorted(self._shapes_seen),
            "recompiles": self.recompiles,
            "reupload_fallbacks": self.reupload_fallbacks,
        }


def probe_link_depth(
    rescorer: "ChurnRescorer",
    interval: float,
    probe_width: int = 8,
    samples: int = 5,
    cap: int = 4,
) -> tuple:
    """Measure the steady synchronous tick round-trip on ``rescorer``'s
    backend and return ``(depth, rtt_seconds)``: the software-pipeline
    depth a churn loop with the given tick ``interval`` needs so the
    collect of a batch dispatched ``depth`` intervals ago blocks well
    under the interval::

        depth >= RTT/interval - 0.6   (0.4-interval headroom for
                                       admit bookkeeping + jitter)

    The pipeline depth is a property of the LINK, not the code — the
    same loop needs depth 1 on a ~65 ms tunnel and depth 2 on a ~200 ms
    one (LADDER_r03_tpu vs LADDER_r05_tpu config 5). Call after warming
    ``probe_width``'s bucket (``rescorer.warm([probe_width])``) so the
    probe measures the link, not a first compile; the probe's own ticks
    are un-recorded from the stats series (previously recorded ticks are
    untouched, so a mid-run re-probe is safe). ``cap`` bounds the depth
    the delta-bucket sizing is rated for (see ``_DELTA_BUCKET``).
    """
    dummies = [
        GroupDemand(
            full_name=f"__rtt__/{i}",
            min_member=1,
            member_request={"cpu": 1},
            has_pod=True,
        )
        for i in range(probe_width)
    ]
    rtts = []
    for _ in range(samples):
        t0 = time.perf_counter()
        rescorer.tick(None, dummies)
        rtts.append(time.perf_counter() - t0)
        rescorer.drop_last_stats()
    rtt = float(np.median(rtts))
    return max(1, min(cap, math.ceil(rtt / interval - 0.6))), rtt


class _DispatchFuture:
    """Minimal future for :class:`_DaemonDispatcher`: result/exception
    delivery via an Event. Cancellation exists only as
    ``shutdown(cancel_futures=True)`` failing still-queued futures."""

    __slots__ = ("_done", "_result", "_exc")

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def _finish(self, result=None, exc: Optional[BaseException] = None) -> None:
        self._result, self._exc = result, exc
        self._done.set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("dispatch result not ready")
        if self._exc is not None:
            raise self._exc
        return self._result


class _DaemonDispatcher:
    """Single-worker executor on a DAEMON thread.

    Exists because concurrent.futures joins its (non-daemon) workers from
    an interpreter-exit hook even after ``shutdown(wait=False)``, so a
    dispatch hung inside a dead backend would block interpreter exit
    forever — the residual join ADVICE r5 flagged in TickPipeline's
    failure path. A daemon worker dies with the process instead; the
    clean path still drains and joins exactly as before."""

    def __init__(self, name: str):
        from collections import deque

        self._cond = threading.Condition()
        self._items = deque()  # (fn, args, future); guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    def submit(self, fn, *args) -> _DispatchFuture:
        fut = _DispatchFuture()
        with self._cond:
            if self._closed:
                raise RuntimeError("dispatcher is shut down")
            self._items.append((fn, args, fut))
            self._cond.notify()
        return fut

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._closed:
                    self._cond.wait()
                if not self._items:
                    return  # closed and drained
                fn, args, fut = self._items.popleft()
            try:
                fut._finish(result=fn(*args))
            except BaseException as e:  # noqa: BLE001 — delivered via result()
                fut._finish(exc=e)

    def shutdown(self, wait: bool = True, cancel_futures: bool = False) -> None:
        with self._cond:
            self._closed = True
            if cancel_futures:
                for _, _, fut in self._items:
                    fut._finish(exc=RuntimeError("dispatch cancelled"))
                self._items.clear()
            self._cond.notify_all()
        if wait:
            self._thread.join()


class TickPipeline:
    """Depth-k software pipeline around a :class:`ChurnRescorer`.

    Encapsulates the choreography a slow link demands (measured and
    asserted by benchmarks/ladder.py config 5): dispatches run on a
    helper thread (per-argument h2d blocking rides the tick interval,
    not the caller's loop), ``collect`` returns the OLDEST in-flight
    batch ``depth`` intervals after its dispatch, and whole batches
    admit atomically through ``admit_verified`` — stale placements are
    skipped with clean rollback and simply re-ride a later dispatch,
    duplicates (the same still-pending gang rides every in-flight
    batch) skip for free via the ``placed_ever`` set.

    Usage::

        with TickPipeline(rescorer, depth) as pipe:
            for groups in fill_windows:      # depth windows, one per tick
                pipe.submit(groups)
                time.sleep(interval)
            while churning:
                out, tick_groups = pipe.collect()
                admitted, skips = pipe.admit_all(out, tick_groups)
                ... release/arrive, build next window ...
                pipe.submit(next_window)
                ... sleep out the interval remainder ...
        # __exit__ drains remaining in-flight batches (unrecorded)

    The dispatch window should be ``depth x`` the single-tick admission
    budget and carry the same pending PREFIX every tick: the oracle
    plans batches sequentially in priority order, so a follower batch
    containing its predecessor's gangs at the same ranks reproduces
    those placements and plans its fresh tail consistently around them.
    Disjoint or partially-admitted windows collide with the
    predecessor's best-fit seats almost every time (measured ~10x the
    skips, benchmarks/ladder.py loop comment).
    """

    def __init__(self, rescorer: "ChurnRescorer", depth: int):
        from collections import deque

        self.rescorer = rescorer
        self.depth = max(1, int(depth))
        self.placed_ever: set = set()
        self.admit_skips = 0
        self._inflight = deque()  # (future, groups) oldest-first
        self._pool = _DaemonDispatcher(name="tick-dispatch")

    # -- pipeline ----------------------------------------------------------

    def submit(self, groups: Sequence[GroupDemand]) -> None:
        """Dispatch a batch for ``groups`` on the helper thread."""
        groups = list(groups)
        self._inflight.append(
            (self._pool.submit(self.rescorer.tick_dispatch, None, groups),
             groups)
        )

    def collect(self) -> tuple:
        """Block until the OLDEST in-flight batch's result is ready and
        return ``(TickResult, groups)`` for it. In a loop that sleeps
        out its interval between submits, the D2H copy rode the sleeps
        and this returns ~immediately once depth matches the link."""
        fut, groups = self._inflight.popleft()
        return self.rescorer.tick_collect(fut.result()), groups

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    def admit_all(self, out: "TickResult", groups: Sequence[GroupDemand]):
        """Atomically admit every placement of one collected batch that
        is not already committed; returns ``(admitted_names, skips)``.
        Skipped gangs (stale placements rejected by the re-verify)
        stay the caller's to re-offer — they re-ride the next window."""
        placed = set(out.placed_groups())
        admitted, skips = [], 0
        for g in groups:
            name = g.full_name
            if name in placed and name not in self.placed_ever:
                if self.rescorer.admit_verified(out, name):
                    self.placed_ever.add(name)
                    admitted.append(name)
                else:
                    skips += 1
        self.admit_skips += skips
        return admitted, skips

    def drain(self, record_stats: bool = False) -> None:
        """Collect and discard every remaining in-flight batch (e.g. at
        loop shutdown); by default their timings are un-recorded so a
        benchmark's steady-state series stays clean."""
        while self._inflight:
            fut, _ = self._inflight.popleft()
            self.rescorer.tick_collect(fut.result())
            if not record_stats:
                self.rescorer.drop_last_stats()

    # -- context manager ---------------------------------------------------

    def __enter__(self) -> "TickPipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # a mid-loop failure must not leave the interpreter joining an
        # in-flight dispatch against a possibly-hung backend forever:
        # drain only on the clean path; on the failure path cancel the
        # queued not-yet-started dispatches (they would still execute
        # against the possibly-hung backend) and skip the join — the
        # worker is a daemon thread (_DaemonDispatcher), so even a
        # dispatch already RUNNING against a hung backend can never
        # block interpreter exit (ADVICE r5: concurrent.futures' exit
        # hook would join it regardless of wait=False)
        try:
            if exc_type is None:
                self.drain()
        finally:
            self._pool.shutdown(
                wait=exc_type is None, cancel_futures=exc_type is not None
            )
