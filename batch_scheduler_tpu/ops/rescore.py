"""Sustained churn re-scoring: the 100ms backfill loop of BASELINE config 5.

The reference has no equivalent — its hot loops re-run serially per pod per
scheduling cycle (reference pkg/scheduler/core/core.go:595-632,701-739).
Here a churning cluster (gangs finishing and freeing capacity, new gangs
arriving) is re-scored as a whole every tick by re-running the fused oracle
batch. Three properties make the tick budget:

- **bucketed padding** (ops.bucketing): pod/node/group counts are padded to
  power-of-two buckets, so a tick only recompiles when the cluster crosses a
  bucket boundary — steady-state churn hits the jit cache every time;
- **pinned lane schema**: the resource-lane dimension R is fixed up front
  (superset of every resource the loop will see), so a new extended resource
  appearing mid-loop can't change array shapes;
- **O(G) host fetch** (ops.oracle.execute_batch_host): each tick pulls only
  the per-group vectors + compact top-K assignment; (G,N) tensors stay on
  device.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..api.types import Node
from .lanes import LaneSchema
from .oracle import batch_top_k, execute_batch_host
from .snapshot import ClusterSnapshot, GroupDemand

__all__ = ["ChurnRescorer", "TickResult"]


@dataclass
class TickResult:
    """One re-score round: the oracle's O(G) answers + timing breakdown."""

    host: dict  # gang_feasible / placed / assignment_* / best / progress
    snapshot: ClusterSnapshot
    pack_seconds: float
    device_seconds: float
    bucket_shape: tuple  # (G_bucket, N_bucket, R, fit_mask_rows)

    @property
    def total_seconds(self) -> float:
        return self.pack_seconds + self.device_seconds

    def placed_groups(self) -> List[str]:
        placed = np.asarray(self.host["placed"])
        return [
            name
            for i, name in enumerate(self.snapshot.group_names)
            if placed[i]
        ]


class ChurnRescorer:
    """Re-scores a churning cluster every tick against a pinned lane schema.

    Usage::

        r = ChurnRescorer(nodes, extra_resources=["nvidia.com/gpu"])
        while churning:
            tick = r.tick(node_requested, pending_groups)
            ... admit tick.placed_groups(), mutate cluster state ...

    ``recompiles`` counts ticks whose padded bucket shape was never seen
    before — the only ticks that can trigger an XLA compile. In steady-state
    churn it stays at its initial value.
    """

    def __init__(
        self,
        nodes: Sequence[Node],
        extra_resources: Sequence[str] = (),
        node_requested: Optional[Dict[str, Dict[str, int]]] = None,
        schema: Optional[LaneSchema] = None,
        sticky_buckets: bool = False,
    ):
        self.nodes = list(nodes)
        self.schema = schema or LaneSchema.collect(
            [n.status.allocatable for n in nodes]
            + list((node_requested or {}).values())
            + [{name: 0} for name in extra_resources]
        )
        # dense occupancy state in device units: committed gang usage lives
        # here, maintained by admit()/release() without any dict packing
        self.requested_lanes = np.zeros(
            (len(self.nodes), self.schema.num_lanes), dtype=np.int32
        )
        self._running: Dict[str, tuple] = {}  # gang -> (node_idx, counts, lane_vec)
        # the alloc side of the snapshot never changes tick-to-tick
        self._alloc_lanes = self.schema.pack_many(
            [n.status.allocatable for n in self.nodes], capacity=True
        )
        self.latencies: List[float] = []
        self.pack_times: List[float] = []
        self.device_times: List[float] = []
        self._shapes_seen: set = set()
        self.recompiles = 0
        # Sticky buckets pin the padded shape to the largest seen — ZERO
        # recompiles ever, at the cost of scanning the max gang count every
        # tick. Off by default: the jit cache already holds every bucket
        # shape it has visited, so oscillating across a boundary only
        # compiles once per shape, and small ticks stay small.
        self._sticky = sticky_buckets
        self._sticky_buckets = (0, 0)
        self._alloc_dev = None  # device-resident padded alloc (see tick)

    def tick(
        self,
        node_requested: Optional[Dict[str, Dict[str, int]]],
        groups: Sequence[GroupDemand],
        nodes: Optional[Sequence[Node]] = None,
    ) -> TickResult:
        """Pack the current cluster state and run one fused oracle batch.

        ``node_requested=None`` uses the internal dense occupancy state
        (admit()/release() bookkeeping — the fast path). Passing a dict
        packs it instead (one-off scoring against external state).

        ``nodes`` overrides the node set for this tick (node churn); by
        default the constructor's node list is used (pod/group churn only).
        """
        if nodes is not None and node_requested is None:
            # the dense occupancy state is indexed by the constructor's node
            # list; scoring a different node set against it would silently
            # drop committed usage (double-booking)
            raise ValueError(
                "tick(nodes=...) requires an explicit node_requested dict; "
                "the internal dense occupancy state is only valid for the "
                "constructor's node list"
            )
        use_nodes = self.nodes if nodes is None else list(nodes)
        t0 = time.perf_counter()
        dense = self.requested_lanes if node_requested is None else None
        snap = ClusterSnapshot(
            use_nodes,
            node_requested or {},
            groups,
            schema=self.schema,
            requested_lanes=dense,
            alloc_lanes=self._alloc_lanes if nodes is None else None,
            min_buckets=self._sticky_buckets,
        )
        t_pack = time.perf_counter() - t0

        args = snap.device_args()
        if nodes is None:
            # the alloc side never changes tick-to-tick: keep the padded
            # array resident on device so steady ticks skip its host->device
            # transfer (the largest per-tick input)
            if (
                self._alloc_dev is None
                or self._alloc_dev.shape != args[0].shape
            ):
                self._alloc_dev = jax.device_put(args[0])
            args = (self._alloc_dev,) + args[1:]

        t1 = time.perf_counter()
        host, _device = execute_batch_host(args, snap.progress_args())
        t_device = time.perf_counter() - t1

        bucket_shape = (
            snap.group_req.shape[0],
            snap.alloc.shape[0],
            snap.alloc.shape[1],
            # mask row rank: 1 (uniform broadcast) vs G (selectors/taints
            # present) is a distinct jit signature — count it as a recompile
            snap.fit_mask.shape[0],
            # top-K readback tier (static in the batch's jit signature): a
            # gang wider than any seen tier compiles — count it too
            batch_top_k(
                snap.alloc.shape[0], int(snap.remaining.max(initial=0))
            ),
        )
        if bucket_shape not in self._shapes_seen:
            self._shapes_seen.add(bucket_shape)
            self.recompiles += 1
        if self._sticky:
            self._sticky_buckets = (
                max(self._sticky_buckets[0], bucket_shape[0]),
                max(self._sticky_buckets[1], bucket_shape[1]),
            )
        result = TickResult(
            host=host,
            snapshot=snap,
            pack_seconds=t_pack,
            device_seconds=t_device,
            bucket_shape=bucket_shape,
        )
        self.latencies.append(result.total_seconds)
        self.pack_times.append(t_pack)
        self.device_times.append(t_device)
        return result

    def warm(
        self,
        group_buckets: Sequence[int],
        with_selectors: bool = False,
        max_remaining: int = 16,
    ) -> None:
        """Precompile the oracle for the given gang-count buckets so no tick
        inside the churn loop ever pays a first-compile (~seconds on TPU).

        A uniform cluster compiles the broadcast ``[1,N]``-mask jit signature
        (ops.snapshot._fit_mask fast path); groups with node selectors (or
        tainted nodes) produce the full ``[G,N]`` signature — a distinct
        compile. Pass ``with_selectors=True`` if churn traffic can carry
        selectors, so both signatures are warm. ``max_remaining`` is the
        widest gang (members still needed) the loop will see: the batch's
        top-K readback tier is static in its jit signature
        (ops.oracle.batch_top_k), so a wider-than-warmed gang would compile
        mid-loop. Timing stats are reset afterwards."""
        for gb in group_buckets:
            variants = [{}]
            if with_selectors:
                variants.append({"node_selector": {"__warm__": "never"}})
            for extra in variants:
                dummies = [
                    GroupDemand(
                        full_name=f"__warm__/{i}",
                        min_member=max(1, max_remaining) if i == 0 else 1,
                        member_request={"cpu": 1},
                        has_pod=True,
                        **extra,
                    )
                    for i in range(gb)
                ]
                self.tick(None, dummies)
        self.latencies.clear()
        self.pack_times.clear()
        self.device_times.clear()

    # -- occupancy bookkeeping (dense fast path) ---------------------------

    def _member_lane_vec(self, group: GroupDemand) -> np.ndarray:
        req = dict(group.member_request)
        req["pods"] = max(req.get("pods", 0), 1)  # implicit pod slot
        return self.schema.pack(req).astype(np.int64)

    def admit(self, tick: TickResult, full_name: str) -> None:
        """Commit a placed gang: charge its assignment (from the tick's
        compact top-K) against the dense occupancy state.

        Valid for gangs assigned to <= ASSIGNMENT_TOP_K distinct nodes (the
        oracle's compact readback; 128 by default — far above any
        minMember in the BASELINE ladder).
        """
        if full_name in self._running:
            raise ValueError(f"{full_name} already admitted")
        gi = tick.snapshot.group_index(full_name)
        if gi is None:
            raise KeyError(full_name)
        group = tick.snapshot.groups[gi]
        nodes_idx = np.asarray(tick.host["assignment_nodes"])[gi]
        counts = np.asarray(tick.host["assignment_counts"])[gi]
        mask = counts > 0
        idx, cnt = nodes_idx[mask], counts[mask].astype(np.int64)
        vec = self._member_lane_vec(group)
        self.requested_lanes[idx] += (cnt[:, None] * vec[None, :]).astype(np.int32)
        self._running[full_name] = (idx, cnt, vec)

    def release(self, full_name: str) -> None:
        """A running gang finished: free its occupancy."""
        idx, cnt, vec = self._running.pop(full_name)
        self.requested_lanes[idx] -= (cnt[:, None] * vec[None, :]).astype(np.int32)

    @property
    def running(self) -> List[str]:
        return list(self._running)

    # -- stats -------------------------------------------------------------

    def percentile(self, q: float) -> float:
        if not self.latencies:
            return 0.0
        return float(np.percentile(np.array(self.latencies), q))

    def summary(self) -> dict:
        return {
            "ticks": len(self.latencies),
            "p50_s": round(self.percentile(50), 5),
            "p95_s": round(self.percentile(95), 5),
            "max_s": round(max(self.latencies), 5) if self.latencies else 0.0,
            "p50_pack_s": round(float(np.median(self.pack_times)), 5) if self.pack_times else 0.0,
            "p50_device_s": round(float(np.median(self.device_times)), 5) if self.device_times else 0.0,
            "bucket_shapes": sorted(self._shapes_seen),
            "recompiles": self.recompiles,
        }
