"""ClusterSnapshot: host-side packing of API objects into the oracle's
padded int32 arrays.

The reference walks a ``SnapshotSharedLister`` of NodeInfo objects per pod
per cycle (reference pkg/scheduler/core/core.go:436-475,566-632). Here the
snapshot is packed once per batch into dense arrays — node allocatable /
requested lanes, per-group member requirements, and a (group × node)
placement-feasibility mask — then every group is scored in one device call.

Host-side string work (node selectors, taints — reference core.go:741-759)
happens exactly once per (group, node) per snapshot, not per pod per cycle,
with a fast path that skips the quadratic walk entirely when no selectors or
taints exist (the overwhelmingly common case).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..api.fit import selector_matches, tolerates_all
from ..api.types import Node, Pod, Toleration
from .bucketing import pad_oracle_batch, pad_rows
from .lanes import LaneSchema

__all__ = ["GroupDemand", "ClusterSnapshot", "node_requested_from_pods"]


@dataclass
class GroupDemand:
    """One PodGroup's demand as seen by the oracle."""

    full_name: str
    min_member: int
    scheduled: int = 0
    matched: int = 0
    priority: int = 0
    creation_ts: float = 0.0
    # Per-member canonical resource requirement (includes an implicit pod
    # slot); from spec.min_resources or the representative pod
    # (reference core.go:489-493).
    member_request: Dict[str, int] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    # Gang already released to bind (reference cache.go:66) — excluded from
    # max-progress selection.
    released: bool = False
    # No representative pod observed yet (reference core.go:709-710).
    has_pod: bool = True

    @property
    def remaining(self) -> int:
        """Members still needing placement. Matched (permitted-but-unbound)
        pods are excluded: the framework has already *assumed* them onto
        nodes, so their capacity is out of the leftover lanes — counting
        them here too would double-charge the gang and starve its own tail."""
        return max(self.min_member - self.scheduled - self.matched, 0)


def node_requested_from_pods(pods: Sequence[Pod]) -> Dict[str, int]:
    """Aggregate the canonical requested resources of pods bound to a node,
    including the implicit pod slot (reference core.go:650-654)."""
    total: Dict[str, int] = {"pods": 0}
    for p in pods:
        total["pods"] += 1
        for k, v in p.resource_require().items():
            total[k] = total.get(k, 0) + v
    return total


class ClusterSnapshot:
    """Padded, device-ready view of (nodes × groups) for one batch."""

    def __init__(
        self,
        nodes: Sequence[Node],
        node_requested: Dict[str, Dict[str, int]],
        groups: Sequence[GroupDemand],
        schema: Optional[LaneSchema] = None,
        requested_lanes: Optional[np.ndarray] = None,
        alloc_lanes: Optional[np.ndarray] = None,
        min_buckets: tuple = (0, 0),
    ):
        self.node_names = [n.metadata.name for n in nodes]
        self.group_names = [g.full_name for g in groups]
        self.groups = list(groups)
        self._node_index = {n: i for i, n in enumerate(self.node_names)}
        self._group_index = {g: i for i, g in enumerate(self.group_names)}

        # a caller-pinned schema keeps the lane dimension stable across
        # successive snapshots (churn re-scoring must hit the jit cache;
        # a resource name appearing/vanishing would otherwise change R)
        self.schema = schema or LaneSchema.collect(
            [node_requested.get(n.metadata.name, {}) for n in nodes]
            + [n.status.allocatable for n in nodes]
            + [g.member_request for g in groups]
        )

        self.num_nodes = len(nodes)
        self.num_groups = len(groups)

        if alloc_lanes is not None:
            alloc = np.asarray(alloc_lanes, dtype=np.int32)
            if alloc.shape != (len(nodes), self.schema.num_lanes):
                raise ValueError(
                    f"alloc_lanes shape {alloc.shape} != "
                    f"({len(nodes)}, {self.schema.num_lanes})"
                )
        else:
            alloc = self.schema.pack_many(
                [n.status.allocatable for n in nodes], capacity=True
            )
        if requested_lanes is not None:
            # dense fast path for churn re-scoring: the caller maintains the
            # (N, R) requested array in device units and skips dict packing.
            # Copied: the caller keeps mutating its array (admit/release) and
            # the snapshot must stay what was actually scored.
            requested = np.array(requested_lanes, dtype=np.int32)
            if requested.shape != (len(nodes), self.schema.num_lanes):
                raise ValueError(
                    f"requested_lanes shape {requested.shape} != "
                    f"({len(nodes)}, {self.schema.num_lanes})"
                )
        else:
            requested = self.schema.pack_many(
                [node_requested.get(n.metadata.name, {}) for n in nodes]
            )
        node_valid = np.array(
            [not n.spec.unschedulable for n in nodes], dtype=bool
        )

        member_reqs = []
        for g in groups:
            req = dict(g.member_request)
            req["pods"] = max(req.get("pods", 0), 1)
            member_reqs.append(req)
        group_req = self.schema.pack_many(member_reqs)

        fit = self._fit_mask(nodes, groups) & node_valid[None, :]

        # queue order: priority desc, creation asc, name (Compare semantics)
        order_host = sorted(
            range(len(groups)),
            key=lambda i: (
                -groups[i].priority,
                groups[i].creation_ts,
                groups[i].full_name,
            ),
        )
        ranks = np.empty(len(groups), dtype=np.int32)
        ranks[order_host] = np.arange(len(groups), dtype=np.int32)

        batch_args, progress_args = pad_oracle_batch(
            min_buckets=min_buckets,
            alloc=alloc,
            requested=requested,
            group_req=group_req,
            remaining=np.array([g.remaining for g in groups], dtype=np.int32),
            fit_mask=fit,
            group_valid=np.ones(len(groups), dtype=bool),
            order=np.array(order_host, dtype=np.int32),
            min_member=np.array([g.min_member for g in groups], dtype=np.int32),
            scheduled=np.array([g.scheduled for g in groups], dtype=np.int32),
            matched=np.array([g.matched for g in groups], dtype=np.int32),
            # Ineligible for max-progress selection: already released or no
            # representative pod yet.
            ineligible=np.array(
                [g.released or not g.has_pod for g in groups], dtype=bool
            ),
            creation_rank=ranks,
        )
        (
            self.alloc,
            self.requested,
            self.group_req,
            self.remaining,
            self.fit_mask,
            self.group_valid,
            self.order,
        ) = batch_args
        (
            self.min_member,
            self.scheduled,
            self.matched,
            self.ineligible,
            self.creation_rank,
        ) = progress_args
        self.node_valid = pad_rows(
            node_valid, self.alloc.shape[0], fill=False
        )

    def _fit_mask(
        self, nodes: Sequence[Node], groups: Sequence[GroupDemand]
    ) -> np.ndarray:
        """Per-(group,node) placement feasibility.

        Fast path: with no node selectors and no taints anywhere (the
        overwhelmingly common case) the mask is uniform — return a single
        broadcast ``[1,N]`` row. At 1k groups x 5k nodes the full mask is
        ~8 MB of host->device transfer per batch; the broadcast row is 8 KB.
        The oracle kernels accept either shape (ops.oracle.assign_gangs).
        """
        any_taints = any(n.spec.taints for n in nodes)
        if not any_taints and not any(g.node_selector for g in groups):
            return np.ones((1, len(nodes)), dtype=bool)
        mask = np.ones((len(groups), len(nodes)), dtype=bool)
        for gi, g in enumerate(groups):
            if not g.node_selector and not any_taints:
                continue
            for ni, node in enumerate(nodes):
                ok = selector_matches(g.node_selector, node.metadata.labels)
                if ok and node.spec.taints:
                    ok = tolerates_all(g.tolerations, node.spec.taints)
                mask[gi, ni] = ok
        return mask

    # -- lookups -----------------------------------------------------------

    def group_index(self, full_name: str) -> Optional[int]:
        return self._group_index.get(full_name)

    def node_index(self, name: str) -> Optional[int]:
        return self._node_index.get(name)

    def device_args(self) -> tuple:
        """Argument tuple for ops.oracle.schedule_batch."""
        return (
            self.alloc,
            self.requested,
            self.group_req,
            self.remaining,
            self.fit_mask,
            self.group_valid,
            self.order,
        )

    def progress_args(self) -> tuple:
        """Argument tuple for ops.oracle.find_max_group."""
        return (
            self.min_member,
            self.scheduled,
            self.matched,
            self.ineligible,
            self.creation_rank,
        )

    @property
    def shape(self) -> tuple:
        return (
            self.group_req.shape[0],
            self.alloc.shape[0],
            self.schema.num_lanes,
        )
