"""ClusterSnapshot: host-side packing of API objects into the oracle's
padded int32 arrays.

The reference walks a ``SnapshotSharedLister`` of NodeInfo objects per pod
per cycle (reference pkg/scheduler/core/core.go:436-475,566-632). Here the
snapshot is packed once per batch into dense arrays — node allocatable /
requested lanes, per-group member requirements, and a (group × node)
placement-feasibility mask — then every group is scored in one device call.

Host-side string work (node selectors, taints — reference core.go:741-759)
happens exactly once per (group, node) per snapshot, not per pod per cycle,
with a fast path that skips the quadratic walk entirely when no selectors or
taints exist (the overwhelmingly common case).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..api.fit import selector_matches, tolerates_all
from ..api.types import Node, Pod, Toleration
from .bucketing import pad_oracle_batch, pad_rows
from .lanes import LaneSchema

__all__ = [
    "GroupDemand",
    "ClusterSnapshot",
    "DeltaSnapshotPacker",
    "SnapshotDelta",
    "node_requested_from_pods",
    "snapshot_lite_enabled",
]


_EMPTY_IDX = np.zeros(0, dtype=np.int32)

_LITE_ENV = "BST_SNAPSHOT_LITE"
_lite_warned = [False]


def snapshot_lite_enabled() -> bool:
    """Parse-guarded BST_SNAPSHOT_LITE read: default ON; ``0``/``off``/
    ``false`` disables the persistent-buffer fast path (every pack then
    runs the full ClusterSnapshot construction — the PR 11 behaviour,
    kept as the bench comparison baseline). Unrecognised values warn once
    and keep the default (the BST_SCAN_WAVE idiom)."""
    import os

    raw = os.environ.get(_LITE_ENV, "").strip().lower()
    if raw in ("", "1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no"):
        return False
    if not _lite_warned[0]:
        _lite_warned[0] = True
        import sys

        print(
            f"ignoring unrecognised {_LITE_ENV}={raw!r}; snapshot-lite "
            "stays enabled",
            file=sys.stderr,
        )
    return True


_I32_MAX = np.iinfo(np.int32).max


def _ts_sort_keys(ts: np.ndarray):
    """Order-preserving (hi, lo) int32 key pair for float64 creation
    timestamps: total-ordered exactly like Python ``<`` on finite doubles
    (with ``-0.0`` collapsed onto ``0.0``, which host tuple compare also
    treats as equal). The IEEE754 bits are mapped to a monotone uint64
    (sign-flip for positives, full complement for negatives), split into
    32-bit halves, and each half biased into int32 — so a device lexsort
    over ``(ts_hi, ts_lo)`` reproduces the host's float ascending order
    bit-for-bit."""
    ts = np.asarray(ts, dtype=np.float64)
    ts = np.where(ts == 0.0, 0.0, ts)  # -0.0 and 0.0 must key equal
    u = ts.view(np.uint64)
    mask = np.where(
        (u >> np.uint64(63)).astype(bool),
        np.uint64(0xFFFFFFFFFFFFFFFF),
        np.uint64(0x8000000000000000),
    )
    k = u ^ mask
    hi = (k >> np.uint64(32)).astype(np.uint32) ^ np.uint32(0x80000000)
    lo = (k & np.uint64(0xFFFFFFFF)).astype(np.uint32) ^ np.uint32(0x80000000)
    return hi.view(np.int32), lo.view(np.int32)


@dataclass
class SnapshotDelta:
    """What changed between two consecutive packs — the churned-row record
    the device-resident state layer (ops.device_state, docs/pipelining.md
    "Device-resident state") applies as jit'd scatter-updates instead of
    re-uploading a full snapshot.

    ``kind`` is ``"delta"`` when the packer's cached schema, node list and
    group set all held, so the previous pack's packed ``[N, R]`` /
    ``[G, R]`` buffers become this pack's by rewriting exactly the listed
    rows (the row VALUES live in the emitted ClusterSnapshot's padded
    arrays at the same indices — padding appends, so unpadded indices are
    valid in padded space). ``kind == "keyframe"`` means the buffers must
    be replaced wholesale; ``reason`` says why (the invalidation rules of
    docs/pipelining.md, extended to residency):

    - ``first``      — no previous pack
    - ``node-list``  — node names/order changed (positional keys broke)
    - ``node-churn`` — a node OBJECT changed or a churned row stopped
                       packing under the cached schema (the packer's
                       full-repack rules; the lane shifts may have moved)
    - ``group-set``  — the group name set/order changed (group row
                       indices are positional)

    ``generation`` increments once per pack; consumers verify contiguity
    (``generation == applied + 1``) before scattering, and resync from a
    keyframe on any gap — never silently score stale rows.

    ``source`` records which refresh path produced the pack (additive —
    consumers key on ``kind`` only): ``"scan"`` for a full O(N+G) read of
    the cluster state (the legacy and snapshot-lite scan paths), or
    ``"events"`` for an O(churn) event fold (``pack_fold``).
    ``meta_rows`` lists group rows whose QUEUE-ORDER meta (priority /
    creation_ts sort keys) churned — the device-derive path scatters
    those and re-derives the order permutation on device
    (ops.device_state, docs/pipelining.md "Snapshot-lite & event
    ingest").
    """

    generation: int
    kind: str  # "delta" | "keyframe"
    reason: str = ""  # keyframe reason, "" for deltas
    # churned REQUESTED node rows / group demand rows / node policy rows,
    # unpadded row indices (int32); empty on keyframes
    node_rows: np.ndarray = field(default_factory=lambda: _EMPTY_IDX)
    group_rows: np.ndarray = field(default_factory=lambda: _EMPTY_IDX)
    policy_node_rows: np.ndarray = field(default_factory=lambda: _EMPTY_IDX)
    source: str = "scan"  # "scan" | "events"
    meta_rows: np.ndarray = field(default_factory=lambda: _EMPTY_IDX)


@dataclass
class GroupDemand:
    """One PodGroup's demand as seen by the oracle."""

    full_name: str
    min_member: int
    scheduled: int = 0
    matched: int = 0
    priority: int = 0
    creation_ts: float = 0.0
    # Per-member canonical resource requirement (includes an implicit pod
    # slot); from spec.min_resources or the representative pod
    # (reference core.go:489-493).
    member_request: Dict[str, int] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    # Gang already released to bind (reference cache.go:66) — excluded from
    # max-progress selection.
    released: bool = False
    # No representative pod observed yet (reference core.go:709-710).
    has_pod: bool = True
    # Policy columns (batch_scheduler_tpu.policy, docs/policy.md): label
    # hashes of the gang's soft-affinity / hard-anti-affinity targets
    # (0 = none), the spread opt-in, and the gang's currently-matched
    # members per node (the spread term's domain occupancy source).
    affinity_hash: int = 0
    anti_hash: int = 0
    spread: bool = False
    placed_nodes: Dict[str, int] = field(default_factory=dict)

    @property
    def remaining(self) -> int:
        """Members still needing placement. Matched (permitted-but-unbound)
        pods are excluded: the framework has already *assumed* them onto
        nodes, so their capacity is out of the leftover lanes — counting
        them here too would double-charge the gang and starve its own tail."""
        return max(self.min_member - self.scheduled - self.matched, 0)


def node_requested_from_pods(pods: Sequence[Pod]) -> Dict[str, int]:
    """Aggregate the canonical requested resources of pods bound to a node,
    including the implicit pod slot (reference core.go:650-654)."""
    total: Dict[str, int] = {"pods": 0}
    for p in pods:
        total["pods"] += 1
        for k, v in p.resource_require().items():
            total[k] = total.get(k, 0) + v
    return total


def _policy_hash_lanes() -> int:
    from ..policy.terms import HASH_LANES

    return HASH_LANES


def _member_request_row(g: GroupDemand) -> Dict[str, int]:
    """A group's per-member demand dict with the implicit pod slot applied —
    THE conversion both the full pack and the delta packer use, so the two
    can never drift."""
    req = dict(g.member_request)
    req["pods"] = max(req.get("pods", 0), 1)
    return req


def _demand_fp(g: GroupDemand) -> tuple:
    """Content fingerprint of every oracle-visible demand field the lite
    packer diffs. Callers may mutate a GroupDemand IN PLACE between packs,
    so change detection must compare captured content, never a stored
    object reference (the legacy ``_group_rows`` memo was content-keyed
    for the same reason). ``remaining`` is derived from indices 1..3."""
    return (
        tuple(sorted(g.member_request.items())),
        g.min_member,
        g.scheduled,
        g.matched,
        g.priority,
        g.creation_ts,
        bool(g.released),
        bool(g.has_pod),
    )


class ClusterSnapshot:
    """Padded, device-ready view of (nodes × groups) for one batch."""

    def __init__(
        self,
        nodes: Sequence[Node],
        node_requested: Dict[str, Dict[str, int]],
        groups: Sequence[GroupDemand],
        schema: Optional[LaneSchema] = None,
        requested_lanes: Optional[np.ndarray] = None,
        alloc_lanes: Optional[np.ndarray] = None,
        group_req_lanes: Optional[np.ndarray] = None,
        min_buckets: tuple = (0, 0),
        policy_engine=None,
        node_policy_lanes: Optional[tuple] = None,
    ):
        self.node_names = [n.metadata.name for n in nodes]
        self.group_names = [g.full_name for g in groups]
        self.groups = list(groups)
        self._node_index = {n: i for i, n in enumerate(self.node_names)}
        self._group_index = {g: i for i, g in enumerate(self.group_names)}

        # a caller-pinned schema keeps the lane dimension stable across
        # successive snapshots (churn re-scoring must hit the jit cache;
        # a resource name appearing/vanishing would otherwise change R)
        self.schema = schema or LaneSchema.collect(
            [node_requested.get(n.metadata.name, {}) for n in nodes]
            + [n.status.allocatable for n in nodes]
            + [g.member_request for g in groups]
        )

        self.num_nodes = len(nodes)
        self.num_groups = len(groups)

        if alloc_lanes is not None:
            alloc = np.asarray(alloc_lanes, dtype=np.int32)
            if alloc.shape != (len(nodes), self.schema.num_lanes):
                raise ValueError(
                    f"alloc_lanes shape {alloc.shape} != "
                    f"({len(nodes)}, {self.schema.num_lanes})"
                )
        else:
            alloc = self.schema.pack_many(
                [n.status.allocatable for n in nodes], capacity=True
            )
        if requested_lanes is not None:
            # dense fast path for churn re-scoring: the caller maintains the
            # (N, R) requested array in device units and skips dict packing.
            # Copied: the caller keeps mutating its array (admit/release) and
            # the snapshot must stay what was actually scored.
            requested = np.array(requested_lanes, dtype=np.int32)
            if requested.shape != (len(nodes), self.schema.num_lanes):
                raise ValueError(
                    f"requested_lanes shape {requested.shape} != "
                    f"({len(nodes)}, {self.schema.num_lanes})"
                )
        else:
            requested = self.schema.pack_many(
                [node_requested.get(n.metadata.name, {}) for n in nodes]
            )
        node_valid = np.array(
            [not n.spec.unschedulable for n in nodes], dtype=bool
        )

        if group_req_lanes is not None:
            # delta-pack fast path: the caller (DeltaSnapshotPacker) packed
            # the member-demand rows — with the implicit pod slot already
            # applied — against THIS schema and hands over ownership.
            group_req = np.asarray(group_req_lanes, dtype=np.int32)
            if group_req.shape != (len(groups), self.schema.num_lanes):
                raise ValueError(
                    f"group_req_lanes shape {group_req.shape} != "
                    f"({len(groups)}, {self.schema.num_lanes})"
                )
        else:
            group_req = self.schema.pack_many(
                [_member_request_row(g) for g in groups]
            )

        fit = self._fit_mask(nodes, groups) & node_valid[None, :]

        # queue order: priority desc, creation asc, name (Compare semantics)
        order_host = sorted(
            range(len(groups)),
            key=lambda i: (
                -groups[i].priority,
                groups[i].creation_ts,
                groups[i].full_name,
            ),
        )
        ranks = np.empty(len(groups), dtype=np.int32)
        ranks[order_host] = np.arange(len(groups), dtype=np.int32)

        batch_args, progress_args = pad_oracle_batch(
            min_buckets=min_buckets,
            alloc=alloc,
            requested=requested,
            group_req=group_req,
            remaining=np.array([g.remaining for g in groups], dtype=np.int32),
            fit_mask=fit,
            group_valid=np.ones(len(groups), dtype=bool),
            order=np.array(order_host, dtype=np.int32),
            min_member=np.array([g.min_member for g in groups], dtype=np.int32),
            scheduled=np.array([g.scheduled for g in groups], dtype=np.int32),
            matched=np.array([g.matched for g in groups], dtype=np.int32),
            # Ineligible for max-progress selection: already released or no
            # representative pod yet.
            ineligible=np.array(
                [g.released or not g.has_pod for g in groups], dtype=bool
            ),
            creation_rank=ranks,
        )
        (
            self.alloc,
            self.requested,
            self.group_req,
            self.remaining,
            self.fit_mask,
            self.group_valid,
            self.order,
        ) = batch_args
        (
            self.min_member,
            self.scheduled,
            self.matched,
            self.ineligible,
            self.creation_rank,
        ) = progress_args
        self.node_valid = pad_rows(
            node_valid, self.alloc.shape[0], fill=False
        )

        # -- packed policy columns (batch_scheduler_tpu.policy) -----------
        # Built only when an enabled engine is attached; policy-off
        # snapshots carry None and every downstream path runs the exact
        # pre-policy code (the zero-policy identity of docs/policy.md).
        self.policy_engine = policy_engine
        self.policy_cols = None
        # churned-row record stamped by DeltaSnapshotPacker.pack (None on
        # directly-constructed snapshots: no previous pack to delta from)
        self.delta: Optional["SnapshotDelta"] = None
        # queue-order sort-key columns (inv_prio, ts_hi, ts_lo, name_rank),
        # padded [Gb] int32 — stamped by the packer's snapshot-lite capture
        # so ops.device_state can derive fit/order on device; None on
        # directly-constructed snapshots (host columns stay authoritative)
        self.meta_cols: Optional[tuple] = None
        if policy_engine is not None and policy_engine.enabled:
            from ..policy.terms import (
                DOMAIN_BUCKETS,
                node_policy_row,
            )

            nb, gb = self.alloc.shape[0], self.group_req.shape[0]
            if node_policy_lanes is not None:
                node_hash, node_dom = node_policy_lanes
                node_hash = np.asarray(node_hash, np.int32)
                node_dom = np.asarray(node_dom, np.int32)
            else:
                spread_key = policy_engine.config.spread_node_key
                node_hash = np.zeros(
                    (len(nodes), _policy_hash_lanes()), np.int32
                )
                node_dom = np.zeros(len(nodes), np.int32)
                truncated = 0
                for i, n in enumerate(nodes):
                    row, dom, trunc = node_policy_row(
                        n.metadata.labels or {}, spread_key
                    )
                    node_hash[i] = row
                    node_dom[i] = dom
                    truncated += trunc
                if truncated:
                    from ..utils.metrics import DEFAULT_REGISTRY

                    DEFAULT_REGISTRY.counter(
                        "bst_policy_label_truncations_total",
                        "Node labels beyond the packed hash lanes "
                        "(affinity against them can never match)",
                    ).inc(truncated)
            prio = np.array([g.priority for g in groups], np.int32)
            aff = np.array([g.affinity_hash for g in groups], np.int32)
            anti = np.array([g.anti_hash for g in groups], np.int32)
            gang_dom = np.zeros((len(groups), DOMAIN_BUCKETS), np.int32)
            for gi, g in enumerate(groups):
                if not g.spread or not g.placed_nodes:
                    continue
                for node_name, count in g.placed_nodes.items():
                    ni = self._node_index.get(node_name)
                    if ni is not None:
                        gang_dom[gi, int(node_dom[ni])] += int(count)
            self.policy_cols = (
                pad_rows(prio, gb),
                pad_rows(aff, gb),
                pad_rows(anti, gb),
                pad_rows(gang_dom, gb),
                pad_rows(node_hash, nb),
                pad_rows(node_dom, nb),
            )

    def _fit_mask(
        self, nodes: Sequence[Node], groups: Sequence[GroupDemand]
    ) -> np.ndarray:
        """Per-(group,node) placement feasibility.

        Fast path: with no node selectors and no taints anywhere (the
        overwhelmingly common case) the mask is uniform — return a single
        broadcast ``[1,N]`` row. At 1k groups x 5k nodes the full mask is
        ~8 MB of host->device transfer per batch; the broadcast row is 8 KB.
        The oracle kernels accept either shape (ops.oracle.assign_gangs).
        """
        any_taints = any(n.spec.taints for n in nodes)
        if not any_taints and not any(g.node_selector for g in groups):
            return np.ones((1, len(nodes)), dtype=bool)
        mask = np.ones((len(groups), len(nodes)), dtype=bool)
        for gi, g in enumerate(groups):
            if not g.node_selector and not any_taints:
                continue
            for ni, node in enumerate(nodes):
                ok = selector_matches(g.node_selector, node.metadata.labels)
                if ok and node.spec.taints:
                    ok = tolerates_all(g.tolerations, node.spec.taints)
                mask[gi, ni] = ok
        return mask

    # -- lookups -----------------------------------------------------------

    def group_index(self, full_name: str) -> Optional[int]:
        return self._group_index.get(full_name)

    def node_index(self, name: str) -> Optional[int]:
        return self._node_index.get(name)

    def device_args(self) -> tuple:
        """Argument tuple for ops.oracle.schedule_batch."""
        return (
            self.alloc,
            self.requested,
            self.group_req,
            self.remaining,
            self.fit_mask,
            self.group_valid,
            self.order,
        )

    def progress_args(self) -> tuple:
        """Argument tuple for ops.oracle.find_max_group."""
        return (
            self.min_member,
            self.scheduled,
            self.matched,
            self.ineligible,
            self.creation_rank,
        )

    def policy_payload(self):
        """The ``policy=`` argument for ops.oracle.dispatch_batch —
        ``(policy_cols, terms, weights)`` when an enabled engine packed
        columns for this snapshot, else None (the exact pre-policy path)."""
        if self.policy_cols is None or self.policy_engine is None:
            return None
        cfg = self.policy_engine.config
        if not cfg.scoring_terms:
            # preemption-only configs pack columns (the planner reads
            # priorities) but score nothing: the base rungs stay live
            return None
        return (self.policy_cols, cfg.scoring_terms, cfg.weights)

    @property
    def shape(self) -> tuple:
        return (
            self.group_req.shape[0],
            self.alloc.shape[0],
            self.schema.num_lanes,
        )


@dataclass
class _LiteState:
    """The packer's persistent PADDED working set (snapshot-lite,
    docs/pipelining.md "Snapshot-lite & event ingest"): everything a
    ClusterSnapshot carries, kept alive across packs so a delta-applicable
    refresh touches only churned rows — no per-refresh pad copies, no
    fit-mask scan, no queue-order sort.

    Mutability contract (what `_lite_snapshot` must copy vs may share):

    - ``pad_requested`` / ``pad_group_req`` and the five tail arrays are
      mutated IN PLACE per pack → copied into every emitted snapshot
      (utils.audit holds snapshot arrays by reference);
    - ``order`` / ``creation_rank`` / ``meta`` are REPLACED wholesale on
      queue-meta churn (never mutated) → shared with snapshots;
    - ``pad_alloc`` / ``fit_row`` / ``node_valid`` / ``group_valid`` are
      immutable while the lite state is valid (any alloc / taint /
      unschedulable / selector / membership change invalidates it) →
      shared.

    Validity requires: node list and gang set positionally stable, the
    uniform-fit fast path (no selectors, no taints — ``fit_row`` IS the
    padded node_valid row), policy engine off, and every churned value
    inside the pad_oracle_batch bounds (a violation falls back to the
    full path so the canonical OverflowError raises there)."""

    n: int
    g: int
    nb: int
    gb: int
    node_names: tuple
    group_names: tuple
    node_index: dict
    group_index: dict
    node_names_list: list
    group_names_list: list
    demands: list
    fps: list  # per-row _demand_fp — content diffs survive in-place mutation
    gang_bound: int
    pad_alloc: np.ndarray  # [Nb,R] shared (alloc churn keyframes)
    pad_requested: np.ndarray  # [Nb,R] mutated in place
    pad_group_req: np.ndarray  # [Gb,R] mutated in place
    remaining: np.ndarray  # [Gb] mutated in place
    min_member: np.ndarray  # [Gb] mutated in place
    scheduled: np.ndarray  # [Gb] mutated in place
    matched: np.ndarray  # [Gb] mutated in place
    ineligible: np.ndarray  # [Gb] mutated in place
    fit_row: np.ndarray  # [1,Nb] shared (uniform-fit invariant)
    node_valid: np.ndarray  # [Nb] shared
    group_valid: np.ndarray  # [Gb] shared
    order: np.ndarray  # [Gb] replaced wholesale on meta churn
    creation_rank: np.ndarray  # [Gb] replaced wholesale on meta churn
    meta: tuple  # (inv_prio, ts_hi, ts_lo, name_rank) [Gb] i32, replaced


class DeltaSnapshotPacker:
    """Persistent packed host buffers: rewrite only churned rows per refresh.

    The full pack walks every node/group dict every batch — schema collect
    alone scans ~11k dicts at the north-star shape, and ``pack_many``
    re-keys all of them even when the memo hits. On a low-churn steady
    state almost none of that work changes between refreshes. This packer
    keeps the packed ``[N, R]`` / ``[G, R]`` arrays alive across calls and
    rewrites only:

    - node **requested** rows whose requested-dict content changed (the
      resource_version does not cover scheduler-side accounting, so the
      dict is compared directly — still ~10x cheaper than re-packing);
    - group demand rows, rebuilt from a persistent per-demand row memo
      (group membership churns; the memo makes each row a copy).

    Full repack remains the fallback whenever a node OBJECT changed
    (``(name, resource_version)`` key — the lane shifts are sized from
    the alloc peaks, so alloc churn must re-collect the schema exactly
    like the scorer's old per-batch schema reuse did), the node list
    changed, or a churned demand/requested row stops packing exactly
    under the cached schema (new resource name, out-of-domain value —
    ``LaneSchema.covers``).

    Handed-over arrays are COPIES: a published ClusterSnapshot must stay
    what was actually scored while the packer keeps mutating its buffers.
    Not thread-safe; callers serialize packs (the scorer's refresh lock).
    """

    def __init__(self, policy_engine=None):
        self.schema: Optional[LaneSchema] = None
        self._node_names: Optional[tuple] = None
        self._alloc_keys: list = []
        self._req_dicts: list = []  # copies: validity is dict equality
        self._alloc: Optional[np.ndarray] = None
        self._requested: Optional[np.ndarray] = None
        # persistent row memos (cleared when the schema actually changes;
        # a memo hit implies the row was validated exact at insert time)
        self._req_row_memo: Dict[tuple, np.ndarray] = {}
        self._group_row_memo: Dict[tuple, np.ndarray] = {}
        self.full_repacks = 0
        self.delta_packs = 0
        self.last_rows_rewritten = 0
        # snapshot-lite working set (None until a capture-eligible full
        # construction; see _LiteState) + per-path counters
        self._lite: Optional[_LiteState] = None
        self.lite_packs = 0  # lite scan-path packs
        self.fold_packs = 0  # event-fold packs (pack_fold)
        self.order_resorts = 0  # queue-meta churn resorts
        # Churned-row delta emission (SnapshotDelta): one record per pack,
        # consumed by the device-resident state layer (ops.device_state)
        # and the wire delta path (service.client RemoteScorer). The
        # generation increments on EVERY pack — consumers detect gaps.
        self.generation = 0
        self.last_delta: Optional[SnapshotDelta] = None
        self._group_names: Optional[tuple] = None
        self._group_prev: Optional[np.ndarray] = None  # last [G, R] rows
        # Policy column persistence (docs/policy.md "Packing"): node
        # label-hash / spread-domain rows keyed by each node's label dict,
        # so label churn rewrites only touched rows — independent of the
        # lane-side full-repack rules (a resource_version bump full-repacks
        # the LANES but the policy rows of unchanged-label nodes survive).
        # Group policy columns are O(G·D) and rebuilt per pack (spread
        # occupancy churns with every permit; memoizing it would just
        # trade the fill for an equality walk).
        self.policy_engine = policy_engine
        self._policy_labels: list = []  # per-node sorted label tuples
        self._policy_hash: Optional[np.ndarray] = None
        self._policy_dom: Optional[np.ndarray] = None
        self.policy_rows_rewritten = 0
        self._policy_rows_idx: list = []

    # -- internals ----------------------------------------------------------

    class _SchemaMiss(Exception):
        """A churned row no longer packs exactly under the cached schema
        (new resource name or out-of-domain value): fall back to the full
        repack, never to a silent clamp."""

    def _full_repack(self, nodes, alloc_dicts, req_dicts, groups) -> None:
        new_schema = LaneSchema.collect(
            list(req_dicts) + list(alloc_dicts)
            + [g.member_request for g in groups]
        )
        if (
            self.schema is None
            or new_schema.names != self.schema.names
            or new_schema.shifts != self.schema.shifts
        ):
            # packing actually changes: the memoized rows are stale
            self.schema = new_schema
            self._req_row_memo.clear()
            self._group_row_memo.clear()
        self._node_names = tuple(n.metadata.name for n in nodes)
        self._alloc_keys = [
            (n.metadata.name, n.metadata.resource_version) for n in nodes
        ]
        self._req_dicts = [dict(d) for d in req_dicts]
        self._alloc = self.schema.pack_many(alloc_dicts, capacity=True)
        self._requested = self.schema.pack_many(req_dicts)
        self.full_repacks += 1
        self.last_rows_rewritten = 2 * len(nodes)

    def _delta_rows(self, nodes, req_dicts) -> list:
        """Rewrite churned REQUESTED rows in place and return their row
        indices; raises _SchemaMiss when a churned row stops packing
        exactly under the cached schema — or when any node OBJECT changed
        (resource_version bump). Alloc-side churn always full-repacks: the
        lane shifts are sized from the observed alloc peaks, and a delta
        rewrite under the cached shifts could keep a stale (coarser)
        granularity after the peak node shrank — the old per-batch schema
        reuse re-collected on exactly this key, and the packer must not
        weaken that. Node updates are rare (scheduler-side accounting
        moves ``requested``, not the node object), so the steady state
        stays on the delta path.

        Coupled with ops.device_state.DeviceStateHolder.apply_rows: the
        rows this method rewrites host-side are exactly the rows the
        device holder scatter-updates (analysis/coupling.py
        "delta-row-scatter" group)."""
        schema = self.schema
        rewritten: list = []
        req_memo = self._req_row_memo
        for i, n in enumerate(nodes):
            if (n.metadata.name, n.metadata.resource_version) != self._alloc_keys[i]:
                raise self._SchemaMiss
            d = req_dicts[i]
            if d != self._req_dicts[i]:
                key = tuple(sorted(d.items()))
                row = req_memo.get(key)
                if row is None:
                    if not schema.covers([d]):
                        raise self._SchemaMiss
                    row = schema.pack(d)
                    req_memo[key] = row
                self._requested[i] = row
                self._req_dicts[i] = dict(d)
                rewritten.append(i)
        return rewritten

    def _group_rows(self, groups) -> np.ndarray:
        """Demand rows from the persistent memo: membership churns freely
        and a memo hit is one O(R) copy. Raises _SchemaMiss on a demand
        the cached schema cannot pack exactly."""
        schema = self.schema
        memo = self._group_row_memo
        out = np.empty((len(groups), schema.num_lanes), np.int32)
        for gi, g in enumerate(groups):
            key = tuple(sorted(g.member_request.items()))
            row = memo.get(key)
            if row is None:
                d = _member_request_row(g)
                if not schema.covers([d]):
                    raise self._SchemaMiss
                row = schema.pack(d)
                memo[key] = row
            out[gi] = row
        return out

    # -- snapshot-lite (docs/pipelining.md "Snapshot-lite & event ingest") --

    class _LiteBail(Exception):
        """A churned group broke a lite invariant (selector appeared,
        value out of the pad_oracle_batch bounds): fall back to the full
        construction path — which rebuilds the fit mask, or raises the
        canonical OverflowError — never a silent clamp. Raised ONLY from
        the two-phase planner's validate pass, so a bail leaves the lite
        buffers untouched."""

    def _capture_lite(self, snap: ClusterSnapshot, nodes, groups) -> None:
        """Adopt a freshly built full ClusterSnapshot as the persistent
        lite working set (and stamp its queue-order meta columns for the
        device-derive path). Eligibility: knob on, policy off, and the
        uniform-fit fast path — no selectors, no taints — so the padded
        fit row IS node_valid and churned groups cannot change it."""
        self._lite = None
        if not snapshot_lite_enabled():
            return
        engine = self.policy_engine
        if engine is not None and getattr(engine, "enabled", False):
            return
        if snap.fit_mask.shape[0] != 1:
            return
        if any(g.node_selector for g in groups) or any(
            n.spec.taints for n in nodes
        ):
            return
        for g in groups:
            # the device sort key is int32: a priority outside its domain
            # cannot round-trip through ~p (host sort uses Python ints)
            if not (-(2**31) <= g.priority < 2**31):
                return
        n, g_count = snap.num_nodes, snap.num_groups
        nb, gb = snap.alloc.shape[0], snap.group_req.shape[0]
        from .oracle import GANG_MAX

        # padded queue-order meta: pad sentinels sort strictly AFTER every
        # real row (pad ts_hi = INT32_MAX exceeds any finite double's
        # biased hi half) and name_rank = the row index keeps the pad tail
        # in arange(g, gb) order — a full-[Gb] static lexsort reproduces
        # pad_oracle_batch's order column exactly, no dynamic g argument
        prio = np.array([d.priority for d in groups], dtype=np.int64)
        ts_hi_r, ts_lo_r = _ts_sort_keys(
            np.array([d.creation_ts for d in groups], dtype=np.float64)
        )
        rank = np.empty(g_count, dtype=np.int32)
        rank[
            sorted(range(g_count), key=lambda i: groups[i].full_name)
        ] = np.arange(g_count, dtype=np.int32)
        inv_prio = np.full(gb, _I32_MAX, dtype=np.int32)
        inv_prio[:g_count] = ~prio.astype(np.int32)
        ts_hi = np.full(gb, _I32_MAX, dtype=np.int32)
        ts_hi[:g_count] = ts_hi_r
        ts_lo = np.full(gb, _I32_MAX, dtype=np.int32)
        ts_lo[:g_count] = ts_lo_r
        name_rank = np.arange(gb, dtype=np.int32)
        name_rank[:g_count] = rank

        lite = _LiteState(
            n=n,
            g=g_count,
            nb=nb,
            gb=gb,
            node_names=tuple(snap.node_names),
            group_names=tuple(snap.group_names),
            node_index=snap._node_index,
            group_index=snap._group_index,
            node_names_list=snap.node_names,
            group_names_list=snap.group_names,
            demands=list(groups),
            fps=[_demand_fp(d) for d in groups],
            gang_bound=min(GANG_MAX, (2**31 - 1) // nb),
            pad_alloc=snap.alloc,
            pad_requested=np.array(snap.requested),
            pad_group_req=np.array(snap.group_req),
            remaining=np.array(snap.remaining),
            min_member=np.array(snap.min_member),
            scheduled=np.array(snap.scheduled),
            matched=np.array(snap.matched),
            ineligible=np.array(snap.ineligible),
            fit_row=snap.fit_mask,
            node_valid=snap.node_valid,
            group_valid=snap.group_valid,
            order=snap.order,
            creation_rank=snap.creation_rank,
            meta=(inv_prio, ts_hi, ts_lo, name_rank),
        )
        self._lite = lite
        # rebind the packer's working arrays as VIEWS into the padded
        # buffers: _delta_rows keeps its exact body (coupled formula) and
        # its writes land directly in padded space — padding appends, so
        # unpadded indices are valid there
        self._requested = lite.pad_requested[:n]
        self._group_prev = lite.pad_group_req[:g_count]
        snap.meta_cols = lite.meta
        # audit v2 re-fold base (utils.audit): the per-gang demand
        # fingerprints a keyframe record must carry so the replayer can
        # prime this exact lite state and re-run recorded event folds.
        # A shallow list copy — fp tuples are immutable, and later
        # in-place `fps[gi] = ...` updates must not leak into the record
        snap.lite_fps = list(lite.fps)

    def _plan_group_change(self, gi: int, old_fp: tuple, g: GroupDemand):
        """Validate-only half of a lite group update: returns None when
        nothing oracle-visible changed, else the planned write. Diffs the
        fresh demand against the CAPTURED fingerprint, not the stored
        object — callers may have mutated the same GroupDemand in place,
        which would make an attribute compare vacuous. Raises _SchemaMiss
        (covers miss → keyframe like _group_rows) or _LiteBail
        (invariant/bound break → full path). MUST NOT mutate lite state —
        a bail after partial writes would tear the positional diff the
        delta consumers scatter from."""
        fp = _demand_fp(g)
        row = None
        if fp[0] != old_fp[0]:
            row = self._group_row_memo.get(fp[0])
            if row is None:
                d = _member_request_row(g)
                if not self.schema.covers([d]):
                    raise self._SchemaMiss
                row = self.schema.pack(d)
                self._group_row_memo[fp[0]] = row
        tail = None
        if fp[1:4] != old_fp[1:4]:
            from .oracle import GANG_MAX

            # mirror pad_oracle_batch's progress bounds: a violating value
            # must surface as ITS OverflowError via the full path
            if (
                max(abs(g.min_member), abs(g.scheduled), abs(g.matched))
                > GANG_MAX
                or g.remaining > self._lite.gang_bound
            ):
                raise self._LiteBail
            tail = (g.min_member, g.scheduled, g.matched, g.remaining)
        inel = bool(g.released or not g.has_pod)
        inel_changed = fp[6:8] != old_fp[6:8]
        meta_changed = fp[4:6] != old_fp[4:6]
        if meta_changed and not (-(2**31) <= g.priority < 2**31):
            raise self._LiteBail
        if g.node_selector:
            raise self._LiteBail  # uniform-fit invariant broke
        if row is None and tail is None and not inel_changed and not meta_changed:
            return None
        return (gi, g, fp, row, tail, inel_changed, inel, meta_changed)

    def _apply_group_changes(self, changes) -> tuple:
        """Apply planned group updates to the lite buffers; resort the
        queue order when any sort key churned. Returns (group_rows,
        meta_rows) index lists for the delta record."""
        lite = self._lite
        group_rows: list = []
        meta_rows: list = []
        for gi, g, fp, row, tail, inel_changed, inel, meta_changed in changes:
            if row is not None:
                lite.pad_group_req[gi] = row
                group_rows.append(gi)
            if tail is not None:
                mm, sc, ma, rem = tail
                lite.min_member[gi] = mm
                lite.scheduled[gi] = sc
                lite.matched[gi] = ma
                lite.remaining[gi] = rem
            if inel_changed:
                lite.ineligible[gi] = inel
            if meta_changed:
                meta_rows.append(gi)
            lite.demands[gi] = g
            lite.fps[gi] = fp
        if meta_rows:
            self._lite_resort()
        return group_rows, meta_rows

    def _lite_resort(self) -> None:
        """A queue sort key (priority / creation_ts) churned: rebuild the
        order permutation, creation ranks and meta columns WHOLESALE
        (replaced, never mutated — emitted snapshots share the old
        arrays). The O(G log G) host sort stays authoritative for audit
        and explain; the device derives the same permutation from the
        meta columns (byte-equal by construction, ops.device_state)."""
        lite = self._lite
        g, gb = lite.g, lite.gb
        demands = lite.demands
        order_host = sorted(
            range(g),
            key=lambda i: (
                -demands[i].priority,
                demands[i].creation_ts,
                demands[i].full_name,
            ),
        )
        ranks = np.empty(g, dtype=np.int32)
        ranks[order_host] = np.arange(g, dtype=np.int32)
        order = np.concatenate(
            [
                np.asarray(order_host, dtype=np.int32),
                np.arange(g, gb, dtype=np.int32),
            ]
        )
        creation_rank = np.full(gb, gb - 1, dtype=np.int32)
        creation_rank[:g] = ranks
        prio = np.array([d.priority for d in demands], dtype=np.int64)
        ts_hi_r, ts_lo_r = _ts_sort_keys(
            np.array([d.creation_ts for d in demands], dtype=np.float64)
        )
        inv_prio = np.full(gb, _I32_MAX, dtype=np.int32)
        inv_prio[:g] = ~prio.astype(np.int32)
        ts_hi = np.full(gb, _I32_MAX, dtype=np.int32)
        ts_hi[:g] = ts_hi_r
        ts_lo = np.full(gb, _I32_MAX, dtype=np.int32)
        ts_lo[:g] = ts_lo_r
        lite.order = order
        lite.creation_rank = creation_rank
        lite.meta = (inv_prio, ts_hi, ts_lo, lite.meta[3])
        self.order_resorts += 1
        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_refresh_order_resorts_total",
            "Queue-order resorts forced by priority/creation-ts churn on "
            "the snapshot-lite path",
        ).inc()

    def _lite_snapshot(self, delta: SnapshotDelta) -> ClusterSnapshot:
        """Materialise a ClusterSnapshot from the lite working set without
        running __init__: in-place-mutated buffers are copied (audit holds
        snapshot arrays by reference), wholesale-replaced and immutable
        ones are shared (core.explain.baseline_inputs_key hashes VALUES,
        so sharing is observationally safe)."""
        lite = self._lite
        snap = ClusterSnapshot.__new__(ClusterSnapshot)
        snap.node_names = lite.node_names_list
        snap.group_names = lite.group_names_list
        snap.groups = list(lite.demands)
        snap._node_index = lite.node_index
        snap._group_index = lite.group_index
        snap.schema = self.schema
        snap.num_nodes = lite.n
        snap.num_groups = lite.g
        snap.alloc = lite.pad_alloc
        snap.requested = lite.pad_requested.copy()
        snap.group_req = lite.pad_group_req.copy()
        snap.remaining = lite.remaining.copy()
        snap.fit_mask = lite.fit_row
        snap.group_valid = lite.group_valid
        snap.order = lite.order
        snap.min_member = lite.min_member.copy()
        snap.scheduled = lite.scheduled.copy()
        snap.matched = lite.matched.copy()
        snap.ineligible = lite.ineligible.copy()
        snap.creation_rank = lite.creation_rank
        snap.node_valid = lite.node_valid
        snap.policy_engine = None
        snap.policy_cols = None
        snap.meta_cols = lite.meta
        snap.delta = delta
        # audit v2 re-fold base — see _capture_lite
        snap.lite_fps = list(lite.fps)
        return snap

    def _lite_emit(
        self, node_rows, group_rows, meta_rows, source: str, path: str
    ) -> ClusterSnapshot:
        self.generation += 1
        delta = SnapshotDelta(
            self.generation,
            "delta",
            node_rows=np.asarray(node_rows, dtype=np.int32),
            group_rows=np.asarray(group_rows, dtype=np.int32),
            source=source,
            meta_rows=np.asarray(meta_rows, dtype=np.int32),
        )
        self.last_delta = delta
        self.delta_packs += 1
        self.last_rows_rewritten = len(node_rows)
        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_pack_rows_rewritten",
            "Node lane rows rewritten by the delta snapshot packer "
            "(2N on a full repack)",
        ).inc(self.last_rows_rewritten)
        DEFAULT_REGISTRY.counter(
            "bst_refresh_lite_packs_total",
            "Snapshot-lite packs that skipped the full ClusterSnapshot "
            "construction, by refresh path (scan | fold)",
        ).inc(path=path)
        return self._lite_snapshot(delta)

    def _lite_delta_pack(self, groups, node_idx) -> Optional[ClusterSnapshot]:
        """Lite scan pack: the node side is already rewritten in place
        (_delta_rows writes through the padded-buffer view); one O(G)
        compare over the demand list plans the group side. Returns the
        emitted snapshot, or None to fall back to the full construction
        path — the planner is two-phase, so a bail leaves the buffers
        exactly as the previous pack published them. Raises _SchemaMiss
        exactly like _group_rows (caller keyframes "node-churn")."""
        if self.policy_engine is not None and getattr(
            self.policy_engine, "enabled", False
        ):
            return None
        lite = self._lite
        try:
            changes = []
            for gi, (old_fp, g) in enumerate(zip(lite.fps, groups)):
                c = self._plan_group_change(gi, old_fp, g)
                if c is not None:
                    changes.append(c)
        except self._LiteBail:
            return None
        group_rows, meta_rows = self._apply_group_changes(changes)
        self.lite_packs += 1
        return self._lite_emit(node_idx, group_rows, meta_rows, "scan", "scan")

    def pack_fold(
        self, node_updates, group_updates
    ) -> Optional[ClusterSnapshot]:
        """O(churn) event-fold pack (stage 3 of "Kill the snapshot"):
        rewrite ONLY the named entities — nothing else is read, which is
        the whole point. Caller contract (core.oracle_scorer._try_fold):
        the node list, gang set and every unnamed entity's state are
        UNCHANGED since the last pack — proven by the event log's
        version-bump accounting and the status cache's mutation counter,
        never assumed. Returns None when the fold does not apply (no lite
        state, unknown name, schema covers miss, bound violation): the
        caller falls back to the full scan ``pack()``, which is always
        correct.

        ``node_updates``: iterable of ``(node_name, requested_dict)``
        (fresh ``cluster.node_requested`` reads);
        ``group_updates``: iterable of fresh ``GroupDemand`` reads for
        the named gangs. The fold is idempotent — updates carry current
        state, not event payloads, so a name folded twice converges."""
        lite = self._lite
        if lite is None or self.schema is None or not snapshot_lite_enabled():
            return None
        if self.policy_engine is not None and getattr(
            self.policy_engine, "enabled", False
        ):
            return None
        schema = self.schema
        node_plan: list = []
        try:
            for name, d in node_updates:
                i = lite.node_index.get(name)
                if i is None:
                    return None
                if d == self._req_dicts[i]:
                    continue
                key = tuple(sorted(d.items()))
                row = self._req_row_memo.get(key)
                if row is None:
                    if not schema.covers([d]):
                        return None
                    row = schema.pack(d)
                    self._req_row_memo[key] = row
                node_plan.append((i, row, dict(d)))
            changes = []
            for g in group_updates:
                gi = lite.group_index.get(g.full_name)
                if gi is None:
                    return None
                c = self._plan_group_change(gi, lite.fps[gi], g)
                if c is not None:
                    changes.append(c)
        except (self._SchemaMiss, self._LiteBail):
            return None
        node_rows: list = []
        for i, row, d in node_plan:
            # writes through the same padded buffer _delta_rows targets
            # (self._requested is its [:n] view) — the delta-row-scatter
            # coupling sees identical values either way
            lite.pad_requested[i] = row
            self._req_dicts[i] = d
            node_rows.append(i)
        group_rows, meta_rows = self._apply_group_changes(changes)
        self.fold_packs += 1
        return self._lite_emit(node_rows, group_rows, meta_rows, "events", "fold")

    def _policy_node_rows(self, nodes) -> Optional[tuple]:
        """Persistent node policy columns: rewrite only rows whose LABELS
        changed (spread key included — it lives in the labels). Returns
        (hash[N, H], dom[N]) copies for the snapshot, or None when no
        enabled engine is attached."""
        engine = self.policy_engine
        if engine is None or not engine.enabled:
            return None
        from ..policy.terms import node_policy_row

        spread_key = engine.config.spread_node_key
        lanes = _policy_hash_lanes()
        n = len(nodes)
        if (
            self._policy_hash is None
            or self._policy_hash.shape != (n, lanes)
        ):
            self._policy_hash = np.zeros((n, lanes), np.int32)
            self._policy_dom = np.zeros(n, np.int32)
            self._policy_labels = [None] * n
        rewritten = 0
        truncated = 0
        rewritten_idx: list = []
        for i, node in enumerate(nodes):
            labels = node.metadata.labels or {}
            key = tuple(sorted(labels.items()))
            if self._policy_labels[i] == key:
                continue
            row, dom, trunc = node_policy_row(labels, spread_key)
            self._policy_hash[i] = row
            self._policy_dom[i] = dom
            self._policy_labels[i] = key
            rewritten += 1
            rewritten_idx.append(i)
            truncated += trunc
        self.policy_rows_rewritten = rewritten
        self._policy_rows_idx = rewritten_idx
        from ..utils.metrics import DEFAULT_REGISTRY

        if rewritten:
            DEFAULT_REGISTRY.counter(
                "bst_pack_policy_rows_rewritten",
                "Node policy (label-hash/spread-domain) rows rewritten by "
                "the delta snapshot packer",
            ).inc(rewritten)
        if truncated:
            DEFAULT_REGISTRY.counter(
                "bst_policy_label_truncations_total",
                "Node labels beyond the packed hash lanes "
                "(affinity against them can never match)",
            ).inc(truncated)
        return self._policy_hash.copy(), self._policy_dom.copy()

    def pack(
        self,
        nodes: Sequence[Node],
        node_requested: Dict[str, Dict[str, int]],
        groups: Sequence[GroupDemand],
    ) -> ClusterSnapshot:
        """Build one ClusterSnapshot, rewriting only churned rows when the
        cached schema and node list still hold."""
        alloc_dicts = [n.status.allocatable for n in nodes]
        req_dicts = [node_requested.get(n.metadata.name, {}) for n in nodes]
        names = tuple(n.metadata.name for n in nodes)
        group_names = tuple(g.full_name for g in groups)

        if names != self._node_names:
            # node list changed: the policy row cache is positionally keyed
            self._policy_hash = None

        had_prev = self._alloc is not None
        keyframe_reason = None
        node_idx: list = []
        group_req = None
        if had_prev and names == self._node_names:
            try:
                node_idx = self._delta_rows(nodes, req_dicts)
                # snapshot-lite fast path: positionally-stable node list
                # AND gang set, uniform fit, policy off — emit straight
                # from the persistent padded working set (no pad copies,
                # no fit scan, no sort; docs/pipelining.md)
                if (
                    self._lite is not None
                    and snapshot_lite_enabled()
                    and group_names == self._lite.group_names
                ):
                    snap = self._lite_delta_pack(groups, node_idx)
                    if snap is not None:
                        return snap
                group_req = self._group_rows(groups)
                self.delta_packs += 1
                self.last_rows_rewritten = len(node_idx)
            except self._SchemaMiss:
                group_req = None
                keyframe_reason = "node-churn"
        elif had_prev:
            keyframe_reason = "node-list"
        else:
            keyframe_reason = "first"
        if group_req is None:
            self._full_repack(nodes, alloc_dicts, req_dicts, groups)
            group_req = self._group_rows(groups)

        # group-side churn: with the group NAME SET stable, row indices are
        # positional and the per-row diff against the previous pack is the
        # scatter list; a changed set invalidates positional indices (and
        # the lane-side delta stays host-valid — only CONSUMERS of the
        # record must resync from a keyframe)
        group_idx: list = []
        if keyframe_reason is None:
            if (
                group_names != self._group_names
                or self._group_prev is None
                or self._group_prev.shape != group_req.shape
            ):
                keyframe_reason = "group-set"
            elif len(groups):
                group_idx = np.nonzero(
                    (group_req != self._group_prev).any(axis=1)
                )[0].tolist()
        self._group_names = group_names
        self._group_prev = group_req  # read-only on both sides; no copy

        node_policy = self._policy_node_rows(nodes)
        self.generation += 1
        if keyframe_reason is None:
            delta = SnapshotDelta(
                self.generation,
                "delta",
                node_rows=np.asarray(node_idx, np.int32),
                group_rows=np.asarray(group_idx, np.int32),
                policy_node_rows=np.asarray(self._policy_rows_idx, np.int32),
            )
        else:
            delta = SnapshotDelta(
                self.generation, "keyframe", reason=keyframe_reason
            )
        self.last_delta = delta

        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_pack_rows_rewritten",
            "Node lane rows rewritten by the delta snapshot packer "
            "(2N on a full repack)",
        ).inc(self.last_rows_rewritten)
        snap = ClusterSnapshot(
            nodes,
            node_requested,
            groups,
            schema=self.schema,
            alloc_lanes=self._alloc.copy(),
            requested_lanes=self._requested,  # ClusterSnapshot copies
            group_req_lanes=group_req,  # freshly allocated per pack
            policy_engine=self.policy_engine,
            node_policy_lanes=node_policy,
        )
        snap.delta = delta
        # every full construction re-captures (or drops) the lite working
        # set — keyframes and legacy deltas both leave it coherent
        self._capture_lite(snap, nodes, groups)
        return snap
