from .bucketing import bucket_size, pad_rows, pad_to
from .lanes import CORE_LANES, INT32_MAX, LaneSchema
from .oracle import (
    assign_gangs,
    find_max_group,
    gang_feasible,
    group_capacity,
    left_resources,
    schedule_batch,
    score_nodes,
)
from .rescore import ChurnRescorer, TickPipeline, probe_link_depth
from .snapshot import ClusterSnapshot, GroupDemand, node_requested_from_pods

__all__ = [
    "bucket_size",
    "pad_rows",
    "pad_to",
    "CORE_LANES",
    "INT32_MAX",
    "LaneSchema",
    "assign_gangs",
    "find_max_group",
    "gang_feasible",
    "group_capacity",
    "left_resources",
    "schedule_batch",
    "score_nodes",
    "ClusterSnapshot",
    "GroupDemand",
    "node_requested_from_pods",
    "ChurnRescorer",
    "TickPipeline",
    "probe_link_depth",
]
