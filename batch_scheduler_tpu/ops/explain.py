"""Gang denial explanation: the jit'd kernel behind /debug/explain.

The oracle already scores every gang x every node per batch, but when a
gang sits Pending the control plane surfaces one sentence
(``ResourceNotEnoughError``) and a feasible-node count. This kernel turns
the same device-resident ``[N, R]`` / ``[G, R]`` buffers into a structured
denial breakdown for ONE gang:

- **entry-leftover capture**: the serial assignment scan re-runs with the
  carried leftover CAPTURED at the target gang's step, so the explanation
  distinguishes "infeasible even alone" (independent capacity, what
  PreFilter's ``cluster cannot fit gang`` means) from "feasible alone but
  consumed by earlier gangs" (entry capacity, the ``reserved for earlier
  gangs`` denial). The scan body calls the SAME ``_member_capacity`` /
  ``_select_best_fit`` helpers as ``assign_gangs`` — the captured leftover
  is exactly what the serving scan carried, on every rung (all rungs are
  bit-identical to the serial scan by construction).
- **per-lane blame**: per-node one-member deficits
  (``max(req - left, 0)`` on demanded lanes), and the binding lane — for
  each capacity-blocked node, the lane whose per-lane fit is smallest;
  the histogram over lanes names the resource that blocks the most nodes.
- **exclusion split**: nodes excluded by the hard fit mask
  (selector/taints/cordon), by a hard policy mask (anti-affinity — the
  policy variant), and by capacity, counted separately over REAL (unpadded)
  nodes.
- **near-miss nodes**: the top-K nodes ranked best capacity first, then
  smallest total deficit — where an operator (or the what-if engine)
  should look first.

The policy variant mirrors ``assign_gangs_policy``'s composite scan body
(penalty shift + keep mask) so explanations of policy-rung batches see the
same entry leftovers the serving scan produced.

Host-side assembly (names, flight-recorder cross-stamp, policy term blame,
preemption candidacy) lives in ``core.explain``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .oracle import (
    _BIG,
    _BINS,
    _exact_floordiv,
    _member_capacity,
    _select_best_fit,
    left_resources,
)

__all__ = ["explain_gang", "NEAR_MISS_K"]

# How many near-miss nodes the kernel ranks and returns per query. Static
# (one jit signature), small (the payload is human-facing), and far below
# every node bucket's floor.
NEAR_MISS_K = 8


def _scan_take(left, req, mask, need, pen_keep):
    """One serial-scan step's take vector against carried ``left`` — the
    EXACT body of ops.oracle.assign_gangs (and, with ``pen_keep``, of
    assign_gangs_policy): same helpers, same composite-key clipping, so
    the captured entry leftover is bit-identical to what the serving scan
    carried. Change those bodies and this one together."""
    if pen_keep is None:
        cap = _member_capacity(left, req[None, :]) * mask
        capc = jnp.minimum(cap, need)
        take2d, _ = _select_best_fit(cap[None, :], capc[None, :], need)
    else:
        pen, keep = pen_keep
        cap = _member_capacity(left, req[None, :]) * mask * keep
        capc = jnp.minimum(cap, need)
        base = jnp.minimum(cap, _BINS - 1)
        key = jnp.where(cap > 0, jnp.clip(base + pen, 1, _BINS - 1), 0)
        take2d, _ = _select_best_fit(
            cap[None, :], capc[None, :], need, key=key[None, :]
        )
    return take2d[0]


@partial(jax.jit, static_argnames=("policy_terms", "policy_weights"))
def explain_gang(alloc, requested, group_req, remaining, fit_mask,
                 group_valid, order, g, n_real, policy_cols=None,
                 policy_terms: tuple = (), policy_weights: tuple = ()):
    """Structured denial breakdown for gang index ``g`` of one batch.

    Inputs are the canonical padded 7-tuple (ops.bucketing.pad_oracle_batch
    order) splatted, plus the gang index, the REAL node count (padded rows
    are excluded from every count), and optionally the packed policy
    columns + static term config (the policy-rung composite). Returns a
    dict of device arrays; see core.explain for the host assembly.
    """
    policy_on = policy_cols is not None and bool(policy_terms)
    pen_fn = None
    if policy_on:
        from ..policy.terms import compose_terms

        prio, aff, anti, gang_dom, node_hash, node_dom = policy_cols
        pen_fn = compose_terms(policy_terms, policy_weights)

    left0 = left_resources(alloc, requested)
    n = left0.shape[0]
    mask_rows = fit_mask.shape[0]

    def gang_pen_keep(gi):
        if not policy_on:
            return None
        return pen_fn(
            jnp.take(aff, gi), jnp.take(anti, gi),
            jnp.take(gang_dom, gi, axis=0), node_hash, node_dom,
        )

    def body(carry, gi):
        left, captured = carry
        req = jnp.take(group_req, gi, axis=0)
        mask = jnp.take(
            fit_mask, jnp.minimum(gi, mask_rows - 1), axis=0
        ).astype(jnp.int32)
        need = jnp.take(remaining, gi)
        captured = jnp.where(gi == g, left, captured)
        take = _scan_take(left, req, mask, need, gang_pen_keep(gi))
        return (left - take[:, None] * req[None, :], captured), None

    (left_fin, left_entry), _ = jax.lax.scan(
        body, (left0, left0), order, unroll=4
    )

    # -- the target gang's view at its scan entry (and independently) ------
    req = jnp.take(group_req, g, axis=0)
    mask = jnp.take(
        fit_mask, jnp.minimum(g, mask_rows - 1), axis=0
    ).astype(jnp.int32)
    need = jnp.take(remaining, g)
    real = jax.lax.broadcasted_iota(jnp.int32, (n,), 0) < n_real
    if policy_on:
        pen, keep = gang_pen_keep(g)
        keep = keep.astype(jnp.int32)
    else:
        pen = jnp.zeros((n,), jnp.int32)
        keep = jnp.ones((n,), jnp.int32)
    maskk = mask * keep
    cap_entry = _member_capacity(left_entry, req[None, :]) * maskk
    cap_indep = _member_capacity(left0, req[None, :]) * maskk

    # per-lane one-member deficits + the binding lane per blocked node
    safe_req = jnp.clip(req, 1, _BIG)
    lpos = jnp.clip(left_entry, 0, _BIG)
    per_lane = jnp.where(
        req[None, :] > 0, _exact_floordiv(lpos, safe_req[None, :]), _BIG
    )  # [N, R] members each lane alone would allow
    deficit = jnp.where(
        req[None, :] > 0, jnp.clip(req[None, :] - left_entry, 0, _BIG), 0
    )  # [N, R] shortfall to fit ONE member
    block_lane = jnp.argmin(per_lane, axis=1)  # [N] tightest demanded lane
    blocked = (real & (maskk > 0) & (cap_entry == 0)).astype(jnp.int32)
    lanes = req.shape[0]
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (lanes,), 0)
    binding_counts = jnp.sum(
        blocked[:, None] * (block_lane[:, None] == lane_iota[None, :]),
        axis=0,
    )  # [R] blocked nodes per binding lane

    masked_out = jnp.sum((real & (mask == 0)).astype(jnp.int32))
    policy_masked = jnp.sum(
        (real & (mask > 0) & (keep == 0)).astype(jnp.int32)
    )
    capacity_blocked = jnp.sum(blocked)
    nodes_entry = jnp.sum((real & (cap_entry > 0)).astype(jnp.int32))
    nodes_indep = jnp.sum((real & (cap_indep > 0)).astype(jnp.int32))
    feasible_entry = jnp.sum(jnp.minimum(cap_entry, need) * real) >= need
    feasible_indep = jnp.sum(jnp.minimum(cap_indep, need) * real) >= need

    # near-miss ranking: best entry capacity first, then smallest total
    # deficit. The composite stays inside int32: the capacity term is
    # bucket-clipped (< 2**7) * 2**23 and the deficit term < 2**22.
    total_deficit = jnp.sum(jnp.minimum(deficit, 2**18), axis=1)
    score = jnp.where(
        real & (maskk > 0),
        jnp.minimum(cap_entry, _BINS - 1) * (2**23)
        - jnp.minimum(total_deficit, 2**22 - 1),
        -(2**30),
    )
    k = min(NEAR_MISS_K, n)
    _, near_idx = jax.lax.top_k(score, k)
    near_cap = jnp.take(cap_entry, near_idx)
    near_cap_indep = jnp.take(cap_indep, near_idx)
    near_deficit = jnp.take(deficit, near_idx, axis=0)  # [K, R]
    near_left = jnp.take(jnp.clip(left_entry, 0, _BIG), near_idx, axis=0)
    near_pen = jnp.take(pen, near_idx)

    # per-lane cluster headroom (device units, float to dodge the int32
    # 5k-node sum overflow): at the gang's entry and after the full batch
    realf = real.astype(jnp.float32)[:, None]
    headroom_entry = jnp.sum(
        jnp.clip(left_entry, 0, _BIG).astype(jnp.float32) * realf, axis=0
    )
    headroom_after = jnp.sum(
        jnp.clip(left_fin, 0, _BIG).astype(jnp.float32) * realf, axis=0
    )

    return {
        "need": need,
        "feasible_entry": feasible_entry,
        "feasible_indep": feasible_indep,
        "nodes_entry": nodes_entry,
        "nodes_indep": nodes_indep,
        "masked_out": masked_out,
        "policy_masked": policy_masked,
        "capacity_blocked": capacity_blocked,
        "binding_counts": binding_counts,
        "near_idx": near_idx,
        "near_cap": near_cap,
        "near_cap_indep": near_cap_indep,
        "near_deficit": near_deficit,
        "near_left": near_left,
        "near_pen": near_pen,
        "headroom_entry": headroom_entry,
        "headroom_after": headroom_after,
    }
