"""The bin-packing oracle: jitted JAX kernels scoring all PodGroups × all
nodes in one batch.

This replaces the reference's two serial hot loops — per-pod cluster
feasibility (``findMaxPG`` + ``compareClusterResourceAndRequire``, reference
pkg/scheduler/core/core.go:595-632,701-739) and per-node fit
(``singleNodeResource`` + ``compareResourceAndRequire``, core.go:634-699) —
with dense int32 tensor kernels:

- ``left_resources``      per-node leftover = floor(alloc·percent) − requested
- ``group_capacity``      members-per-node capacity matrix cap[G,N]
- ``gang_feasible``       Σ_n cap[g,n] ≥ remaining[g]  (exact, in member
                          counts, so 5k-node sums stay far inside int32 —
                          and *stronger* than the reference's raw resource-sum
                          check, which ignores per-node fragmentation)
- ``find_max_group``      vectorized group-progress argmax (findMaxPG parity)
- ``score_nodes``         per-(group,node) placement ranks for the Score
                          extension point (a stub in the reference,
                          core.go:263-265)
- ``assign_gangs``        greedy whole-batch gang placement via ``lax.scan``
                          over groups in priority order

All kernels take statically-bucketed shapes (see ops.bucketing) and int32
lanes (see ops.lanes); invalid rows are masked, never branched on, so there
is no data-dependent Python control flow under jit.

Determinism note: the reference's findMaxPG tie-break depends on Go map
iteration order, which is randomised (core.go:701-739). ``find_max_group``
resolves ties deterministically: prefer groups with nothing scheduled yet
(same intent as core.go:725-735), then earlier creation rank.
"""

from __future__ import annotations

import os
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "left_resources",
    "group_capacity",
    "gang_feasible",
    "find_max_group",
    "find_max_group_host",
    "repack_assignment_span",
    "score_nodes",
    "assign_gangs",
    "assign_gangs_policy",
    "assign_gangs_wavefront",
    "assign_gangs_sharded",
    "assign_gangs_topk",
    "assign_gangs_topk_sharded",
    "scan_sharded_active",
    "scan_topk_active",
    "schedule_batch",
    "execute_batch_host",
    "dispatch_batch",
    "collect_batch",
    "donation_supported",
    "PendingBatch",
    "forced_scan_rung",
    "bucket_cost_report",
    "bucket_cost_for",
    "drain_telemetry_threads",
]

# Plain int (not a device array) so pallas kernels can share these helpers
# without capturing traced constants.
_BIG = 2**30

# Largest admissible gang: keeps every need-clipped capacity cumsum in the
# assignment scan exact in int32 (bound proven in assign_gangs' docstring).
# Enforced at the batch boundary (ops.bucketing.pad_oracle_batch).
GANG_MAX = 2**18

# Best-fit ranking buckets for the gang-placement scan. Nodes are ranked
# tightest-first by min(cap, _BINS-1); all nodes that could hold >= _BINS-1
# members of a gang are equally "loose" and tie-break by node index. 128
# covers every realistic per-node member count (the pods lane alone caps a
# node at ~110 members) while keeping the per-step histogram tiny.
_BINS = 128

# Process-wide gate for the fused pallas assignment kernel; flipped off on
# the first hardware failure (see execute_batch_host) or via env var.
# Pallas enablement is PER MASK MODE: a lowering/runtime failure on one
# kernel variant (e.g. the per-group [G,N] mask path) disables only that
# variant — it must not poison the other, independently proven one.
# Read/written from multiple threads (background refresh + scheduling
# cycles) without a lock: a benign race — the worst interleaving runs one
# extra fallback batch and prints a duplicate warning (ADVICE r3); do not
# add invariants here that assume single-threaded access.
_pallas_enabled = {
    mode: os.environ.get("BST_DISABLE_PALLAS", "") != "1"
    for mode in ("broadcast", "per_group")
}

# Thread-local scan-rung pin for deterministic replay
# (core.oracle_scorer.replay_batch) and the in-production identity audit
# (utils.health.IdentityAuditor): forces dispatch_batch onto an explicit
# (use_pallas, scan_wave) rung FOR THE CURRENT THREAD without touching the
# process-wide gates above — a replay exercising one rung must never
# change which rung concurrent serving batches run on, and a replay
# failure must never permanently demote the serving path (the ladder's
# disable-on-failure policy is skipped while pinned).
_rung_override = threading.local()


class forced_scan_rung:
    """Context manager pinning this thread's batches to one scan rung.

    ``scan_topk`` > 0 pins the hierarchical top-K rung
    (``assign_gangs_topk``) at that candidate width — single-process only,
    like every pin; the sharded mesh variants are never pinned (their
    recorded batches are verified by CROSS-rung replay identity)."""

    def __init__(self, use_pallas: bool, scan_wave: int, scan_topk: int = 0):
        self._rung = (bool(use_pallas), int(scan_wave), int(scan_topk))

    def __enter__(self):
        self._prev = getattr(_rung_override, "value", None)
        _rung_override.value = self._rung
        return self

    def __exit__(self, *exc):
        _rung_override.value = self._prev
        return False


@jax.jit
def _exact_floordiv(num, den):
    """Exact ``num // den`` for int32 ``0 <= num <= 2**30, 1 <= den <= 2**30``.

    XLA lowers int32 division on TPU to a long scalar expansion; over the
    oracle's (G,N,R) tensor that one op dominates the whole batch. Instead:
    two float32 reciprocal-multiply Newton steps, then an integer fixup.
    Error analysis: the first quotient is within ``0.5 + q*2**-22`` of exact,
    so the int32 residual ``num - q*den`` never overflows given the 2**30
    operand bound (enforced at pack time, ops.lanes.LANE_MAX); the second
    step lands within 1, and the fixups make it exact.
    """
    inv = 1.0 / den.astype(jnp.float32)
    q = jnp.round(num.astype(jnp.float32) * inv).astype(jnp.int32)
    r = num - q * den
    q = q + jnp.round(r.astype(jnp.float32) * inv).astype(jnp.int32)
    r = num - q * den
    q = jnp.where(r < 0, q - 1, q)
    q = jnp.where(num - q * den >= den, q + 1, q)
    return q


def _cumsum(x, axis):
    """Inclusive cumsum via Hillis-Steele doubling (log2(n) shift-adds).

    ``jnp.cumsum`` has no Pallas TPU (Mosaic) lowering; static pad/slice/add
    do. Used by ``_select_best_fit`` on BOTH the lax.scan and pallas paths so
    the two stay bit-identical (int32 addition is associative, so the
    doubling order changes nothing).
    """
    n = x.shape[axis]
    shift = 1
    while shift < n:
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, shift, axis=axis))
        shifted = jax.lax.concatenate(
            [zeros, jax.lax.slice_in_dim(x, 0, n - shift, axis=axis)], axis
        )
        x = x + shifted
        shift *= 2
    return x


def _select_best_fit(cap, capc, need, key=None):
    """Tightest-first take vector for one gang: the histogram threshold
    selection documented in assign_gangs. Shapes are [1, N] (2-D so the iota
    lowers on TPU inside pallas kernels too); returns (take[1,N], feasible).
    Shared by the lax.scan path and the fused pallas kernel
    (ops.pallas_assign). The node-sharded rung re-derives these exact
    threshold/remainder formulas from summary histograms (``_hist_select``
    and the sharded mega path below) — its bit-identity guarantee holds
    only while the formulas match, so change all of them together.

    ``key`` overrides the selection bucket per node (the policy rung's
    composite: tightness + policy penalties, assign_gangs_policy). Any
    override must keep the base invariant key==0 ⟺ capacity==0 (bucket 0
    carries zero capacity, so the threshold formulas are unchanged);
    ``key=None`` is the exact pre-policy tightness bucket."""
    feasible = jnp.sum(capc) >= need
    if key is None:
        key = jnp.minimum(cap, _BINS - 1)  # tightness bucket (0 = no fit)
    bins = jax.lax.broadcasted_iota(jnp.int32, (_BINS, 1), 0)
    bin_totals = jnp.sum(
        jnp.where(key == bins, capc, 0), axis=1, keepdims=True
    )  # [_BINS, 1]
    cum_bins = _cumsum(bin_totals, axis=0)
    # threshold bucket: first where cumulative capacity covers the gang
    thresh = jnp.minimum(jnp.sum((cum_bins < need).astype(jnp.int32)), _BINS - 1)
    cum_at = jnp.sum(jnp.where(bins == thresh, cum_bins, 0))
    tot_at = jnp.sum(jnp.where(bins == thresh, bin_totals, 0))
    rem_t = need - (cum_at - tot_at)
    in_t = key == thresh
    capc_t = jnp.where(in_t, capc, 0)
    prefix_t = _cumsum(capc_t, axis=1) - capc_t
    take = jnp.where(
        key < thresh, capc, jnp.where(in_t, jnp.clip(rem_t - prefix_t, 0, capc), 0)
    )
    return take * feasible.astype(jnp.int32), feasible


def _member_capacity(left, req):
    """min over resource lanes of floor(left/req), for req-positive lanes —
    how many members of a demand row fit in a leftover row. Broadcasts:
    callers shape ``left``/``req`` to a common [..., R]. Inputs are clamped
    into the ``_exact_floordiv`` domain; the ``_BIG`` ceiling only saturates
    values already rejected at the batch boundary (ops.bucketing LANE_MAX /
    GANG_MAX checks). Shared by the batch kernel and the assignment scan;
    the pallas kernel (ops.pallas_assign) carries the same computation in
    its transposed [R, N] layout — change both together."""
    safe_req = jnp.clip(req, 1, _BIG)
    lpos = jnp.clip(left, 0, _BIG)
    per_lane = jnp.where(req > 0, _exact_floordiv(lpos, safe_req), _BIG)
    return jnp.min(per_lane, axis=-1)


@partial(jax.jit, static_argnames=("percent_num", "percent_den"))
def left_resources(alloc, requested, percent_num: int = 1, percent_den: int = 1):
    """Per-node leftover lanes: floor(alloc·percent) − requested.

    ``percent`` is the reference's reserve fraction (1.0 for the max-progress
    group, 0.7 otherwise — core.go:140,161,656-659), expressed as an exact
    integer ratio. Computed as ``q·num + (r·num)//den`` with ``q,r =
    divmod(alloc, den)`` so nothing overflows int32.
    """
    if percent_num == percent_den:
        scaled = alloc
    else:
        q = alloc // percent_den
        r = alloc - q * percent_den
        scaled = q * percent_num + (r * percent_num) // percent_den
    return scaled - requested


@jax.jit
def group_capacity(left, group_req, fit_mask):
    """cap[G,N]: how many members of group g fit on node n's leftover.

    cap = min over lanes with req>0 of left // req, clamped to >= 0, masked
    by per-(group,node) placement feasibility (selector/taints/validity).
    A node with any overcommitted lane naturally yields 0.
    """
    cap = _member_capacity(left[None, :, :], group_req[:, None, :])  # [G,N]
    return cap.astype(jnp.int32) * fit_mask.astype(jnp.int32)


@jax.jit
def gang_feasible(cap, remaining, group_valid):
    """ok[G]: total member capacity across the cluster covers the gang's
    still-unbound members. Per-node capacity is clipped at the gang's own
    remaining count before summing — equivalent (one node covering the whole
    gang already saturates the test) and it keeps the N-node sum exact in
    int32 even when sparse requests make single-node capacities huge."""
    total = jnp.sum(jnp.minimum(cap, remaining[:, None]), axis=1)
    return (total >= remaining) & group_valid


@jax.jit
def find_max_group(min_member, scheduled, matched, ineligible, creation_rank):
    """Vectorized findMaxPG (reference core.go:701-739).

    progress = (matched + scheduled)·1000 // min_member for eligible groups
    (not yet released, has a representative pod, still needs members), else 0
    when fully satisfied. Returns (best_index, best_exists, progress[G]).

    Tie-break (deterministic, unlike the Go map iteration): prefer groups
    with scheduled == 0, then earlier creation rank.
    """
    g = min_member.shape[0]
    needs = (min_member - scheduled) > 0
    denom = jnp.maximum(min_member, 1)
    progress = jnp.where(needs, (matched + scheduled) * 1000 // denom, 0)
    progress = jnp.clip(progress, 0, 2047)
    eligible = ~ineligible
    key = (
        progress.astype(jnp.int32) * (2 * g + 2)
        + jnp.where(scheduled == 0, g + 1, 0)
        + (g - creation_rank.astype(jnp.int32))
    )
    key = jnp.where(eligible, key, -1)
    best = jnp.argmax(key)
    return best.astype(jnp.int32), key[best] >= 0, progress


def find_max_group_host(min_member, scheduled, matched, ineligible,
                        creation_rank):
    """Host-side numpy twin of ``find_max_group`` — same formula, same
    tie-break, same argmax-first-occurrence semantics — used by the
    coalescer demux (service.coalescer): a merged mega-batch's device
    ``best`` ranges over EVERY tenant's gangs, but each tenant's response
    must carry the best of ITS OWN padded span, computed from pure inputs
    (the progress args are untouched by the scan). Feed it the tenant's
    own padded progress args and the answer is bit-identical to what a
    dedicated sidecar's device pass would have stamped. int32 stays exact:
    progress <= 2047 and g <= GANG_MAX keep the key below 2**31."""
    min_member = np.asarray(min_member)
    scheduled = np.asarray(scheduled)
    g = int(min_member.shape[0])
    needs = (min_member - scheduled) > 0
    denom = np.maximum(min_member, 1)
    progress = np.where(
        needs, (np.asarray(matched) + scheduled) * 1000 // denom, 0
    )
    progress = np.clip(progress, 0, 2047)
    eligible = ~np.asarray(ineligible)
    key = (
        progress.astype(np.int32) * (2 * g + 2)
        + np.where(scheduled == 0, g + 1, 0)
        + (g - np.asarray(creation_rank).astype(np.int32))
    )
    key = np.where(eligible, key, -1)
    best = int(np.argmax(key))
    return best, bool(key[best] >= 0), progress.astype(np.int32)


def repack_assignment_span(nodes_row, counts_row, node_offset: int,
                           span_n_bucket: int, k: int):
    """Re-derive ONE gang's dedicated-sidecar compact assignment row from
    its mega-batch row (service.coalescer demux).

    The compact readback is ``lax.top_k`` over the gang's take vector:
    entries sorted by (count desc, node index asc), and the zero-count
    tail is therefore the ASCENDING node indices not holding a take. In
    the block-diagonal mega-batch the positive takes can only land in the
    gang's own node block (every other block is masked to zero capacity),
    and their relative order under the global index tie-break equals the
    dedicated run's local order — so the dedicated row is exactly: the
    in-block positive entries shifted by ``-node_offset`` (truncated to
    ``k``), then ascending free indices over the tenant's own
    ``[0, span_n_bucket)`` padded space. ``k`` is the dedicated batch's
    ``batch_top_k(span_n_bucket, span_remaining_max)`` — compute it from
    the tenant's OWN padded args, exactly as dispatch_batch would.
    Returns ``(nodes[k] int32, counts[k] int32)``."""
    nodes_row = np.asarray(nodes_row)
    counts_row = np.asarray(counts_row)
    pos = counts_row > 0
    real_nodes = (nodes_row[pos] - node_offset).astype(np.int32)[:k]
    real_counts = counts_row[pos].astype(np.int32)[:k]
    out_nodes = np.zeros(k, dtype=np.int32)
    out_counts = np.zeros(k, dtype=np.int32)
    m = real_nodes.shape[0]
    out_nodes[:m] = real_nodes
    out_counts[:m] = real_counts
    if m < k:
        # vectorized ascending-free-index tail: this runs once per gang
        # on the coalescer's single worker thread, so a python
        # list-comprehension over the node bucket would make the demux
        # O(g*n_bucket) interpreted work per tenant
        free = np.ones(span_n_bucket, dtype=bool)
        free[real_nodes] = False
        fill = np.flatnonzero(free)[: k - m]
        out_nodes[m:m + fill.shape[0]] = fill
    return out_nodes, out_counts


@jax.jit
def score_nodes(cap):
    """score[G,N] for the Score extension point: best-fit ranking.

    Higher is better. Nodes that fit at least one member are ranked by
    *tightness* — fewer future members would fit, so gangs pack densely and
    large holes stay available for wide pods. Infeasible nodes score
    INT32_MIN-ish.
    """
    fits = cap > 0
    return jnp.where(fits, _BIG - cap, -_BIG)


@jax.jit
def assign_gangs(left0, group_req, remaining, fit_mask, order):
    """Greedy whole-batch gang placement.

    Walks groups in ``order`` (priority-first, the queue-sort order) with a
    ``lax.scan`` carrying the live leftover lanes; each step places all of a
    gang's remaining members at once — best-fit packing onto the
    tightest-fitting nodes — iff the whole gang fits (all-or-nothing at the
    batch level, which *is* gang semantics). Returns:

    - alloc[G,N]  members of group g placed on node n (rows in group index
      space, not scan order)
    - placed[G]   whether the gang was placed this batch
    - left[N,R]   leftover lanes after all placements

    One jitted call replaces the pod-at-a-time Permit accounting loop for
    batch mode; the reference has no equivalent (it admits gangs pod by pod
    against a TTL cache, core.go:268-309).

    Each scan step selects tightest-first WITHOUT a sort: nodes are bucketed
    by clamped capacity (``_BINS`` histogram). Buckets strictly below the
    threshold bucket (the one where cumulative capacity crosses ``need``)
    contribute every member they can hold; buckets above contribute none; so
    only the threshold bucket needs within-bucket (node-index) ordering —
    one O(N) cumsum. A sort-based selection costs O(N log^2 N) bitonic
    stages on TPU per group; this matches the sorted greedy exactly for
    per-node capacities < _BINS-1 (above that, equally-loose nodes tie-break
    by index instead of by capacity). Exactness bound: cumulative sums use
    capacities clipped at ``need``, so they stay inside int32 for any gang
    with min_member <= 2**18 — far above any real gang.

    ``fit_mask`` may be ``[G,N]`` or a broadcast ``[1,N]`` row (the
    no-selectors/no-taints common case — see ops.snapshot; an 8 MB host
    transfer becomes 8 KB).
    """
    n = left0.shape[0]
    mask_rows = fit_mask.shape[0]

    def body(left, g):
        req = jnp.take(group_req, g, axis=0)
        mask = jnp.take(fit_mask, jnp.minimum(g, mask_rows - 1), axis=0)
        need = jnp.take(remaining, g)

        cap = _member_capacity(left, req[None, :]) * mask  # [N] >= 0
        capc = jnp.minimum(cap, need)  # overflow-safe effective capacity
        take2d, feasible = _select_best_fit(cap[None, :], capc[None, :], need)
        take = take2d[0]
        left = left - take[:, None] * req[None, :]
        return left, (take, feasible)

    left, (takes, placed) = jax.lax.scan(body, left0, order, unroll=4)
    g = group_req.shape[0]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed = jnp.zeros((g,), bool).at[order].set(placed)
    return alloc, placed, left


@partial(jax.jit, static_argnames=("policy_terms", "policy_weights"))
def assign_gangs_policy(left0, group_req, remaining, fit_mask, order,
                        prio, aff, anti, gang_dom, node_hash, node_dom,
                        policy_terms: tuple = (),
                        policy_weights: tuple = ()):
    """Policy-composite form of ``assign_gangs``: same scan, same
    tightest-first machinery, with each gang's selection bucket shifted by
    the composed policy terms (batch_scheduler_tpu.policy.terms,
    docs/policy.md "Term algebra"):

    - soft penalties (affinity miss, spread-domain occupancy) ADD to the
      tightness bucket, clipped into ``[1, _BINS-1]`` — penalized nodes
      are consumed later but never excluded, and the within-bucket
      node-index tie-break of ``_select_best_fit`` is untouched (the
      override key keeps bucket 0 ⟺ zero capacity, so the threshold and
      remainder formulas hold verbatim);
    - hard masks (anti-affinity) multiply into the capacity row exactly
      like the fit mask.

    ``policy_terms``/``policy_weights`` are static (each policy config is
    its own jit signature — bounded: configs change per deployment, not
    per batch). With every term disabled (or all-zero columns) the
    composite key equals the base tightness bucket and the result is
    bit-identical to ``assign_gangs`` — the zero-policy identity
    ``make bench-policy`` enforces.

    This is the single scan rung policy batches run: the wavefront /
    sharded / top-K rungs EXPLICITLY DEMOTE to it (dispatch_batch) rather
    than approximate the composite — their uniform-wave and summary-merge
    fast paths assume the selection key is a function of capacity alone,
    which per-gang penalties break (docs/scan_parallelism.md "Policy
    composite").
    """
    from ..policy.terms import compose_terms

    pen_fn = compose_terms(policy_terms, policy_weights)
    n = left0.shape[0]
    mask_rows = fit_mask.shape[0]

    def body(left, g):
        req = jnp.take(group_req, g, axis=0)
        mask = jnp.take(fit_mask, jnp.minimum(g, mask_rows - 1), axis=0)
        need = jnp.take(remaining, g)
        pen, keep = pen_fn(
            jnp.take(aff, g), jnp.take(anti, g),
            jnp.take(gang_dom, g, axis=0), node_hash, node_dom,
        )

        cap = _member_capacity(left, req[None, :]) * mask * keep  # [N]
        capc = jnp.minimum(cap, need)
        base = jnp.minimum(cap, _BINS - 1)
        key = jnp.where(
            cap > 0, jnp.clip(base + pen, 1, _BINS - 1), 0
        )
        take2d, feasible = _select_best_fit(
            cap[None, :], capc[None, :], need, key=key[None, :]
        )
        take = take2d[0]
        left = left - take[:, None] * req[None, :]
        return left, (take, feasible)

    left, (takes, placed) = jax.lax.scan(body, left0, order, unroll=4)
    g = group_req.shape[0]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed = jnp.zeros((g,), bool).at[order].set(placed)
    return alloc, placed, left


@partial(jax.jit, static_argnames=("wave", "with_stats"))
def assign_gangs_wavefront(left0, group_req, remaining, fit_mask, order,
                           wave: int = 8, with_stats: bool = False):
    """Wavefront form of ``assign_gangs``: same inputs, same outputs,
    bit-identical results, ~G/W sequential steps instead of G.

    The serial scan's bottleneck is its step COUNT, not its step cost
    (87% of batch compute at the north-star shape, SCAN_SPLIT_r05.json),
    and partitioning each step drags collectives through the whole loop
    (6x slower, SHARDING_r03.json). So this cuts steps instead: gangs are
    pre-partitioned (in priority order) into waves of ``wave`` consecutive
    gangs, and one ``lax.scan`` step places a whole wave:

    1. **Uniform-wave fast path** — a wave whose gangs all share one
       request row and one mask row (bulk submissions of identical gangs:
       the north-star workload, and the padded tail) is placed with ONE
       aggregate selection. For identical per-member requests, taking
       ``t`` members off a node drops its capacity by exactly ``t``
       (``floor((x-t*q)/q) == floor(x/q)-t`` per lane), so the serial
       gang-by-gang tightest-first fill equals a single member stream
       ordered by (tightness bucket, node index): gang j takes the
       stream interval ``[sum of earlier feasible needs, +need_j)``.
       Stream positions come from the same histogram machinery as
       ``_select_best_fit``, with within-bucket (node index) resolution
       computed only for the <= W+1 buckets that contain a gang
       boundary; per-gang feasibility is verified batched at the assumed
       boundaries, and any infeasible gang demotes the wave to the
       serial replay — so a committed wave costs ~one selection instead
       of W.
    2. **Batched speculative path** — otherwise, every gang computes its
       capacities and tightest-first take against the WAVE-START
       leftover, as if it were first (one vmapped ``_select_best_fit``,
       W-way), then a **conflict check** recomputes each gang's capacity
       vector under the exclusive prefix of the wave's earlier takes. If
       every gang's capacities are unchanged, the fast takes ARE the
       serial takes (induction over the wave: gang j's serial leftover is
       the wave-start leftover minus exactly those prefix deltas, and the
       selection is a deterministic function of the capacity vector).
    3. **Demotion** — any mismatch demotes the wave to a ``lax.cond``
       branch that replays it serially (the exact per-gang body of
       ``assign_gangs``), so contended waves pay the serial cost and
       nothing else changes.

    Bit-identity therefore holds by construction on EVERY input: the
    uniform path is the serial fill in aggregate form, the speculative
    path is proven equal before it commits, and the slow path is the
    serial scan. Uniform and low-contention workloads commit every wave
    on a fast path, dropping the sequential dependency chain to
    ceil(G/W) steps.

    Overflow discipline: prefix leftovers are accumulated with a clamp at
    ``-_BIG`` (each wave delta is bounded by the wave-start leftover
    <= LANE_MAX, so one clamped subtraction cannot wrap int32), and a
    clamped-negative leftover yields capacity 0 exactly like its
    unclamped value would — the conflict check is exact. On the
    no-conflict path no clamp ever fires (the running value equals the
    serial leftover, which stays >= 0), so the committed leftover is
    exact too.

    ``with_stats`` additionally returns per-wave diagnostics for the
    SCAN_SPLIT artifact: ``(conflicts[S], uniform[S])`` — waves demoted
    to the serial replay, and waves committed by the uniform aggregate
    path.
    """
    n = left0.shape[0]
    g = group_req.shape[0]
    w = max(int(wave), 1)
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )

    # pre-permute into scan order so each wave is a contiguous chunk (the
    # pallas kernel's idiom); pad the group axis to a wave multiple with
    # inert rows (zero demand, remaining 0, masked out) that run AFTER
    # every real gang and take nothing.
    steps = -(-g // w)
    g_pad = steps * w
    gr = jnp.take(group_req, order, axis=0)
    rem = jnp.take(remaining, order, axis=0)
    mask = fit_mask.astype(jnp.int32)
    if per_group_mask:
        mask = jnp.take(mask, order, axis=0)
    if g_pad != g:
        gr = jnp.pad(gr, ((0, g_pad - g), (0, 0)))
        rem = jnp.pad(rem, ((0, g_pad - g),))
        if per_group_mask:
            mask = jnp.pad(mask, ((0, g_pad - g), (0, 0)))
    r = gr.shape[1]
    gr_w = gr.reshape(steps, w, r)
    rem_w = rem.reshape(steps, w)
    xs = (gr_w, rem_w, mask.reshape(steps, w, n)) if per_group_mask else (
        gr_w, rem_w,
    )
    bcast_row = None if per_group_mask else mask  # [1, N]

    def _one(cap, capc, need):
        take2d, feas = _select_best_fit(cap[None, :], capc[None, :], need)
        return take2d[0], feas

    select_wave = jax.vmap(_one)
    # the aggregate stream's histogram sums stay exact in int32 only while
    # total_need * N fits (same bound class pad_oracle_batch enforces per
    # gang); oversized waves fall through to the speculative path
    mega_need_max = (2**31 - 1) // max(n, 1)

    def step(left, chunk):
        if per_group_mask:
            req_c, need_c, mask_c = chunk  # [W,R], [W], [W,N]
        else:
            req_c, need_c = chunk
            mask_c = bcast_row  # [1,N] broadcasts over the wave
        total_need = jnp.sum(need_c)
        uniform = jnp.all(req_c == req_c[0:1])
        if per_group_mask:
            uniform = uniform & jnp.all(mask_c == mask_c[0:1])
        mega_ok = uniform & (total_need <= mega_need_max)

        def replay_wave(left):
            # the serial scan body, gang by gang — the demotion target of
            # both fast paths; reports conflict=True (a demoted wave)
            takes, feats = [], []
            for j in range(w):
                row = mask_c[j] if per_group_mask else mask_c[0]
                cap_j = _member_capacity(left, req_c[j][None, :]) * row
                capc_j = jnp.minimum(cap_j, need_c[j])
                t, f = _one(cap_j, capc_j, need_c[j])
                left = left - t[:, None] * req_c[j][None, :]
                takes.append(t)
                feats.append(f)
            return (
                jnp.stack(takes), jnp.stack(feats), left, jnp.bool_(True)
            )

        def mega(left):
            # ONE aggregate tightest-first fill for a wave of identical
            # demand rows, split at gang boundaries (see docstring).
            # Gang boundaries are ASSUMED at the prefix sums of the needs
            # (i.e. every gang feasible) so the boundary resolution can
            # batch; the assumption is then verified batched, and any
            # infeasible gang demotes the wave to the serial replay
            # (sound by induction: if every gang passes its check at the
            # assumed boundary, the assumed boundaries ARE the serial
            # ones). Only the <= W+1 buckets containing a boundary need
            # within-bucket (node-index) resolution — one [W+1, N]
            # masked cumsum, NOT the full [_BINS, N] one (measured 76 ms
            # a wave at the north-star shape, 10x the rest of the step).
            req0 = req_c[0]
            cap0 = _member_capacity(left, req0[None, :]) * mask_c[0]  # [N]
            key = jnp.minimum(cap0, _BINS - 1)
            capc_t = jnp.minimum(cap0, total_need)  # stream units per node
            bins = jax.lax.broadcasted_iota(jnp.int32, (_BINS, 1), 0)
            bin_totals = jnp.sum(
                jnp.where(key[None, :] == bins, capc_t[None, :], 0),
                axis=1,
            )  # [_BINS]
            cum_incl = _cumsum(bin_totals[None, :], axis=1)[0]
            cum_excl = cum_incl - bin_totals
            # assumed boundaries A_j = sum of earlier needs, j = 0..W
            bounds = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(need_c)]
            )  # [W+1]
            # bucket containing each boundary (== _BINS when past the end)
            bbkt = jnp.sum(
                (cum_incl[None, :] <= bounds[:, None]).astype(jnp.int32),
                axis=1,
            )  # [W+1]
            # within-bucket exclusive prefix, boundary buckets only
            bmask = key[None, :] == bbkt[:, None]  # [W+1, N]
            bvals = jnp.where(bmask, capc_t[None, :], 0)
            bwithin = _cumsum(bvals, axis=1) - bvals
            # taken[j, n]: units of node n inside the first A_j stream units
            boffs = (bounds - jnp.take(cum_excl, bbkt, mode="clip"))[:, None]
            taken = jnp.where(
                key[None, :] < bbkt[:, None],
                capc_t[None, :],
                jnp.where(
                    bmask, jnp.clip(boffs - bwithin, 0, capc_t[None, :]), 0
                ),
            )  # [W+1, N]
            # verify the all-feasible assumption: remaining capacity after
            # the first A_j members is exactly cap0 - taken_j
            feas = (
                jnp.sum(
                    jnp.minimum(cap0[None, :] - taken[:-1], need_c[:, None]),
                    axis=1,
                )
                >= need_c
            )  # [W]
            all_ok = jnp.all(feas)

            def commit(left):
                takes_m = taken[1:] - taken[:-1]  # telescoped intervals
                left_after = left - taken[-1][:, None] * req0[None, :]
                return (
                    takes_m,
                    jnp.ones((w,), bool),
                    left_after,
                    jnp.bool_(False),
                )

            return jax.lax.cond(all_ok, commit, replay_wave, left)

        def speculative(left):
            # batched fast path: every gang scores the wave-start leftover
            cap = (
                _member_capacity(left[None, :, :], req_c[:, None, :]) * mask_c
            )
            capc = jnp.minimum(cap, need_c[:, None])
            takes_w, feas_w = select_wave(cap, capc, need_c)  # [W,N], [W]
            deltas = takes_w[:, :, None] * req_c[:, None, :]  # [W,N,R]

            # exclusive-prefix leftovers, clamp-accumulated (see docstring)
            acc = left
            prefixed = []
            for j in range(w):
                prefixed.append(acc)
                acc = jnp.maximum(acc - deltas[j], -_BIG)
            cap_pref = _member_capacity(
                jnp.stack(prefixed), req_c[:, None, :]
            ) * mask_c
            conflict = jnp.any(cap_pref != cap)

            def fast(left):
                # acc == serial leftover after the wave (no clamp fired)
                return takes_w, feas_w, acc, jnp.bool_(False)

            return jax.lax.cond(conflict, replay_wave, fast, left)

        takes_out, feas_out, left, conflict = jax.lax.cond(
            mega_ok, mega, speculative, left
        )
        return left, (takes_out, feas_out, conflict, mega_ok)

    left, (takes, placed, conflicts, megas) = jax.lax.scan(step, left0, xs)
    takes = takes.reshape(g_pad, n)[:g]
    placed = placed.reshape(g_pad)[:g]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed_full = jnp.zeros((g,), bool).at[order].set(placed)
    if with_stats:
        return alloc, placed_full, left, (conflicts, megas)
    return alloc, placed_full, left


def _shard_axes(mesh) -> tuple:
    """All of a mesh's axis names, major-to-minor — the flattened shard
    axis the node-sharded scan runs over. The 2-D ("groups", "nodes") grid
    exists for the O(G·N·R) scoring; the scan has no group parallelism to
    spend, so it splits the NODE axis over every device."""
    return tuple(mesh.axis_names)


def _hist_select(bin_tot, shard_off, key_l, capc_l, need):
    """Per-shard take vector from GLOBAL tightness histograms: the
    ``_select_best_fit`` selection recomputed from summary data.

    ``bin_tot[W, _BINS]`` is the global per-bucket capacity histogram (the
    psum of every shard's local histogram), ``shard_off[W, _BINS]`` this
    shard's exclusive prefix within each bucket (sum of EARLIER shards'
    local histograms — global node order is shard-major, so bucket-internal
    node-index order decomposes into (earlier shards' total, local
    prefix)), ``key_l``/``capc_l`` ``[W, n_local]`` the local tightness
    buckets and need-clipped capacities, ``need[W]`` the gang demands.

    Bit-identity with the serial selection: every quantity here is an int32
    sum over a permutation of the same addends the serial cumsum folds
    (int32 addition is associative/commutative, wraparound included), and
    the threshold/remainder formulas are copied verbatim — so
    ``shard_off + local prefix`` IS the serial ``prefix_t`` restricted to
    this shard's rows, and the local takes concatenate (in shard order) to
    exactly the serial take vector. Returns (take_l[W, n_local], feas[W]).
    """
    cum = _cumsum(bin_tot, axis=1)  # [W, _BINS] inclusive
    total = cum[:, _BINS - 1]
    feas = total >= need
    thresh = jnp.minimum(
        jnp.sum((cum < need[:, None]).astype(jnp.int32), axis=1), _BINS - 1
    )  # [W]
    tot_at = jnp.take_along_axis(bin_tot, thresh[:, None], axis=1)[:, 0]
    cum_at = jnp.take_along_axis(cum, thresh[:, None], axis=1)[:, 0]
    rem_t = need - (cum_at - tot_at)  # members still needed in thresh bucket
    off = jnp.take_along_axis(shard_off, thresh[:, None], axis=1)  # [W, 1]
    in_t = key_l == thresh[:, None]
    capc_t = jnp.where(in_t, capc_l, 0)
    prefix_l = _cumsum(capc_t, axis=1) - capc_t
    take_l = jnp.where(
        key_l < thresh[:, None],
        capc_l,
        jnp.where(
            in_t, jnp.clip(rem_t[:, None] - off - prefix_l, 0, capc_l), 0
        ),
    )
    return take_l * feas.astype(jnp.int32)[:, None], feas


def assign_gangs_sharded(left0, group_req, remaining, fit_mask, order, mesh,
                         wave: int = 8, with_stats: bool = False):
    """Node-sharded wavefront gang placement: same inputs and outputs as
    ``assign_gangs_wavefront`` (bit-identical to the serial scan), but the
    carried ``[N, R]`` leftover stays PARTITIONED over the whole mesh and
    the per-wave merge moves only O(S·W·_BINS) summary ints.

    The partitioned-scan failure mode this replaces (SHARDING_r05.json) was
    GSPMD dragging full node state through every step: ~50 collective
    sites (all-gathers of ``left``, collective-permute chains) inside the
    G-step loop, 6x slower than one device. Here the collectives are
    chosen by hand inside a ``shard_map``:

    1. Every shard scores ONLY its contiguous node slice: local member
       capacities, local tightness histogram ``[W, _BINS]`` (need-clipped
       capacity per bucket — the complete sufficient statistic for the
       serial tightest-first selection).
    2. ONE ``all_gather`` per wave merges the per-shard histograms
       (``[S, W, _BINS]`` ints — summary data, never node state). Every
       shard then derives the identical global threshold buckets, and its
       own within-bucket offset = sum of earlier shards' histograms, so
       each shard applies exactly its slice of the serial take vector —
       the "winner applies locally" rule: no leftover ever crosses shards.
    3. ONE ``psum`` per wave verifies the speculative wave (the exclusive-
       prefix conflict check of the wavefront scan, evaluated shard-local
       and reduced as a single bit) or, on the uniform aggregate path, the
       batched gang-boundary feasibilities. A conflicted wave demotes to a
       gang-at-a-time replay (W summary all-gathers — still never node
       state), preserving the wavefront's demotion ladder semantics.

    Tie-breaks stay deterministic on the GLOBAL node index because shards
    hold contiguous node blocks in mesh-major order and every within-
    bucket remainder is resolved as (earlier-shard total, local prefix).

    The node axis is padded to a shard multiple with zero rows (zero
    leftover + zero mask ⇒ zero capacity in every histogram), so uneven
    node counts shard cleanly and padded rows can never win a member.

    Returns ``(alloc[G,N], placed[G], left[N,R])`` (+ per-wave
    ``(conflicts, megas)`` stats when ``with_stats``), exactly like the
    wavefront scan.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec

    n, r = left0.shape
    g = group_req.shape[0]
    w = max(int(wave), 2)
    axes = _shard_axes(mesh)
    s = int(np.prod([mesh.shape[a] for a in axes]))
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )

    # -- node-axis shard padding (zero rows: capacity 0 under any mask) --
    n_pad = -(-n // s) * s
    left_p = left0
    mask = fit_mask.astype(jnp.int32)
    if n_pad != n:
        left_p = jnp.pad(left_p, ((0, n_pad - n), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, n_pad - n)))

    # -- gang-axis wave chunking, identical to assign_gangs_wavefront --
    steps = -(-g // w)
    g_pad = steps * w
    gr = jnp.take(group_req, order, axis=0)
    rem = jnp.take(remaining, order, axis=0)
    if per_group_mask:
        mask = jnp.take(mask, order, axis=0)
    if g_pad != g:
        gr = jnp.pad(gr, ((0, g_pad - g), (0, 0)))
        rem = jnp.pad(rem, ((0, g_pad - g),))
        if per_group_mask:
            mask = jnp.pad(mask, ((0, g_pad - g), (0, 0)))
    gr_w = gr.reshape(steps, w, r)
    rem_w = rem.reshape(steps, w)
    # Mask uniformity per wave, computed ONCE outside the scan (a global
    # reduction over sharded mask rows) so the in-scan mega/speculative
    # branch choice needs no extra collective. Broadcast masks are
    # uniform by definition.
    if per_group_mask:
        mask_w = mask.reshape(steps, w, n_pad)
        mask_uni = jnp.all(mask_w == mask_w[:, :1], axis=(1, 2))
    else:
        mask_w = mask  # [1, n_pad]
        mask_uni = jnp.ones((steps,), bool)
    mega_need_max = (2**31 - 1) // max(n_pad, 1)

    def shard_body(left_l, gr_w, rem_w, mask_l, mask_uni):
        # left_l: [n_pad/S, R] — this shard's contiguous node block.
        # mask_l: [1, nl] broadcast or [steps, w, nl] per-group slice.
        sid = jnp.int32(0)
        for name in axes:
            sid = sid * mesh.shape[name] + jax.lax.axis_index(name)
        earlier = (
            jax.lax.broadcasted_iota(jnp.int32, (s, 1, 1), 0) < sid
        )  # [S,1,1] — mask selecting shards before this one

        bins3 = jax.lax.broadcasted_iota(jnp.int32, (1, _BINS, 1), 1)

        def local_hist(key_l, capc_l):
            """[W?, _BINS] need-clipped capacity histogram of the local
            node slice (W leading axis optional via broadcasting)."""
            return jnp.sum(
                jnp.where(key_l[:, None, :] == bins3, capc_l[:, None, :], 0),
                axis=2,
            )  # [W?, _BINS]

        def merge(hist_l):
            """The per-wave summary merge: one all-gather of every
            shard's histogram; returns (global totals, this shard's
            exclusive within-bucket offsets)."""
            hists = jax.lax.all_gather(hist_l, axes)  # [S, W?, _BINS]
            bin_tot = jnp.sum(hists, axis=0)
            shard_off = jnp.sum(jnp.where(earlier, hists, 0), axis=0)
            return bin_tot, shard_off

        def step(left, chunk):
            if per_group_mask:
                req_c, need_c, uni_mask, mask_c = chunk  # mask_c: [w, nl]
            else:
                req_c, need_c, uni_mask = chunk
                mask_c = mask_l  # [1, nl] broadcasts over the wave
            total_need = jnp.sum(need_c)
            uniform = jnp.all(req_c == req_c[0:1]) & uni_mask
            mega_ok = uniform & (total_need <= mega_need_max)

            def replay_wave(left):
                # gang-at-a-time demotion target: exact serial order, one
                # summary all-gather per gang (never node state)
                takes, feats = [], []
                for j in range(w):
                    row = mask_c[j] if per_group_mask else mask_c[0]
                    cap_j = (
                        _member_capacity(left, req_c[j][None, :]) * row
                    )  # [nl]
                    capc_j = jnp.minimum(cap_j, need_c[j])
                    key_j = jnp.minimum(cap_j, _BINS - 1)
                    bin_tot, shard_off = merge(
                        local_hist(key_j[None, :], capc_j[None, :])
                    )
                    t, f = _hist_select(
                        bin_tot, shard_off, key_j[None, :], capc_j[None, :],
                        need_c[j][None],
                    )
                    left = left - t[0][:, None] * req_c[j][None, :]
                    takes.append(t[0])
                    feats.append(f[0])
                return (
                    jnp.stack(takes), jnp.stack(feats), left, jnp.bool_(True)
                )

            def mega(left):
                # uniform-wave aggregate: ONE member stream split at gang
                # boundaries (assign_gangs_wavefront's fast path), with the
                # stream histogram merged once and boundary feasibility
                # verified by one psum.
                req0 = req_c[0]
                row = mask_c[0]
                cap0 = _member_capacity(left, req0[None, :]) * row  # [nl]
                key = jnp.minimum(cap0, _BINS - 1)
                capc_t = jnp.minimum(cap0, total_need)  # stream units
                bin_tot, shard_off = merge(
                    local_hist(key[None, :], capc_t[None, :])
                )  # [1, _BINS] each
                cum_incl = _cumsum(bin_tot, axis=1)[0]  # [_BINS]
                cum_excl = cum_incl - bin_tot[0]
                bounds = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int32), jnp.cumsum(need_c)]
                )  # [W+1]
                bbkt = jnp.sum(
                    (cum_incl[None, :] <= bounds[:, None]).astype(jnp.int32),
                    axis=1,
                )  # [W+1]; == _BINS past the stream end
                bmask = key[None, :] == bbkt[:, None]  # [W+1, nl]
                bvals = jnp.where(bmask, capc_t[None, :], 0)
                bwithin = _cumsum(bvals, axis=1) - bvals
                boffs = (
                    bounds - jnp.take(cum_excl, bbkt, mode="clip")
                )[:, None]
                soffs = jnp.take(shard_off[0], bbkt, mode="clip")[:, None]
                taken = jnp.where(
                    key[None, :] < bbkt[:, None],
                    capc_t[None, :],
                    jnp.where(
                        bmask,
                        jnp.clip(boffs - soffs - bwithin, 0, capc_t[None, :]),
                        0,
                    ),
                )  # [W+1, nl] — this shard's slice of the stream prefix
                feas_part = jnp.sum(
                    jnp.minimum(cap0[None, :] - taken[:-1], need_c[:, None]),
                    axis=1,
                )  # [W] local partial feasibility sums
                feas = jax.lax.psum(feas_part, axes) >= need_c
                all_ok = jnp.all(feas)

                def commit(left):
                    takes_m = taken[1:] - taken[:-1]
                    left_after = left - taken[-1][:, None] * req0[None, :]
                    return (
                        takes_m,
                        jnp.ones((w,), bool),
                        left_after,
                        jnp.bool_(False),
                    )

                return jax.lax.cond(all_ok, commit, replay_wave, left)

            def speculative(left):
                # every gang scores the wave-start LOCAL slice as if first
                cap = (
                    _member_capacity(left[None, :, :], req_c[:, None, :])
                    * mask_c
                )  # [w, nl]
                capc = jnp.minimum(cap, need_c[:, None])
                key = jnp.minimum(cap, _BINS - 1)
                bin_tot, shard_off = merge(local_hist(key, capc))
                takes_w, feas_w = _hist_select(
                    bin_tot, shard_off, key, capc, need_c
                )
                deltas = takes_w[:, :, None] * req_c[:, None, :]
                # exclusive-prefix conflict check, shard-local (same clamp
                # discipline as assign_gangs_wavefront), reduced to one bit
                acc = left
                prefixed = []
                for j in range(w):
                    prefixed.append(acc)
                    acc = jnp.maximum(acc - deltas[j], -_BIG)
                cap_pref = (
                    _member_capacity(jnp.stack(prefixed), req_c[:, None, :])
                    * mask_c
                )
                conflict_l = jnp.any(cap_pref != cap).astype(jnp.int32)
                conflict = jax.lax.psum(conflict_l, axes) > 0

                def fast(left):
                    return takes_w, feas_w, acc, jnp.bool_(False)

                return jax.lax.cond(conflict, replay_wave, fast, left)

            takes_out, feas_out, left, conflict = jax.lax.cond(
                mega_ok, mega, speculative, left
            )
            return left, (takes_out, feas_out, conflict, mega_ok)

        xs = (gr_w, rem_w, mask_uni)
        if per_group_mask:
            xs = xs + (mask_l,)
        left_l, (takes, placed, conflicts, megas) = jax.lax.scan(
            step, left_l, xs
        )
        return left_l, takes, placed, conflicts, megas

    P = PartitionSpec
    mask_in_spec = (
        P(None, None, axes) if per_group_mask else P(None, axes)
    )
    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(axes, None),            # left: node-blocked over every device
            P(None, None, None),      # per-wave demand rows (replicated)
            P(None, None),            # per-wave remaining (replicated)
            mask_in_spec,             # fit mask: node axis sharded
            P(None),                  # per-wave mask uniformity (replicated)
        ),
        out_specs=(
            P(axes, None),            # left_after stays node-sharded
            P(None, None, axes),      # takes: node axis sharded
            P(None, None),            # placed flags (replicated)
            P(None),                  # per-wave conflict stats (replicated)
            P(None),                  # per-wave mega stats (replicated)
        ),
        check_rep=False,
    )
    left_after, takes, placed, conflicts, megas = sharded(
        left_p, gr_w, rem_w, mask_w, mask_uni
    )
    takes = takes.reshape(g_pad, n_pad)[:g, :n]
    placed = placed.reshape(g_pad)[:g]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed_full = jnp.zeros((g,), bool).at[order].set(placed)
    left_after = left_after[:n]
    if with_stats:
        return alloc, placed_full, left_after, (conflicts, megas)
    return alloc, placed_full, left_after


# Block width of the two-level coarse rank. A straight lax.top_k over N
# lowers to a comparator sort on CPU (~30x the cost of the arithmetic in
# a top-K wave — measured 156ms vs 5ms at [W=8, N=65536]); the two-level
# form reduces N to N/32 block minima first (vectorized min), picks the
# k best BLOCKS, and sorts only the gathered k·32 pool. Exact for any
# block width: composites are unique, so a block holding a true top-k
# element has a block-min at most that element and must itself rank in
# the top-k blocks.
_COARSE_BLOCK = 32


def _coarse_rank(cap, k: int, span: int, pos=None):
    """Coarse pass: the top-``k`` candidate columns of a ``[..., N]``
    capacity row, ordered by (tightness bucket, node index) — exactly the
    order the exact tightest-first selection consumes nodes in.

    ``span`` is the GLOBAL node extent the composite rank key is built
    over (``N`` locally; the padded global N on a shard, where ``pos``
    carries the shard's global index offset — see the sharded body).
    Returns ``(idx[..., k], v[..., k])`` where ``v = key·(span+1) + index``
    ascends over the candidates; slots past the last fitting node carry a
    ``_BIG`` sentinel value, and CALLERS MUST MASK capacities gathered at
    sentinel slots by ``v < _BIG`` (a sentinel's index may alias a real
    node: the two-level pool pads to a block multiple and clamps).
    Exact composite: ``key ≤ _BINS-1`` and ``span < 2**23`` keep ``v``
    far inside int32 (the 8M-node ceiling is documented in
    docs/scan_parallelism.md)."""
    n = cap.shape[-1]
    key = jnp.minimum(cap, _BINS - 1)
    if pos is None:
        pos = jax.lax.broadcasted_iota(jnp.int32, cap.shape, cap.ndim - 1)
    v = jnp.where(key > 0, key * (span + 1) + pos, _BIG)
    c = _COARSE_BLOCK
    if n <= max(1024, c * k):
        # small rows (or k too close to the block count): the direct
        # top_k costs less than the two-level plumbing
        neg, idx = jax.lax.top_k(-v, k)
        return idx, -neg
    lead = cap.shape[:-1]
    nb = -(-n // c)
    if nb * c != n:
        v_pad = jnp.pad(
            v, [(0, 0)] * (cap.ndim - 1) + [(0, nb * c - n)],
            constant_values=_BIG,
        )
    else:
        v_pad = v
    bmin = jnp.min(v_pad.reshape(lead + (nb, c)), axis=-1)
    _, bidx = jax.lax.top_k(-bmin, k)
    pool_idx = (
        bidx[..., None] * c + jnp.arange(c, dtype=jnp.int32)
    ).reshape(lead + (k * c,))
    v_pool = jnp.take_along_axis(v_pad, pool_idx, axis=-1)
    neg, p = jax.lax.top_k(-v_pool, k)
    idx = jnp.take_along_axis(pool_idx, p, axis=-1)
    # clamp pad-phantom sentinels into range; their v stays _BIG, which
    # is what downstream masking keys on
    return jnp.minimum(idx, n - 1), -neg


@partial(jax.jit, static_argnames=("wave", "k", "with_stats"))
def assign_gangs_topk(left0, group_req, remaining, fit_mask, order,
                      wave: int = 8, k: int = 16, with_stats: bool = False):
    """Hierarchical top-K form of ``assign_gangs_wavefront``: same inputs,
    same outputs, bit-identical to the serial scan, but each wave's exact
    selection machinery runs on ``[W, K]`` GATHERED candidate slices
    instead of the full ``[W, N]`` row — the two-level device pipeline of
    the 100k-node scale tier (docs/scan_parallelism.md "Hierarchical
    top-K").

    Per wave, against the wave-entry leftover:

    1. **Coarse pass** — one ``[W, N, R]`` member-capacity sweep (the only
       O(N) work in the step) ranks every node per gang by the SAME
       need-clipped tightness score the exact scan uses, and keeps the
       top-K candidate columns in (tightness bucket, node index) order.
    2. **Exact pass on candidates** — ``_select_best_fit`` runs verbatim
       on the gathered ``[W, K]`` slices. The candidate set is the first K
       nodes in the exact selection's own consumption order, so whenever
       the K candidates' need-clipped capacity covers the gang
       (``covered``), the restricted selection IS the dense selection:
       every tightness bucket below the K-th candidate's bucket (the
       per-gang **bound**) is complete in the slice, the bound bucket's
       included nodes are its node-index prefix, and coverage pins the
       threshold at or inside the bound — so threshold, remainder, and
       within-bucket fill all coincide with the dense formulas.
    3. **Demotion, not hope** — exactness never rests on K being "big
       enough". A gang whose candidates cannot cover its need while the
       pooled (full-N) capacity says placement may exist demotes to a
       **dense-column replay**: the full-N selection for that one gang
       (``bst_topk_demotions`` counts these — the K-mistuned signal). A
       gang that is pooled-infeasible needs no demotion: capacities only
       decrease within a batch, so the wave-entry pooled bound is already
       an upper bound on its turn-time capacity.
    4. **Conflict check on the candidate union** — the speculative wave
       commits only if no gang's capacities changed on the union of the
       wave's candidate columns under the exclusive prefix of earlier
       takes (the wavefront conflict check, evaluated on ≤ W·K columns).
       Takes land only on candidate columns, so untouched non-candidates
       keep their wave-entry tightness and the per-gang bound covers
       them; touched columns are all in the union and checked directly.
       Any violation demotes the wave to the gang-at-a-time replay, where
       each gang re-ranks FRESH at its turn (staleness-free) and applies
       rule 3.

    The uniform wave (mega) path restricts the aggregate member stream
    the same way: the stream consumes nodes in exactly (tightness, index)
    order, so when the K candidates cover the wave's total need the
    candidate-restricted stream is the dense stream, boundary
    feasibilities are recovered exactly as ``pooled − candidate-entry +
    candidate-post-take`` sums, and anything else demotes.

    Outputs match ``assign_gangs_wavefront``; ``with_stats`` returns
    ``(conflicts[S], megas[S], dense_demotions[S])`` — the third series
    is new: dense-column replays per wave (the bst_topk_demotions feed).
    """
    n = left0.shape[0]
    g = group_req.shape[0]
    w = max(int(wave), 2)
    kk = max(2, min(int(k), n))
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )

    steps = -(-g // w)
    g_pad = steps * w
    gr = jnp.take(group_req, order, axis=0)
    rem = jnp.take(remaining, order, axis=0)
    mask = fit_mask.astype(jnp.int32)
    if per_group_mask:
        mask = jnp.take(mask, order, axis=0)
    if g_pad != g:
        gr = jnp.pad(gr, ((0, g_pad - g), (0, 0)))
        rem = jnp.pad(rem, ((0, g_pad - g),))
        if per_group_mask:
            mask = jnp.pad(mask, ((0, g_pad - g), (0, 0)))
    r = gr.shape[1]
    gr_w = gr.reshape(steps, w, r)
    rem_w = rem.reshape(steps, w)
    xs = (gr_w, rem_w, mask.reshape(steps, w, n)) if per_group_mask else (
        gr_w, rem_w,
    )
    bcast_row = None if per_group_mask else mask  # [1, N]

    def _one(cap, capc, need):
        take2d, feas = _select_best_fit(cap[None, :], capc[None, :], need)
        return take2d[0], feas

    select_wave = jax.vmap(_one)
    mega_need_max = (2**31 - 1) // max(n, 1)

    def step(left, chunk):
        if per_group_mask:
            req_c, need_c, mask_c = chunk  # [W,R], [W], [W,N]
        else:
            req_c, need_c = chunk
            mask_c = bcast_row  # [1,N] broadcasts over the wave
        total_need = jnp.sum(need_c)
        uniform = jnp.all(req_c == req_c[0:1])
        if per_group_mask:
            uniform = uniform & jnp.all(mask_c == mask_c[0:1])
        mega_ok = uniform & (total_need <= mega_need_max)

        def replay_wave(left):
            # gang-at-a-time demotion target: each gang coarse-ranks FRESH
            # at its own turn, so the restricted selection is exact
            # whenever its candidates cover the need; otherwise the gang
            # demotes to the dense-column replay (full-N selection) and
            # is counted
            takes, feats = [], []
            dense_n = jnp.int32(0)
            for j in range(w):
                row = mask_c[j] if per_group_mask else mask_c[0]
                cap_j = _member_capacity(left, req_c[j][None, :]) * row
                capc_j = jnp.minimum(cap_j, need_c[j])
                pooled_j = jnp.sum(capc_j)
                idx_j, vals_j = _coarse_rank(cap_j, kk, n)
                live_j = (vals_j < _BIG).astype(jnp.int32)
                cap_jk = jnp.take(cap_j, idx_j) * live_j
                capc_jk = jnp.take(capc_j, idx_j) * live_j
                covered = jnp.sum(capc_jk) >= need_c[j]
                use_restricted = covered | (pooled_j < need_c[j])

                def restricted(_):
                    t_k, f = _one(cap_jk, capc_jk, need_c[j])
                    # .add, not .set: sentinel slots may alias a real
                    # node's index (their take is 0 — capc masked)
                    take = jnp.zeros((n,), jnp.int32).at[idx_j].add(t_k)
                    return take, f

                def dense_col(_):
                    return _one(cap_j, capc_j, need_c[j])

                take_j, feas_j = jax.lax.cond(
                    use_restricted, restricted, dense_col, None
                )
                left = left - take_j[:, None] * req_c[j][None, :]
                dense_n = dense_n + (~use_restricted).astype(jnp.int32)
                takes.append(take_j)
                feats.append(feas_j)
            return (
                jnp.stack(takes), jnp.stack(feats), left, jnp.bool_(True),
                dense_n,
            )

        def mega(left):
            # uniform-wave aggregate stream, restricted to K candidates:
            # the stream consumes nodes in (tightness, index) order, i.e.
            # exactly the candidate order, so a covering candidate set
            # makes the plain exclusive cumsum over candidates the whole
            # boundary machinery — no [_BINS, N] histogram, no [W+1, N]
            # masked cumsums
            req0 = req_c[0]
            row = mask_c[0]
            cap0 = _member_capacity(left, req0[None, :]) * row  # [N]
            capc_t = jnp.minimum(cap0, total_need)  # stream units per node
            idx, vals = _coarse_rank(cap0, kk, n)
            live = (vals < _BIG).astype(jnp.int32)
            cap_k = jnp.take(cap0, idx) * live
            capc_k = jnp.take(capc_t, idx) * live
            covered = jnp.sum(capc_k) >= total_need
            # exact boundary feasibility: dense sums split into pooled
            # full-N terms (wave-entry, no stream) + candidate-only
            # corrections — non-candidates take nothing from the stream
            pooled_need = jnp.sum(
                jnp.minimum(cap0[None, :], need_c[:, None]), axis=1
            )  # [W]
            prefix = _cumsum(capc_k[None, :], axis=1)[0] - capc_k  # excl
            bounds = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(need_c)]
            )  # [W+1]
            taken = jnp.clip(
                bounds[:, None] - prefix[None, :], 0, capc_k[None, :]
            )  # [W+1, kk]
            cand_entry = jnp.minimum(cap_k[None, :], need_c[:, None])
            cand_post = jnp.minimum(
                cap_k[None, :] - taken[:-1], need_c[:, None]
            )
            feas = (
                pooled_need
                - jnp.sum(cand_entry, axis=1)
                + jnp.sum(cand_post, axis=1)
            ) >= need_c
            all_ok = covered & jnp.all(feas)

            def commit(left):
                takes_m = taken[1:] - taken[:-1]  # [W, kk]
                # .add, not .set: sentinel slots may alias a real node's
                # index (their take is 0 — capc masked at the gather)
                takes_full = (
                    jnp.zeros((w, n), jnp.int32).at[:, idx].add(takes_m)
                )
                left_after = left.at[idx].add(
                    -(taken[-1][:, None] * req0[None, :])
                )
                return (
                    takes_full,
                    jnp.ones((w,), bool),
                    left_after,
                    jnp.bool_(False),
                    jnp.int32(0),
                )

            return jax.lax.cond(all_ok, commit, replay_wave, left)

        def speculative(left):
            cap = (
                _member_capacity(left[None, :, :], req_c[:, None, :]) * mask_c
            )  # [W, N]
            capc = jnp.minimum(cap, need_c[:, None])
            pooled = jnp.sum(capc, axis=1)
            idx, vals = _coarse_rank(cap, kk, n)  # [W, kk]
            live = (vals < _BIG).astype(jnp.int32)
            cap_k = jnp.take_along_axis(cap, idx, axis=1) * live
            capc_k = jnp.take_along_axis(capc, idx, axis=1) * live
            covered = jnp.sum(capc_k, axis=1) >= need_c
            ok_gang = covered | (pooled < need_c)
            takes_k, feas_k = select_wave(cap_k, capc_k, need_c)
            # conflict check on the union of the wave's candidate columns
            # (every take lands inside it; untouched non-candidates are
            # covered by the per-gang bound — see docstring)
            ucols = idx.reshape(-1)  # [U]
            left_u = jnp.take(left, ucols, axis=0)  # [U, R]
            mask_u = jnp.take(mask_c, ucols, axis=1)  # [W?, U]
            cap0_u = jnp.take(cap, ucols, axis=1)  # [W, U]
            eq = (idx[:, :, None] == ucols[None, None, :]).astype(jnp.int32)
            t_u = jnp.sum(takes_k[:, :, None] * eq, axis=1)  # [W, U]
            deltas_u = t_u[:, :, None] * req_c[:, None, :]  # [W, U, R]
            acc = left_u
            prefixed = []
            for j in range(w):
                prefixed.append(acc)
                acc = jnp.maximum(acc - deltas_u[j], -_BIG)
            cap_pref_u = _member_capacity(
                jnp.stack(prefixed), req_c[:, None, :]
            ) * mask_u
            conflict = jnp.any(cap_pref_u != cap0_u) | ~jnp.all(ok_gang)

            def fast(left):
                gang_rows = jax.lax.broadcasted_iota(
                    jnp.int32, (w, kk), 0
                )
                # .add, not .set: sentinel slots may alias a real node's
                # index (their take is 0 — capc masked at the gather)
                takes_full = (
                    jnp.zeros((w, n), jnp.int32)
                    .at[gang_rows, idx]
                    .add(takes_k)
                )
                flat = (takes_k[:, :, None] * req_c[:, None, :]).reshape(
                    w * kk, r
                )
                left_after = left.at[ucols].add(-flat)
                return (
                    takes_full, feas_k, left_after, jnp.bool_(False),
                    jnp.int32(0),
                )

            return jax.lax.cond(conflict, replay_wave, fast, left)

        takes_out, feas_out, left, conflict, dense_n = jax.lax.cond(
            mega_ok, mega, speculative, left
        )
        return left, (takes_out, feas_out, conflict, mega_ok, dense_n)

    left, (takes, placed, conflicts, megas, dense_ns) = jax.lax.scan(
        step, left0, xs
    )
    takes = takes.reshape(g_pad, n)[:g]
    placed = placed.reshape(g_pad)[:g]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed_full = jnp.zeros((g,), bool).at[order].set(placed)
    if with_stats:
        return alloc, placed_full, left, (conflicts, megas, dense_ns)
    return alloc, placed_full, left


def assign_gangs_topk_sharded(left0, group_req, remaining, fit_mask, order,
                              mesh, wave: int = 8, k: int = 16,
                              with_stats: bool = False):
    """Node-sharded hierarchical top-K scan: ``assign_gangs_topk``
    composed with the PR-6 sharding discipline (``assign_gangs_sharded``).
    Same inputs/outputs as the wavefront scan, bit-identical to the serial
    scan, with the carried ``[N, R]`` leftover partitioned over the mesh.

    Each shard coarse-ranks ONLY its contiguous node slice (its local
    top-K by the global composite (tightness, global index) key); the
    per-wave merge all-gathers one ``[S, W, payload]`` summary — the
    local candidates' composite keys + need-clipped capacities + pooled
    sums, a few KB, never node state — and every shard derives the
    identical global top-K (the K smallest composites of the S·K gathered
    candidates: each shard's members of the global top-K are necessarily
    in its local top-K). The exact selection then runs REPLICATED on the
    merged ``[W, K]`` summary slices, and each shard applies only the
    takes landing in its own global-index range (winner-applies-locally —
    no leftover ever crosses shards). The wavefront conflict check runs
    shard-local on the union columns each shard owns and reduces to one
    psum bit, so the fast-path budget is ≤ 2 summary-sized collectives
    per wave (mega waves: 1 — the commit decision is replicated summary
    arithmetic). Demoted waves replay gang-at-a-time with one summary
    all-gather per gang whose payload also carries the full ``[_BINS]``
    tightness histogram, so the dense-column replay (a gang whose
    candidates cannot cover its need) is served by ``_hist_select`` from
    the SAME gather — no conditional collectives anywhere: every branch
    decision is computed from replicated summary data, identical on all
    shards.

    Stats and demotion semantics match ``assign_gangs_topk``.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec

    n, r = left0.shape
    g = group_req.shape[0]
    w = max(int(wave), 2)
    axes = _shard_axes(mesh)
    s = int(np.prod([mesh.shape[a] for a in axes]))
    per_group_mask = fit_mask.shape[0] != 1
    if per_group_mask and fit_mask.shape[0] != g:
        raise ValueError(
            f"fit_mask rows {fit_mask.shape[0]} must be 1 or match "
            f"group count {g}"
        )

    # node-axis shard padding (zero rows: capacity 0 under any mask)
    n_pad = -(-n // s) * s
    nl = n_pad // s
    kk_l = max(1, min(int(k), nl))      # local candidates per shard
    kk = max(2, min(int(k), s * kk_l))  # merged global candidate width
    left_p = left0
    mask = fit_mask.astype(jnp.int32)
    if n_pad != n:
        left_p = jnp.pad(left_p, ((0, n_pad - n), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, n_pad - n)))

    # gang-axis wave chunking, identical to assign_gangs_wavefront
    steps = -(-g // w)
    g_pad = steps * w
    gr = jnp.take(group_req, order, axis=0)
    rem = jnp.take(remaining, order, axis=0)
    if per_group_mask:
        mask = jnp.take(mask, order, axis=0)
    if g_pad != g:
        gr = jnp.pad(gr, ((0, g_pad - g), (0, 0)))
        rem = jnp.pad(rem, ((0, g_pad - g),))
        if per_group_mask:
            mask = jnp.pad(mask, ((0, g_pad - g), (0, 0)))
    gr_w = gr.reshape(steps, w, r)
    rem_w = rem.reshape(steps, w)
    if per_group_mask:
        mask_w = mask.reshape(steps, w, n_pad)
        mask_uni = jnp.all(mask_w == mask_w[:, :1], axis=(1, 2))
    else:
        mask_w = mask  # [1, n_pad]
        mask_uni = jnp.ones((steps,), bool)
    mega_need_max = (2**31 - 1) // max(n_pad, 1)

    def shard_body(left_l, gr_w, rem_w, mask_l, mask_uni):
        sid = jnp.int32(0)
        for name in axes:
            sid = sid * mesh.shape[name] + jax.lax.axis_index(name)
        off = sid * nl
        earlier = (
            jax.lax.broadcasted_iota(jnp.int32, (s, 1, 1), 0) < sid
        )  # [S,1,1]
        bins3 = jax.lax.broadcasted_iota(jnp.int32, (1, _BINS, 1), 1)

        def local_hist(key_l, capc_l):
            return jnp.sum(
                jnp.where(key_l[:, None, :] == bins3, capc_l[:, None, :], 0),
                axis=2,
            )  # [W?, _BINS]

        def local_rank(cap_l):
            """Local coarse pass with GLOBAL composite keys: cap_l is
            [..., nl]; the composite uses off+pos so merged candidates
            order by (tightness, global node index). Same two-level
            block rank (and the same caller-must-mask sentinel contract)
            as the single-device coarse pass."""
            pos = off + jax.lax.broadcasted_iota(
                jnp.int32, cap_l.shape, cap_l.ndim - 1
            )
            return _coarse_rank(cap_l, kk_l, n_pad, pos=pos)

        def merge_topk(vals_l, extra_l):
            """ONE summary all-gather per wave: local candidate
            composites + their payload columns + trailing pooled scalars.
            Returns (merged composite [.., kk], merged payload columns
            gathered at the same positions, summed pooled scalars)."""
            packed = jnp.concatenate(
                [vals_l] + extra_l["cols"] + [extra_l["sums"]], axis=-1
            )
            gathered = jax.lax.all_gather(packed, axes)  # [S, ..., P]
            lead = gathered.shape[1:-1]
            vals_all = jnp.moveaxis(
                gathered[..., :kk_l], 0, -2
            ).reshape(lead + (s * kk_l,))
            ncols = len(extra_l["cols"])
            cols_all = [
                jnp.moveaxis(
                    gathered[..., (i + 1) * kk_l:(i + 2) * kk_l], 0, -2
                ).reshape(lead + (s * kk_l,))
                for i in range(ncols)
            ]
            sums = jnp.sum(gathered[..., (ncols + 1) * kk_l:], axis=0)
            neg, pos = jax.lax.top_k(-vals_all, kk)
            vals_m = -neg
            cols_m = [
                jnp.take_along_axis(c, pos, axis=-1) for c in cols_all
            ]
            return vals_m, cols_m, sums

        def decode(vals_m):
            """(key, global idx, owned-local idx, owned mask) from merged
            composites; sentinel entries decode to harmless masked-out
            rows (their need-clipped capacity is 0)."""
            key = jnp.minimum(vals_m // (n_pad + 1), _BINS - 1)
            gidx = vals_m - (vals_m // (n_pad + 1)) * (n_pad + 1)
            own = (vals_m < _BIG) & (gidx >= off) & (gidx < off + nl)
            lidx = jnp.clip(gidx - off, 0, nl - 1)
            return key, gidx, own, lidx

        def step(left, chunk):
            if per_group_mask:
                req_c, need_c, uni_mask, mask_c = chunk  # mask_c: [w, nl]
            else:
                req_c, need_c, uni_mask = chunk
                mask_c = mask_l  # [1, nl]
            total_need = jnp.sum(need_c)
            uniform = jnp.all(req_c == req_c[0:1]) & uni_mask
            mega_ok = uniform & (total_need <= mega_need_max)

            def replay_wave(left):
                # gang-at-a-time: one all-gather per gang whose payload
                # carries the fresh local top-K AND the [_BINS] histogram,
                # so both the restricted fill and the dense-column
                # (_hist_select) branch run from the same summary
                takes, feats = [], []
                dense_n = jnp.int32(0)
                for j in range(w):
                    row = mask_c[j] if per_group_mask else mask_c[0]
                    cap_j = (
                        _member_capacity(left, req_c[j][None, :]) * row
                    )  # [nl]
                    capc_j = jnp.minimum(cap_j, need_c[j])
                    key_j = jnp.minimum(cap_j, _BINS - 1)
                    lidx_j, vals_j = local_rank(cap_j[None, :])
                    # sentinel slots may alias a real node: mask their
                    # capacity out of the summary (_coarse_rank contract)
                    live_j = (vals_j[0] < _BIG).astype(jnp.int32)
                    capc_jk = (jnp.take(capc_j, lidx_j[0]) * live_j)[None, :]
                    hist_j = local_hist(key_j[None, :], capc_j[None, :])
                    packed = jnp.concatenate(
                        [
                            vals_j,
                            capc_jk,
                            jnp.sum(capc_j)[None, None],
                            hist_j,
                        ],
                        axis=-1,
                    )  # [1, 2*kk_l + 1 + _BINS]
                    gathered = jax.lax.all_gather(packed, axes)
                    vals_all = gathered[:, 0, :kk_l].reshape(-1)
                    capc_all = gathered[:, 0, kk_l:2 * kk_l].reshape(-1)
                    pooled_j = jnp.sum(gathered[:, 0, 2 * kk_l])
                    hists = gathered[:, :, 2 * kk_l + 1:]  # [S, 1, _BINS]
                    neg, pos = jax.lax.top_k(-vals_all, kk)
                    vals_m = -neg
                    capc_m = jnp.take(capc_all, pos)
                    key_m, gidx_m, own_m, l_m = decode(vals_m)
                    covered = jnp.sum(capc_m) >= need_c[j]
                    use_restricted = covered | (pooled_j < need_c[j])

                    def restricted(_):
                        t_k, f = _select_best_fit(
                            key_m[None, :], capc_m[None, :], need_c[j]
                        )
                        take = (
                            jnp.zeros((nl,), jnp.int32)
                            .at[l_m]
                            .add(jnp.where(own_m, t_k[0], 0))
                        )
                        return take, f

                    def dense_col(_):
                        bin_tot = jnp.sum(hists, axis=0)  # [1, _BINS]
                        shard_off = jnp.sum(
                            jnp.where(earlier, hists, 0), axis=0
                        )
                        t, f = _hist_select(
                            bin_tot, shard_off, key_j[None, :],
                            capc_j[None, :], need_c[j][None],
                        )
                        return t[0], f[0]

                    take_j, feas_j = jax.lax.cond(
                        use_restricted, restricted, dense_col, None
                    )
                    left = left - take_j[:, None] * req_c[j][None, :]
                    dense_n = dense_n + (~use_restricted).astype(jnp.int32)
                    takes.append(take_j)
                    feats.append(feas_j)
                return (
                    jnp.stack(takes), jnp.stack(feats), left,
                    jnp.bool_(True), dense_n,
                )

            def mega(left):
                # uniform-wave aggregate stream on merged candidates;
                # the commit decision is replicated summary arithmetic —
                # ONE collective for the whole wave
                req0 = req_c[0]
                row = mask_c[0]
                cap0 = _member_capacity(left, req0[None, :]) * row  # [nl]
                capc_t = jnp.minimum(cap0, total_need)
                # raw capacities capped high enough that every min() in
                # the feasibility algebra is unchanged (see local mega)
                need_max = jnp.max(need_c)
                capx = jnp.minimum(cap0, total_need + need_max)
                lidx, vals_l = local_rank(cap0[None, :])
                # sentinel slots may alias a real node: mask their
                # capacities out of the summary (_coarse_rank contract)
                live_l = (vals_l[0] < _BIG).astype(jnp.int32)
                capc_lk = (jnp.take(capc_t, lidx[0]) * live_l)[None, :]
                capx_lk = (jnp.take(capx, lidx[0]) * live_l)[None, :]
                pooled_need = jnp.sum(
                    jnp.minimum(cap0[None, :], need_c[:, None]), axis=1
                )  # [W] local
                vals_m, (capc_m, capx_m), sums = merge_topk(
                    vals_l,
                    {"cols": [capc_lk, capx_lk],
                     "sums": pooled_need[None, :]},
                )
                vals_m, capc_m, capx_m = vals_m[0], capc_m[0], capx_m[0]
                pooled_need_g = sums[0]  # [W] global
                key_m, gidx_m, own_m, l_m = decode(vals_m)
                covered = jnp.sum(capc_m) >= total_need
                prefix = _cumsum(capc_m[None, :], axis=1)[0] - capc_m
                bounds = jnp.concatenate(
                    [jnp.zeros((1,), jnp.int32), jnp.cumsum(need_c)]
                )
                taken = jnp.clip(
                    bounds[:, None] - prefix[None, :], 0, capc_m[None, :]
                )  # [W+1, kk]
                cand_entry = jnp.minimum(capx_m[None, :], need_c[:, None])
                cand_post = jnp.minimum(
                    capx_m[None, :] - taken[:-1], need_c[:, None]
                )
                feas = (
                    pooled_need_g
                    - jnp.sum(cand_entry, axis=1)
                    + jnp.sum(cand_post, axis=1)
                ) >= need_c
                all_ok = covered & jnp.all(feas)

                def commit(left):
                    takes_m = taken[1:] - taken[:-1]  # [W, kk]
                    owned_takes = jnp.where(own_m[None, :], takes_m, 0)
                    takes_full = (
                        jnp.zeros((w, nl), jnp.int32)
                        .at[:, l_m]
                        .add(owned_takes)
                    )
                    stream_take = jnp.where(own_m, taken[-1], 0)
                    left_after = left.at[l_m].add(
                        -(stream_take[:, None] * req0[None, :])
                    )
                    return (
                        takes_full,
                        jnp.ones((w,), bool),
                        left_after,
                        jnp.bool_(False),
                        jnp.int32(0),
                    )

                return jax.lax.cond(all_ok, commit, replay_wave, left)

            def speculative(left):
                cap = (
                    _member_capacity(left[None, :, :], req_c[:, None, :])
                    * mask_c
                )  # [w, nl]
                capc = jnp.minimum(cap, need_c[:, None])
                pooled_l = jnp.sum(capc, axis=1)  # [w] local
                lidx, vals_l = local_rank(cap)  # [w, kk_l]
                # sentinel slots may alias a real node: mask their
                # capacity out of the summary (_coarse_rank contract)
                capc_lk = jnp.take_along_axis(capc, lidx, axis=1) * (
                    vals_l < _BIG
                ).astype(jnp.int32)
                vals_m, (capc_m,), sums = merge_topk(
                    vals_l,
                    {"cols": [capc_lk], "sums": pooled_l[:, None]},
                )  # vals_m/capc_m: [w, kk]
                pooled = sums[:, 0]  # [w] global
                key_m, gidx_m, own_m, l_m = decode(vals_m)
                covered = jnp.sum(capc_m, axis=1) >= need_c
                ok_gang = covered | (pooled < need_c)
                takes_k, feas_k = _select_best_fit_wave(
                    key_m, capc_m, need_c
                )
                # conflict check: each shard verifies the union columns
                # IT OWNS under the exclusive prefix of replicated takes,
                # reduced to one bit
                ucols_g = gidx_m.reshape(-1)  # [U] global
                own_u = own_m.reshape(-1)
                l_u = l_m.reshape(-1)
                left_u = jnp.take(left, l_u, axis=0)  # [U, R]
                mask_u = jnp.take(mask_c, l_u, axis=1)  # [W?, U]
                cap0_u = (
                    _member_capacity(
                        left_u[None, :, :], req_c[:, None, :]
                    ) * mask_u
                )  # [w, U] — wave-entry capacities of the union columns
                eq = (
                    gidx_m[:, :, None] == ucols_g[None, None, :]
                ).astype(jnp.int32) * own_m[:, :, None].astype(jnp.int32)
                t_u = jnp.sum(
                    (takes_k * own_m.astype(jnp.int32))[:, :, None] * eq,
                    axis=1,
                )  # [w, U] — owned take mass per union column
                deltas_u = t_u[:, :, None] * req_c[:, None, :]
                acc = left_u
                prefixed = []
                for j in range(w):
                    prefixed.append(acc)
                    acc = jnp.maximum(acc - deltas_u[j], -_BIG)
                cap_pref_u = _member_capacity(
                    jnp.stack(prefixed), req_c[:, None, :]
                ) * mask_u
                conflict_l = jnp.any(
                    (cap_pref_u != cap0_u) & own_u[None, :]
                ).astype(jnp.int32)
                bad = conflict_l + (~jnp.all(ok_gang)).astype(jnp.int32)
                conflict = jax.lax.psum(bad, axes) > 0

                def fast(left):
                    gang_rows = jax.lax.broadcasted_iota(
                        jnp.int32, (w, kk), 0
                    )
                    owned_takes = jnp.where(own_m, takes_k, 0)
                    takes_full = (
                        jnp.zeros((w, nl), jnp.int32)
                        .at[gang_rows, l_m]
                        .add(owned_takes)
                    )
                    flat = (
                        owned_takes[:, :, None] * req_c[:, None, :]
                    ).reshape(w * kk, r)
                    left_after = left.at[l_u].add(-flat)
                    return (
                        takes_full, feas_k, left_after, jnp.bool_(False),
                        jnp.int32(0),
                    )

                return jax.lax.cond(conflict, replay_wave, fast, left)

            takes_out, feas_out, left, conflict, dense_n = jax.lax.cond(
                mega_ok, mega, speculative, left
            )
            return left, (takes_out, feas_out, conflict, mega_ok, dense_n)

        xs = (gr_w, rem_w, mask_uni)
        if per_group_mask:
            xs = xs + (mask_l,)
        left_l, (takes, placed, conflicts, megas, dense_ns) = jax.lax.scan(
            step, left_l, xs
        )
        return left_l, takes, placed, conflicts, megas, dense_ns

    P = PartitionSpec
    mask_in_spec = (
        P(None, None, axes) if per_group_mask else P(None, axes)
    )
    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(axes, None),
            P(None, None, None),
            P(None, None),
            mask_in_spec,
            P(None),
        ),
        out_specs=(
            P(axes, None),
            P(None, None, axes),
            P(None, None),
            P(None),
            P(None),
            P(None),
        ),
        check_rep=False,
    )
    left_after, takes, placed, conflicts, megas, dense_ns = sharded(
        left_p, gr_w, rem_w, mask_w, mask_uni
    )
    takes = takes.reshape(g_pad, n_pad)[:g, :n]
    placed = placed.reshape(g_pad)[:g]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed_full = jnp.zeros((g,), bool).at[order].set(placed)
    left_after = left_after[:n]
    if with_stats:
        return alloc, placed_full, left_after, (conflicts, megas, dense_ns)
    return alloc, placed_full, left_after


def _select_best_fit_wave(key_rows, capc_rows, need):
    """Vmapped ``_select_best_fit`` over summary candidate rows: ``cap``
    is passed as the (already clamped) tightness key — the selection only
    ever consumes ``min(cap, _BINS-1)``, so the key is a sufficient
    stand-in when raw capacities did not ride the summary."""
    def _one(key_r, capc_r, nd):
        take2d, feas = _select_best_fit(
            key_r[None, :], capc_r[None, :], nd
        )
        return take2d[0], feas

    return jax.vmap(_one)(key_rows, capc_rows, need)


# Process-wide gate for the wavefront scan (mirrors _pallas_enabled): a
# compile/runtime failure on the wavefront path disables it for the process
# and batches fall back to the serial scan. List-wrapped for lock-free
# mutation from worker threads (same benign-race contract as
# _pallas_enabled).
_wave_enabled = [True]

_WAVE_ENV = "BST_SCAN_WAVE"
_wave_env_warned = [False]


def _scan_wave_from_env() -> int:
    """Parse the env-gated wave width: 0/unset/1 = serial scan (the
    fallback), anything else bucketed to a static width
    (ops.bucketing.wave_width_bucket) so jit signatures stay bounded.
    Guarded by the same try/except-fallback idiom as
    BST_CHURN_PIPELINE_DEPTH (benchmarks/ladder.py): a typo'd knob must
    degrade to the always-working serial path, never crash a batch."""
    raw = os.environ.get(_WAVE_ENV, "")
    if not raw:
        return 0
    try:
        requested = int(raw)
    except ValueError:
        if not _wave_env_warned[0]:
            _wave_env_warned[0] = True
            import sys

            print(
                f"ignoring unparseable {_WAVE_ENV}={raw!r}; "
                "using the serial assignment scan",
                file=sys.stderr,
            )
        return 0
    from .bucketing import wave_width_bucket

    return wave_width_bucket(requested)


def _disable_wave(e: Exception) -> None:
    _wave_enabled[0] = False
    import warnings

    warnings.warn(
        f"wavefront assignment scan disabled after failure: {e!r}; "
        "falling back to the serial lax.scan path"
    )


# Process-wide gate for the node-sharded scan rung (mirrors _wave_enabled):
# a compile/runtime failure on the sharded merge path demotes mesh batches
# to the replicated-scan layout for the process, without touching the
# wave/pallas gates (the rungs are independent features). Same lock-free
# benign-race contract as the other gates.
_sharded_enabled = [True]

_SHARD_ENV = "BST_SCAN_SHARDED"

# Wave width the sharded scan runs when BST_SCAN_WAVE is unset: the merge
# collective count is G/W per batch, so the sharded rung never runs
# serial-width (W<2 would spend one collective per gang for no batching).
_SHARD_DEFAULT_WAVE = 8


def _scan_sharded_from_env() -> bool:
    """BST_SCAN_SHARDED: default ON (the sharded merge is bit-identical by
    construction and mesh batches fall back to the replicated rung on any
    failure); "0"/"false"/"off" pins mesh batches to the replicated scan."""
    return os.environ.get(_SHARD_ENV, "").strip().lower() not in (
        "0", "false", "off", "no",
    )


def _disable_sharded(e: Exception) -> None:
    _sharded_enabled[0] = False
    import warnings

    warnings.warn(
        f"node-sharded assignment scan disabled after failure: {e!r}; "
        "mesh batches fall back to the replicated-scan layout"
    )


def scan_sharded_active() -> bool:
    """True when the next mesh batch will take the node-sharded scan rung
    (env knob + process gate). Input-placement call sites use this to pick
    the matching layout (``shard_snapshot_args(..., flat_nodes=...)``) —
    placing node state in the 2-D scoring layout while the scan runs the
    sharded rung makes GSPMD reshard the [N,R] lanes at the shard_map
    boundary, exactly the node-state movement the rung exists to avoid.
    The sharded top-K rung composes with (and rides) the same layout."""
    return _sharded_enabled[0] and _scan_sharded_from_env()


# Process-wide gate for the hierarchical top-K scan rung (mirrors
# _sharded_enabled): a compile/runtime failure on the top-K path demotes
# batches to the next ladder rung (sharded on a mesh, else the wavefront/
# serial ladder) for the process, without touching the other gates. Same
# lock-free benign-race contract as every gate here.
_topk_enabled = [True]

_TOPK_ENV = "BST_SCAN_TOPK"
_topk_env_warned = [False]


def _scan_topk_from_env() -> int:
    """Parse the env-gated candidate width for the hierarchical top-K
    scan: 0/unset = rung off (the dense ladder below it), anything else
    bucketed to a static width (ops.bucketing.topk_bucket) so jit
    signatures stay bounded. Same parse-guard idiom as BST_SCAN_WAVE: a
    typo'd knob degrades to the dense ladder, never crashes a batch."""
    raw = os.environ.get(_TOPK_ENV, "")
    if not raw:
        return 0
    try:
        requested = int(raw)
    except ValueError:
        if not _topk_env_warned[0]:
            _topk_env_warned[0] = True
            import sys

            print(
                f"ignoring unparseable {_TOPK_ENV}={raw!r}; "
                "using the dense assignment-scan ladder",
                file=sys.stderr,
            )
        return 0
    from .bucketing import topk_bucket

    return topk_bucket(requested)


def _disable_topk(e: Exception) -> None:
    _topk_enabled[0] = False
    import warnings

    warnings.warn(
        f"hierarchical top-K assignment scan disabled after failure: "
        f"{e!r}; batches fall back to the dense scan ladder"
    )


def scan_topk_active() -> bool:
    """True when the next batch will attempt the hierarchical top-K rung
    (env knob + process gate)."""
    return _topk_enabled[0] and _scan_topk_from_env() > 0


# Max distinct nodes one gang's compact assignment can report; a gang of M
# members spans <= M nodes, so this only truncates gangs wider than 128
# nodes (the dense `assignment` matrix remains authoritative on device).
ASSIGNMENT_TOP_K = 128


@partial(
    jax.jit,
    static_argnames=(
        "use_pallas", "top_k", "scan_mesh", "scan_wave", "scan_shard",
        "scan_topk", "policy_terms", "policy_weights",
    ),
)
def schedule_batch(alloc_lanes, requested, group_req, remaining, fit_mask,
                   group_valid, order, use_pallas: bool = False,
                   top_k: int = ASSIGNMENT_TOP_K, scan_mesh=None,
                   scan_wave: int = 0, scan_shard: bool = False,
                   scan_topk: int = 0, policy_cols=None,
                   policy_terms: tuple = (), policy_weights: tuple = ()):
    """Fused full-batch oracle: leftover -> capacity -> feasibility -> scores
    -> greedy gang assignment, one XLA computation.

    Jitted as ONE computation (``use_pallas`` static): a batch is a single
    dispatch + single async result, so a high-latency host<->device link
    (the axon tunnel) pays one round-trip, not one per sub-kernel — the
    eager ``top_k``/packing tail alone cost ~10x the batch compute there.

    ``use_pallas=True`` (single TPU device) swaps the assignment scan for
    the fused VMEM-resident Pallas kernel (ops.pallas_assign), which
    handles both the broadcast [1,N] mask and the per-group [G,N] mask;
    the GSPMD-sharded path keeps the lax.scan form (a pallas_call is a
    black box to the partitioner).

    ``scan_wave`` > 1 (the BST_SCAN_WAVE knob, bucketed —
    ops.bucketing.wave_width_bucket) selects the wavefront assignment
    scan: up to ``scan_wave`` gangs placed per sequential step,
    bit-identical to the serial scan (``assign_gangs_wavefront``; the
    pallas path uses its chunked-grid wavefront kernel variant). 0 = the
    serial scan, the always-working fallback.

    ``scan_topk`` > 0 (the BST_SCAN_TOPK knob, bucketed —
    ops.bucketing.topk_bucket) selects the hierarchical top-K scan: each
    wave's exact selection runs on gathered [W, K] candidate slices with
    demotion-backed bit-identity (``assign_gangs_topk``); on a mesh with
    ``scan_shard`` it composes with the node-sharded merge
    (``assign_gangs_topk_sharded``). The XL-tier rung.

    This is the ``fit()`` of SURVEY.md §7: everything the control plane needs
    for one scheduling batch in a single device round-trip.

    ``policy_cols`` (+ static ``policy_terms``/``policy_weights``) selects
    the POLICY rung: the composite-key serial scan
    (``assign_gangs_policy``), with the hard-mask terms also folded into
    the batch-head capacity matrix so feasibility/scores agree with what
    the scan will refuse to take. The wavefront/sharded/top-K rungs
    explicitly demote when policies are active (docs/policy.md).

    Output discipline: the (G,N) tensors (capacity/scores/assignment) are
    BIG — fetching them over the host link costs more than computing them
    (measured ~10x the batch time at 5k nodes). Hosts should fetch only the
    O(G) vectors plus the compact top-K assignment, and pull individual
    (G,·) rows on demand (see core.oracle_scorer).
    """
    policy_on = policy_cols is not None and bool(policy_terms)
    left = left_resources(alloc_lanes, requested)
    cap = group_capacity(left, group_req, fit_mask)
    if policy_on:
        # hard-mask policy terms (anti-affinity) shape the head capacity
        # too: a node the policy scan will never take must not answer
        # Filter/feasibility as if it could
        from ..policy.terms import compose_keep_dense

        _prio, _aff, p_anti, _gd, p_node_hash, _nd = policy_cols
        cap = cap * compose_keep_dense(policy_terms, p_anti, p_node_hash)
    feasible = gang_feasible(cap, remaining, group_valid)
    scores = score_nodes(cap)
    if scan_mesh is not None and not scan_shard:
        # GSPMD layout for multi-chip batches: the O(G*N*R) scoring above
        # runs sharded, but the greedy gang scan is SEQUENTIAL over groups
        # with a carried [N,R] leftover — partitioned inputs drag
        # collectives through every one of its G steps (measured 6x SLOWER
        # than one device on an 8-way mesh; benchmarks/sharding_scaling.py).
        # Replicating its inputs costs a one-time handful of collectives
        # (5 in the measured module, SHARDING_r03.json), after which every
        # device runs the scan locally with zero per-step traffic.
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(scan_mesh, PartitionSpec())
        scan_left, scan_gr, scan_rem, scan_fm = (
            jax.lax.with_sharding_constraint(x, repl)
            for x in (left, group_req, remaining, fit_mask)
        )
        if policy_on:
            # the policy scan is serial like the base scan: its columns
            # ride replicated too, or GSPMD drags per-step collectives
            # through the G-step loop (the SHARDING_r03 failure mode)
            policy_cols = tuple(
                jax.lax.with_sharding_constraint(x, repl)
                for x in policy_cols
            )
    else:
        scan_left, scan_gr, scan_rem, scan_fm = (
            left, group_req, remaining, fit_mask,
        )
    wave_stats = None
    topk_stats = None
    if policy_on:
        # the policy rung: composite-key serial scan. Takes precedence
        # over every parallel rung — the wavefront/sharded/top-K fast
        # paths assume the selection key is a function of capacity alone
        # and must demote rather than commit wrong-composite waves
        # (dispatch_batch already strips them; this guard keeps direct
        # schedule_batch callers honest too).
        p_prio, p_aff, p_anti, p_gdom, p_nhash, p_ndom = policy_cols
        assignment, placed, left_after = assign_gangs_policy(
            scan_left, scan_gr, scan_rem, scan_fm, order,
            p_prio, p_aff, p_anti, p_gdom, p_nhash, p_ndom,
            policy_terms=policy_terms, policy_weights=policy_weights,
        )
    elif scan_topk > 0:
        # Hierarchical top-K rung (the XL tier): coarse-rank candidates,
        # exact selection on [G, K] gathered slices, demotion-backed
        # bit-identity (docs/scan_parallelism.md "Hierarchical top-K").
        # Composes with the node-sharded merge when the mesh layout is
        # live; otherwise runs on the (replicated) single-device layout.
        topk_wave = scan_wave if scan_wave > 1 else _SHARD_DEFAULT_WAVE
        if scan_mesh is not None and scan_shard:
            assignment, placed, left_after, topk_stats = (
                assign_gangs_topk_sharded(
                    scan_left, scan_gr, scan_rem, scan_fm, order,
                    mesh=scan_mesh, wave=topk_wave, k=scan_topk,
                    with_stats=True,
                )
            )
        else:
            assignment, placed, left_after, topk_stats = assign_gangs_topk(
                scan_left, scan_gr, scan_rem, scan_fm, order,
                wave=topk_wave, k=scan_topk, with_stats=True,
            )
        wave_stats = topk_stats[:2]
    elif scan_mesh is not None and scan_shard:
        # Node-sharded wavefront scan (the partitioned path that finally
        # wins): each shard scores only its node slice and the per-wave
        # merge moves [S, W, _BINS] summary ints — never node state. The
        # replicated layout above stays the fallback rung
        # (docs/scan_parallelism.md "Sharded merge").
        assignment, placed, left_after, wave_stats = assign_gangs_sharded(
            scan_left, scan_gr, scan_rem, scan_fm, order, mesh=scan_mesh,
            wave=scan_wave if scan_wave > 1 else _SHARD_DEFAULT_WAVE,
            with_stats=True,
        )
    elif use_pallas:
        from .pallas_assign import assign_gangs_pallas

        assignment, placed, left_after = assign_gangs_pallas(
            scan_left, scan_gr, scan_rem, scan_fm, order, wave=scan_wave
        )
    elif scan_wave > 1:
        # with_stats costs nothing extra: the per-wave conflict/mega flags
        # are already carried through the scan; surfacing them feeds the
        # serving-path wave metrics (bst_scan_wave_*) that were previously
        # only computed inside benchmarks/scan_split.py
        assignment, placed, left_after, wave_stats = assign_gangs_wavefront(
            scan_left, scan_gr, scan_rem, scan_fm, order, wave=scan_wave,
            with_stats=True,
        )
    else:
        assignment, placed, left_after = assign_gangs(
            scan_left, scan_gr, scan_rem, scan_fm, order
        )
    placed = placed & group_valid
    # top_k: static width of the compact assignment readback. The default
    # covers any gang; callers that know the batch's max remaining (see
    # execute_batch_host) shrink it — the top-K rows dominate the per-batch
    # host-link bytes, so a tight K is a direct fetch-latency win.
    k = min(top_k, assignment.shape[1])
    assign_counts, assign_nodes = jax.lax.top_k(assignment, k)
    out = {
        "left": left,
        "capacity": cap,
        "gang_feasible": feasible,
        "scores": scores,
        "assignment": assignment,
        "assignment_nodes": assign_nodes,
        "assignment_counts": assign_counts,
        "placed": placed,
        "left_after": left_after,
    }
    if wave_stats is not None:
        out["wave_conflicts"], out["wave_megas"] = wave_stats
    if topk_stats is not None:
        out["topk_demotions"] = topk_stats[2]
    if assignment.shape[1] <= 2**15:
        # Compact fetch: (node << 16 | count) halves the host-link bytes for
        # the top-K assignment — the bulk of the per-batch result transfer.
        # Counts saturate at 65535 (far above any per-node member count; the
        # dense `assignment` stays exact on device).
        out["assignment_packed"] = (
            assign_nodes * (2**16) + jnp.minimum(assign_counts, 2**16 - 1)
        )
    return out


def batch_top_k(n_bucket: int, remaining_max: int) -> int:
    """Static top-K width ``execute_batch_host`` uses for a batch.

    A gang's take touches at most ``remaining`` distinct nodes, so the
    batch-wide max bounds the useful readback width. Rounded up to a power
    of two and FLOORED at 16: every batch whose widest gang needs <= 16
    nodes shares one jit signature (a churn loop's remaining_max jitters
    tick to tick; per-value signatures would recompile mid-loop). Exposed so
    tick-loop callers can fold the tier into their recompile accounting and
    warm() the tiers they expect (ops.rescore.ChurnRescorer)."""
    return min(
        ASSIGNMENT_TOP_K,
        n_bucket,
        max(16, 1 << (max(remaining_max, 1) - 1).bit_length()),
    )


def _batch_blob_impl(alloc_lanes, requested, group_req, remaining, fit_mask,
                     group_valid, order, min_member, scheduled, matched,
                     ineligible, creation_rank, use_pallas: bool = False,
                     pack_assignment: bool = True,
                     top_k: int = ASSIGNMENT_TOP_K, scan_mesh=None,
                     scan_wave: int = 0, scan_shard: bool = False,
                     scan_topk: int = 0, policy_cols=None,
                     policy_terms: tuple = (), policy_weights: tuple = ()):
    """One device computation for a whole control-plane batch: the fused
    oracle + findMaxPG, with every O(G) host-needed output concatenated into
    a single int32 blob. On a high-latency host<->device link (the axon
    tunnel) the per-batch cost is then exactly one dispatch + one fetch
    round-trip; the (G,N) tensors stay behind as device handles.

    Blob layout (G = group bucket, K = top-K):
      [0:G)        placed (0/1)
      [G:2G)       gang_feasible (0/1)
      [2G:3G)      progress (findMaxPG per-group progress)
      [3G]         best group index
      [3G+1]       best_exists (0/1)
      [3G+2:...]   assignment top-K: packed (node<<16|count), G*K — or, when
                   ``pack_assignment=False``, nodes then counts, 2*G*K
      [tail..]     wavefront scan stats, ONLY when the lax wavefront scan
                   ran (scan_wave > 1 and not use_pallas), the node-
                   sharded scan did (scan_shard), or the top-K scan did
                   (scan_topk): 3 int32 — waves-per-batch (sequential
                   steps), conflict-demoted waves (serial replays),
                   uniform-fastpath waves — plus a 4th int32 (dense-
                   column demotions) on the top-K rung only. Static per
                   jit signature, so collect_batch slices by the same
                   predicate.
    """
    out = schedule_batch(alloc_lanes, requested, group_req, remaining,
                         fit_mask, group_valid, order, use_pallas=use_pallas,
                         top_k=top_k, scan_mesh=scan_mesh,
                         scan_wave=scan_wave, scan_shard=scan_shard,
                         scan_topk=scan_topk, policy_cols=policy_cols,
                         policy_terms=policy_terms,
                         policy_weights=policy_weights)
    best, exists, progress = find_max_group(min_member, scheduled, matched,
                                            ineligible, creation_rank)
    if pack_assignment:
        tail = out["assignment_packed"].reshape(-1)
    else:
        tail = jnp.concatenate(
            [out["assignment_nodes"].reshape(-1),
             out["assignment_counts"].reshape(-1)]
        )
    parts = [
        out["placed"].astype(jnp.int32),
        out["gang_feasible"].astype(jnp.int32),
        progress.astype(jnp.int32),
        jnp.stack([best, exists.astype(jnp.int32)]),
        tail,
    ]
    if "wave_conflicts" in out:
        conflicts, megas = out["wave_conflicts"], out["wave_megas"]
        stat_parts = [
            jnp.full((1,), conflicts.shape[0], jnp.int32),
            conflicts.astype(jnp.int32).sum(keepdims=True),
            megas.astype(jnp.int32).sum(keepdims=True),
        ]
        if "topk_demotions" in out:
            stat_parts.append(
                out["topk_demotions"].astype(jnp.int32).sum(keepdims=True)
            )
        parts.append(jnp.concatenate(stat_parts))
    blob = jnp.concatenate(parts)
    if scan_mesh is not None:
        # The blob concatenates pieces with MIXED shardings (gang_feasible
        # rides the groups axis; the packed assignment tail is replicated
        # off the replicated scan). Left to GSPMD, the concatenate resolves
        # through a partial-sum representation and every element comes back
        # multiplied by the node-axis shard count — the "shard-tiled
        # indexes" bug the multi-device sidecar shipped to clients
        # (ROADMAP PR-1 open item; node<<16|count decodes as node*S,
        # count*S). Pinning the blob replicated forces a gather instead of
        # the psum and the host copy is exact on every mesh shape.
        from jax.sharding import NamedSharding, PartitionSpec

        blob = jax.lax.with_sharding_constraint(
            blob, NamedSharding(scan_mesh, PartitionSpec())
        )
    return blob, out


_BLOB_STATICS = ("use_pallas", "pack_assignment", "top_k", "scan_mesh",
                 "scan_wave", "scan_shard", "scan_topk", "policy_terms",
                 "policy_weights")
_batch_blob = jax.jit(_batch_blob_impl, static_argnames=_BLOB_STATICS)
# Donated variant for the double-buffered dispatch-ahead pipeline: the two
# [N, R] inputs (alloc, requested) are donated so XLA can reuse their
# device memory for the same-shaped outputs (left / left_after) instead of
# allocating a third copy per in-flight batch. Callers MUST hand it
# freshly device_put buffers they will not touch again — dispatch_batch's
# donate path does exactly that, and the window-2 in-flight cap means the
# buffer being donated for batch N+1 is never one batch N still reads
# (the A/B alternation: each dispatch's H2D lands in a new buffer while
# the previous one is still owned by the in-flight computation).
_batch_blob_donated = jax.jit(
    _batch_blob_impl, static_argnames=_BLOB_STATICS, donate_argnums=(0, 1)
)

# In-flight fused batches (dispatched, not yet collected), process-wide:
# the pipelining observability the dispatch-ahead paths hang off.
_inflight_lock = threading.Lock()
_inflight_count = [0]  # guarded-by: _inflight_lock


def _note_inflight(delta: int) -> None:
    from ..utils.metrics import DEFAULT_REGISTRY

    with _inflight_lock:
        _inflight_count[0] += delta
        count = _inflight_count[0]
    DEFAULT_REGISTRY.gauge(
        "bst_oracle_inflight_batches",
        "Fused oracle batches dispatched to the device and not yet "
        "collected (>1 means the pipeline is overlapping batches)",
    ).set(float(count))


def donation_supported() -> bool:
    """Whether input-buffer donation buys anything on this backend.
    CPU donation is a per-call warning and a no-op; BST_DONATE=0/1
    overrides the backend default."""
    env = os.environ.get("BST_DONATE", "").strip()
    if env in ("0", "1"):
        return env == "1"
    return jax.default_backend() in ("tpu", "gpu")


class PendingBatch:
    """An in-flight fused batch: dispatched, device->host copy started, not
    yet synced. Produced by ``dispatch_batch``; ``collect_batch`` is the
    sync point. Holding one of these while doing other host work (packing
    the next snapshot, admission bookkeeping, sleeping out a tick interval)
    hides the host<->device link round-trip — the dominant per-batch cost on
    a tunneled TPU — behind that work."""

    __slots__ = (
        "blob", "out", "pack", "used_pallas", "_rerun", "blob_np",
        "mask_mode", "used_wave", "compiled", "n_bucket", "g_bucket",
        "pinned", "used_shard", "shard_count", "used_topk", "used_policy",
    )

    def __init__(
        self, blob, out, pack, used_pallas, rerun, blob_np=None,
        mask_mode="broadcast", used_wave=0, compiled=None,
        n_bucket=0, g_bucket=0, pinned=False, used_shard=False,
        shard_count=0, used_topk=0, used_policy=False,
    ):
        self.blob = blob
        self.out = out
        self.pack = pack
        self.used_pallas = used_pallas
        self._rerun = rerun
        # already-fetched host copy (a dispatch-side fallback proves the
        # scan path by fetching; don't pay the link round-trip twice)
        self.blob_np = blob_np
        self.mask_mode = mask_mode
        # wavefront width this batch ran with (0 = serial scan): collect's
        # blame policy needs to know which optional path was live
        self.used_wave = used_wave
        # oracle device telemetry (docs/observability.md): did this
        # dispatch compile a new executable (jit-cache miss — the 20-40s
        # cold-TPU stall class), and which bucket shape did it run
        self.compiled = compiled
        self.n_bucket = n_bucket
        self.g_bucket = g_bucket
        # dispatched under a forced_scan_rung pin (replay/identity audit):
        # collect-side failures never permanently disable serving features
        self.pinned = pinned
        # node-sharded scan rung (assign_gangs_sharded) + the mesh's
        # device count: collect's blame policy and telemetry need both
        self.used_shard = used_shard
        self.shard_count = shard_count
        # hierarchical top-K rung: the candidate width this batch ran
        # with (0 = rung off); collect's blame + tail slicing need it
        self.used_topk = used_topk
        # policy rung (assign_gangs_policy): policy batches run a single
        # rung (no ladder — a policy batch has no semantically-equivalent
        # fallback), so collect's blame policy must not rerun them serial
        self.used_policy = used_policy


def dispatch_batch(
    batch_args, progress_args, scan_mesh=None, donate: bool = False,
    policy=None,
) -> PendingBatch:
    """Launch one fused batch + max-progress selection WITHOUT waiting for
    the result, and start an async device->host copy of the packed O(G)
    blob. Compilation (including a Pallas Mosaic lowering failure) surfaces
    here synchronously; device execution and the transfer proceed in the
    background until ``collect_batch``.

    ``donate=True`` (dispatch-ahead pipeline, docs/pipelining.md) routes
    through the donated jit: the [N, R] alloc/requested inputs are handed
    to XLA for output reuse. The caller must treat those two args as
    CONSUMED after this call — host numpy args are safe (the H2D transfer
    makes the donated buffer fresh every dispatch, which is what keeps a
    donation from ever aliasing an in-flight batch's inputs); pre-placed
    device arrays must not be reused or re-dispatched. No-op on backends
    without donation (CPU) — see ``donation_supported``.

    ``policy`` = ``(policy_cols, policy_terms, policy_weights)`` selects
    the policy rung (assign_gangs_policy): the wavefront / sharded /
    top-K / pallas rungs are EXPLICITLY DEMOTED for the batch (their fast
    paths assume the selection key is a function of capacity alone) and
    there is NO fallback ladder — a serial non-policy rerun would be a
    semantically different plan, so a policy-rung failure surfaces to the
    caller instead of silently serving the wrong composite. Donation is
    skipped (single-rung batches re-raise; a consumed donated buffer
    would make the error unreplayable)."""
    # The fused Pallas scan is single-device TPU only (both mask modes —
    # broadcast [1,N] and per-group [G,N]), and Mosaic lowering is
    # hardware-path-only (tests exercise interpret mode): if a variant
    # fails to compile/run on this chip, fall back to the lax.scan form
    # permanently for the process FOR THAT VARIANT rather than failing
    # every batch.
    mask_mode = "per_group" if batch_args[4].shape[0] != 1 else "broadcast"
    use_pallas = _pallas_enabled[mask_mode] and jax.default_backend() == "tpu"
    # Wavefront width (0 = serial): env-gated, bucketed static, and behind
    # the process-wide gate so one bad lowering degrades to the serial
    # scan instead of failing every batch.
    scan_wave = _scan_wave_from_env() if _wave_enabled[0] else 0
    # Node-sharded scan rung: mesh batches only, env + process gate. Runs
    # at the wavefront width when one is set, else its own default — the
    # per-wave merge collective is the whole point of the rung.
    scan_sharded = scan_mesh is not None and scan_sharded_active()
    # Hierarchical top-K rung (the XL tier): env + process gate; on a
    # mesh it composes with the sharded layout, single-device it runs
    # the local variant. Sits ABOVE the sharded rung in the ladder.
    scan_topk = _scan_topk_from_env() if _topk_enabled[0] else 0
    # replay/identity-audit rung pin (forced_scan_rung): this thread runs
    # the requested rung, with the pallas gates still honored (a pinned
    # pallas rung off-TPU would fail every batch) and the permanent
    # disable-on-failure policy suppressed below. Pins name explicit
    # (pallas, wave, topk) rungs — the sharded mesh variants are never
    # pinned; their recorded batches are verified by CROSS-rung replay
    # identity.
    forced = getattr(_rung_override, "value", None)
    if forced is not None:
        use_pallas = (
            forced[0] and _pallas_enabled[mask_mode]
            and jax.default_backend() == "tpu"
        )
        scan_wave = forced[1]
        scan_topk = forced[2] if len(forced) > 2 else 0
        scan_sharded = False
    policy_cols = policy_terms = policy_weights = None
    if policy is not None:
        # the policy rung demotes every parallel/fused rung for the batch
        # (explicit demotion, docs/policy.md): composite selection runs
        # the serial policy scan only. Rung pins (replays) keep their
        # policy columns — the recorded batch's semantics ride with them.
        policy_cols, policy_terms, policy_weights = policy
        policy_terms = tuple(policy_terms)
        policy_weights = tuple(policy_weights)
        use_pallas = False
        scan_wave = 0
        scan_sharded = False
        scan_topk = 0
        donate = False
    # The packed form saturates per-node counts at 65535; a take can reach
    # the gang's full remaining count on one node, so gate the compact form
    # on the host-side remaining bound and fall back to the exact
    # nodes+counts blob tail for wider gangs (or > 2**15-node buckets, where
    # the node<<16 packing would overflow).
    n_bucket = batch_args[0].shape[0]
    g_bucket = batch_args[2].shape[0]
    remaining_host = np.asarray(batch_args[3])
    remaining_max = int(remaining_host.max(initial=0))
    pack = n_bucket <= 2**15 and remaining_max <= 2**16 - 1
    top_k = batch_top_k(n_bucket, remaining_max)
    donate = donate and donation_supported()
    # Compile-cache hit/miss telemetry: the jit cache growing across this
    # dispatch means a new executable was BUILT (the cold-batch stall
    # class the PR-1 deadline budget absorbs). Private API, so absence
    # degrades to "unknown" (None), never breaks a batch. The donated
    # variant keeps its own cache — track the one this dispatch uses.
    cache_size_fn = getattr(
        _batch_blob_donated if donate else _batch_blob, "_cache_size", None
    )
    try:
        cache_before = cache_size_fn() if cache_size_fn is not None else None
    except Exception:  # noqa: BLE001 — telemetry only
        cache_before = None

    def run(up: bool, wave: int = 0, dn: bool = False, sh: bool = False,
            tk: int = 0):
        fn = _batch_blob_donated if dn else _batch_blob
        if policy_cols is not None:
            return fn(
                *batch_args, *progress_args, use_pallas=False,
                pack_assignment=pack, top_k=top_k, scan_mesh=scan_mesh,
                policy_cols=tuple(policy_cols), policy_terms=policy_terms,
                policy_weights=policy_weights,
            )
        return fn(
            *batch_args, *progress_args, use_pallas=up, pack_assignment=pack,
            top_k=top_k, scan_mesh=scan_mesh, scan_wave=wave, scan_shard=sh,
            scan_topk=tk,
        )

    # Fallback ladder, most-capable first. Each downgrade drops exactly
    # one optional feature, so a failure can be blamed precisely — and
    # only once the downgraded form EXECUTES where the richer one failed
    # (a cache-hit dispatch alone proves nothing, so the fallback forces
    # the device round-trip; the fetched copy is kept for collect). If
    # every rung fails, the problem is the batch/link, not the feature —
    # the original error surfaces. Rungs are (use_pallas, wave, sharded,
    # topk); the hierarchical top-K rung sits on TOP (composing with the
    # sharded layout on a mesh) and demotes to the sharded merge rung,
    # which demotes to the replicated-scan layout with its own
    # wave/pallas ladder.
    ladder_wave = scan_wave if scan_wave > 1 else _SHARD_DEFAULT_WAVE
    attempts = []
    if scan_topk:
        attempts.append((False, ladder_wave, scan_sharded, scan_topk))
    if scan_sharded:
        attempts.append((False, ladder_wave, True, 0))
    attempts.append((use_pallas, scan_wave, False, 0))
    if scan_wave:
        attempts.append((use_pallas, 0, False, 0))
    if use_pallas:
        attempts.append((False, 0, False, 0))
    if policy_cols is not None:
        # single rung, no ladder: a policy batch has no semantically-
        # equivalent fallback (see docstring) — failure surfaces
        attempts = [(False, 0, False, 0)]

    blob_np = None
    blob = out = None
    errors: list = []
    used_pallas, used_wave, used_shard, used_topk = attempts[0]
    dispatch_t0 = time.perf_counter()
    for i, (up, wave, sh, tk) in enumerate(attempts):
        try:
            # only the first rung donates: a fallback rung re-runs from the
            # same caller args, which a donated first attempt may already
            # have consumed on-device — the ladder must stay replayable
            blob, out = run(up, wave, dn=donate and i == 0, sh=sh, tk=tk)
            if i > 0:
                blob_np = np.asarray(jax.device_get(blob))
        except Exception as e:  # noqa: BLE001 — lowering/compile failure
            errors.append(e)
            if i == len(attempts) - 1:
                raise errors[0] from None
            continue
        used_pallas, used_wave, used_shard, used_topk = up, wave, sh, tk
        if i > 0 and forced is None:
            # this rung executed where the one above it failed: the single
            # feature dropped between the two is provably at fault (the
            # top-K rung owns its whole coarse/gather machinery, so its
            # failure blames top-K even when the next rung also changes
            # layout). A PINNED (replay) thread skips the permanent
            # disable: its failure is replay evidence, not a serving-path
            # verdict.
            prev_up, prev_wave, prev_sh, prev_tk = attempts[i - 1]
            if prev_tk and not tk:
                _disable_topk(errors[-1])
            elif prev_sh and not sh:
                _disable_sharded(errors[-1])
            elif prev_wave and not wave and prev_up == up:
                _disable_wave(errors[-1])
            else:
                _disable_pallas(errors[-1], mask_mode)
        break

    dispatch_s = time.perf_counter() - dispatch_t0
    compiled = None
    if cache_before is not None:
        try:
            compiled = cache_size_fn() > cache_before
        except Exception:  # noqa: BLE001 — telemetry only
            compiled = None
    if compiled:
        # compile-ledger feed (utils.profiler): one entry per executable
        # BUILT on a dispatch path, keyed (bucket shape, rung, donated)
        # with the dispatch wall-clock that absorbed it — the
        # cold-compile cost attribution /debug/perf and the persisted
        # JSONL serve. Telemetry only: any failure is swallowed.
        try:
            from ..utils.profiler import COMPILE_LEDGER

            rung = (
                "policy" if policy_cols is not None
                else "topk" if used_topk > 0
                else "sharded" if used_shard
                else "pallas" if used_pallas
                else "wavefront" if used_wave > 1
                else "serial"
            )
            COMPILE_LEDGER.record(
                g_bucket, n_bucket, rung, donate and i == 0, dispatch_s,
                mask_mode=mask_mode, pinned=forced is not None,
                backend=jax.default_backend(),
            )
        except Exception:  # noqa: BLE001 — telemetry only
            pass
    if compiled and scan_mesh is None and forced is None and (
        policy_cols is None
    ):
        # a fresh executable was just built for this bucket shape: analyze
        # its compiled cost in the background (once per shape per process).
        # `i` is the winning ladder rung — only rung 0 dispatches donated,
        # so the analysis lowers the variant that actually compiled.
        # Pinned (replay/identity-audit) threads are excluded like the
        # disable policy above: their rung is not what serves traffic, and
        # latest-variant-wins must never replace the serving entry with
        # the audit rung's numbers.
        try:
            _maybe_analyze_bucket(
                batch_args, progress_args, used_pallas, pack, top_k,
                used_wave, donated=donate and i == 0, scan_topk=used_topk,
            )
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    # Queue the D2H copy now so it rides behind the computation instead of
    # waiting for the collect call (optional API; device_get works without).
    if blob_np is None:
        try:
            blob.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
    _note_inflight(+1)
    return PendingBatch(
        blob, out, pack, used_pallas, run, blob_np, mask_mode,
        used_wave=used_wave, compiled=compiled,
        n_bucket=n_bucket, g_bucket=g_bucket, pinned=forced is not None,
        used_shard=used_shard,
        shard_count=(
            int(np.prod(scan_mesh.devices.shape)) if used_shard else 0
        ),
        used_topk=used_topk,
        used_policy=policy_cols is not None,
    )


def _disable_pallas(e: Exception, mask_mode: str) -> None:
    _pallas_enabled[mask_mode] = False
    import warnings

    warnings.warn(
        f"pallas assignment kernel ({mask_mode} mask) disabled after "
        f"failure: {e!r}; falling back to the lax.scan path for that "
        "mask mode"
    )


def collect_batch(pending: PendingBatch):
    """Sync point for a ``dispatch_batch`` launch: wait for the packed blob,
    unpack the O(G) host vectors, and hand back the (G,N) device handles.
    A device-side kernel failure surfaces here; if the Pallas path was used,
    the batch re-runs once on the lax.scan form before the kernel is blamed
    and permanently disabled (same policy as the synchronous path)."""
    try:
        return _collect_batch_inner(pending)
    finally:
        _note_inflight(-1)


def _collect_batch_inner(pending: PendingBatch):
    used_pallas, used_wave = pending.used_pallas, pending.used_wave
    used_shard, used_topk = pending.used_shard, pending.used_topk
    try:
        blob_np = (
            pending.blob_np
            if pending.blob_np is not None
            else np.asarray(jax.device_get(pending.blob))
        )
        out = pending.out
    except Exception as e:  # noqa: BLE001 — device-side runtime failure
        if (
            not pending.used_pallas
            and not pending.used_wave
            and not pending.used_shard
            and not pending.used_topk
        ):
            raise
        # Only blame (and permanently disable) the optional path — the
        # pallas kernel, the wavefront scan, the sharded merge, or the
        # top-K scan — if the plain serial scan succeeds where it failed;
        # if that fails too, the problem is the batch/link, not the
        # feature — surface it. When several were live, the single rerun
        # cannot separate them; disabling errs toward the always-working
        # path (each re-proves itself never).
        try:
            blob, out = pending._rerun(False)
            blob_np = np.asarray(jax.device_get(blob))
        except Exception:
            raise e from None
        if not pending.pinned:
            if pending.used_topk:
                # the top-K rung owns its whole coarse/gather machinery;
                # its failure says nothing about the dense ladder below
                _disable_topk(e)
            elif pending.used_shard:
                # the sharded rung owns its whole wave machinery; its
                # failure says nothing about the replicated wavefront path
                _disable_sharded(e)
            else:
                if pending.used_pallas:
                    _disable_pallas(e, pending.mask_mode)
                if pending.used_wave:
                    _disable_wave(e)
        # the blob in hand is the serial replicated rerun
        used_pallas, used_wave, used_shard, used_topk = False, 0, False, 0

    g = out["assignment_nodes"].shape[0]
    k = out["assignment_nodes"].shape[1]
    pack = pending.pack
    # the wave-stat triple (plus the top-K demotion count) rides at the
    # very end of the blob, only when the lax wavefront scan (replicated,
    # sharded, or top-K) produced THIS blob (a collect-side serial rerun
    # has none) — slice the assignment tail by its exact static length
    has_wave_stats = (
        (used_wave > 1 and not used_pallas) or used_shard or used_topk > 0
    )
    tail_len = g * k if pack else 2 * g * k
    tail = blob_np[3 * g + 2: 3 * g + 2 + tail_len]
    if pack:
        packed_np = tail.reshape(g, k)
        nodes_np = packed_np >> 16
        counts_np = packed_np & (2**16 - 1)
    else:
        nodes_np = tail[: g * k].reshape(g, k)
        counts_np = tail[g * k:].reshape(g, k)
    telemetry = {
        "used_pallas": bool(used_pallas),
        "wave_width": int(used_wave),
        "mask_mode": pending.mask_mode,
        "compiled": pending.compiled,
        "n_bucket": int(pending.n_bucket),
        "g_bucket": int(pending.g_bucket),
        "scan_sharded": bool(used_shard),
        "scan_topk": int(used_topk),
        "scan_policy": bool(pending.used_policy),
    }
    if used_shard:
        telemetry["shard_count"] = int(pending.shard_count)
    if has_wave_stats:
        stats_np = blob_np[3 * g + 2 + tail_len:]
        if stats_np.shape[0] >= 3:
            telemetry["waves_per_batch"] = int(stats_np[0])
            telemetry["wave_demotions"] = int(stats_np[1])
            telemetry["wave_uniform"] = int(stats_np[2])
        if used_topk > 0 and stats_np.shape[0] >= 4:
            telemetry["topk_demotions"] = int(stats_np[3])
    if used_topk > 0:
        # coarse-pass cost for TRACE_INFO + the flight recorder: measured
        # once per (bucket, K) on a standalone jitted coarse pass (the
        # per-wave capacity sweep + rank), background-landed like the
        # bucket-cost analysis — None until the probe completes
        coarse_s = _coarse_pass_seconds(
            pending.n_bucket, int(out["left"].shape[1]),
            used_wave if used_wave > 1 else _SHARD_DEFAULT_WAVE, used_topk,
        )
        if coarse_s is not None:
            telemetry["coarse_pass_device_seconds"] = coarse_s
    # per-bucket compiled-cost evidence (flops/bytes/collectives), once the
    # background analysis for this shape has landed — rides to the flight
    # recorder and, on the sidecar, back to the client in TRACE_INFO
    cost = bucket_cost_for(pending.g_bucket, pending.n_bucket)
    if cost and "error" not in cost:
        telemetry["bucket_cost"] = cost
    _fold_batch_metrics(telemetry)
    host = {
        "placed": blob_np[:g].astype(bool),
        "gang_feasible": blob_np[g:2 * g].astype(bool),
        "progress": blob_np[2 * g:3 * g],
        "best": blob_np[3 * g],
        "best_exists": bool(blob_np[3 * g + 1]),
        "assignment_nodes": nodes_np,
        "assignment_counts": counts_np,
        "telemetry": telemetry,
    }
    device_result = {"capacity": out["capacity"], "scores": out["scores"]}
    return host, device_result


def _fold_batch_metrics(telemetry: dict) -> None:
    """Serving-path batch telemetry -> Prometheus. This is where the
    wavefront scan stats become live series (previously only computed
    inside benchmarks/scan_split.py — production runs with BST_SCAN_WAVE
    were blind): waves per batch, demotions (serial replays), uniform
    fast-path waves, plus the scan-path mix and the compile-cache misses.
    Called per batch from collect_batch so the in-process scorer and the
    sidecar server both report without extra wiring."""
    from ..utils.metrics import DEFAULT_REGISTRY as reg

    path = (
        "policy"
        if telemetry.get("scan_policy")
        else "topk"
        if telemetry.get("scan_topk", 0) > 0
        else "pallas"
        if telemetry["used_pallas"]
        else "sharded"
        if telemetry.get("scan_sharded")
        else "wavefront" if telemetry["wave_width"] > 1 else "serial"
    )
    # dominant-tenant attribution (utils.tenancy): the scorer arms a
    # thread-local with the batch's top tenant (namespace-derived,
    # cardinality-capped) before dispatch; paths with no tenant identity
    # (the sidecar sees packed arrays, never names) label "-"
    from ..utils.tenancy import current_batch_tenant

    reg.counter(
        "bst_scan_batches_total",
        "Oracle batches by assignment-scan path and dominant tenant",
    ).inc(path=path, tenant=current_batch_tenant() or "-")
    if telemetry.get("scan_topk", 0) > 0:
        reg.gauge(
            "bst_scan_topk_k",
            "Candidate width K of the hierarchical top-K scan (last top-K "
            "batch)",
        ).set(float(telemetry["scan_topk"]))
        reg.counter(
            "bst_topk_demotions_total",
            "Gangs demoted to the dense-column replay because their top-K "
            "candidates could not cover the need while pooled capacity "
            "remained (the K-mistuned signal)",
        ).inc(telemetry.get("topk_demotions", 0))
    if telemetry.get("scan_sharded"):
        reg.gauge(
            "bst_scan_shard_count",
            "Devices the node-sharded assignment scan split the node axis "
            "over (last sharded batch)",
        ).set(float(telemetry.get("shard_count", 0)))
    reg.gauge(
        "bst_scan_sharded_enabled",
        "1 while the node-sharded scan rung is enabled (0 after a failure "
        "permanently demoted mesh batches to the replicated-scan layout)",
    ).set(1.0 if _sharded_enabled[0] else 0.0)
    if telemetry.get("compiled"):
        reg.counter(
            "bst_oracle_compiles_total",
            "Oracle batches that built a new executable (jit-cache miss)",
        ).inc()
    reg.gauge(
        "bst_scan_wave_enabled",
        "1 while the wavefront scan path is enabled (0 after a failure "
        "permanently demoted the process to the serial scan)",
    ).set(1.0 if _wave_enabled[0] else 0.0)
    if "waves_per_batch" in telemetry:
        reg.histogram(
            "bst_scan_waves_per_batch",
            "Sequential wavefront steps per oracle batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        ).observe(float(telemetry["waves_per_batch"]))
        reg.counter(
            "bst_scan_waves_total", "Wavefront steps executed"
        ).inc(telemetry["waves_per_batch"])
        reg.counter(
            "bst_scan_wave_demotions_total",
            "Waves demoted to the serial replay (conflict or infeasible "
            "boundary)",
        ).inc(telemetry["wave_demotions"])
        reg.counter(
            "bst_scan_wave_uniform_total",
            "Waves served by the uniform-demand aggregate fast path",
        ).inc(telemetry["wave_uniform"])


# -- telemetry daemon-thread registry ---------------------------------------
#
# The bucket-cost analysis and the coarse-pass probe below run XLA compiles
# on daemon threads. A daemon thread still inside an XLA call when the
# interpreter tears the runtime down aborts the process ("terminate called
# without an active exception" — the README's long-standing
# --dispatch-ahead --compile-warmer exit crash: every warmer precompile is
# a jit-cache miss, so each spawned one of these analyses, and nothing
# joined them). Every such thread registers here;
# ``drain_telemetry_threads`` is the teardown join, called from
# OracleScorer.drain_background and OracleServer.server_close AFTER their
# batch producers (warmer, refresh/spec threads, executor) stop — stopped
# producers mean no new registrations race the drain.

_telemetry_threads: set = set()  # guarded-by: _telemetry_threads_lock
_telemetry_threads_lock = threading.Lock()


def _spawn_telemetry_thread(target, name: str) -> None:
    t = threading.Thread(target=target, name=name, daemon=True)
    t.start()
    with _telemetry_threads_lock:
        _telemetry_threads.add(t)
        _telemetry_threads.difference_update(
            {x for x in _telemetry_threads if x is not t and not x.is_alive()}
        )


def drain_telemetry_threads(timeout: float = 60.0) -> bool:
    """Join every live telemetry thread (bucket-cost analyses, coarse
    probes). Returns False when one is still alive after ``timeout`` —
    the caller must not let the process (and the XLA runtime) die yet,
    same contract as OracleScorer.drain_background."""
    deadline = time.monotonic() + timeout
    with _telemetry_threads_lock:
        threads = list(_telemetry_threads)
    ok = True
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
        ok = ok and not t.is_alive()
    with _telemetry_threads_lock:
        _telemetry_threads.difference_update(
            {t for t in threads if not t.is_alive()}
        )
    return ok


# -- standalone coarse-pass cost probe (hierarchical top-K telemetry) -------
#
# The coarse pass runs fused inside the jitted scan, so its per-batch cost
# cannot be clocked in-line; instead a daemon thread times ONE standalone
# jitted coarse step (the [W, N, R] capacity sweep + top-K rank — the only
# O(N) work in a top-K wave) per (n_bucket, lanes, wave, K) shape, and
# collect_batch folds the landed figure into batch telemetry /
# TRACE_INFO as ``coarse_pass_device_seconds``. Same background-landing
# discipline as the bucket-cost analysis below.

_coarse_probe: dict = {}  # guarded-by: _coarse_probe_lock
_coarse_probe_lock = threading.Lock()
_coarse_probe_inflight: set = set()  # guarded-by: _coarse_probe_lock


def _coarse_pass_seconds(n_bucket: int, lanes: int, wave: int, k: int):
    """Measured per-wave coarse-pass seconds for a shape, or None while
    the background probe has not landed. BST_BUCKET_COST=0 disables (the
    probe is a compile, same load class as the bucket-cost analysis)."""
    if os.environ.get("BST_BUCKET_COST", "").strip() == "0":
        return None
    key = (int(n_bucket), int(lanes), int(wave), int(k))
    with _coarse_probe_lock:
        if key in _coarse_probe:
            return _coarse_probe[key]
        if key in _coarse_probe_inflight:
            return None
        _coarse_probe_inflight.add(key)

    def _run() -> None:
        import time

        value = None
        try:
            kk = max(2, min(key[3], key[0]))

            @jax.jit
            def coarse(left, req):
                cap = _member_capacity(
                    left[None, :, :], req[:, None, :]
                )
                return _coarse_rank(cap, kk, key[0])

            left = jnp.ones((key[0], key[1]), jnp.int32)
            req = jnp.ones((key[2], key[1]), jnp.int32)
            jax.block_until_ready(coarse(left, req))
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(coarse(left, req))
                times.append(time.perf_counter() - t0)
            value = round(float(np.median(times)), 6)
        except Exception:  # noqa: BLE001 — telemetry only
            value = None
        with _coarse_probe_lock:
            _coarse_probe[key] = value
            _coarse_probe_inflight.discard(key)

    _spawn_telemetry_thread(_run, "coarse-pass-probe")
    return None


# -- per-bucket HLO cost/memory telemetry (docs/observability.md) -----------
#
# When a dispatch BUILDS a new executable (jit-cache miss), a daemon thread
# re-lowers the same blob signature from ShapeDtypeStructs and runs the
# guarded compiled-artifact analyses — cost_analysis / memory_analysis /
# collective instruction counts (parallel.mesh.compiled_cost_summary) — so
# /debug/buckets and TRACE_INFO can say what each bucket shape COSTS
# (flops, bytes, collectives) and the compile warmer's precompile choices
# are explainable rather than just counted. The persistent XLA compilation
# cache (cmd.main._enable_compilation_cache) makes the re-lowering a cache
# read on warm processes. Single-device signatures only: the sharded
# module's collective counts are measured by benchmarks/sharding_scaling.py
# with the real mesh shardings. BST_BUCKET_COST=0 disables.

_bucket_costs: dict = {}  # guarded-by: _bucket_cost_lock
_bucket_cost_lock = threading.Lock()
_bucket_cost_inflight: set = set()  # guarded-by: _bucket_cost_lock


def bucket_cost_report() -> dict:
    """Per-bucket-shape compiled-cost entries, keyed "GxN" — the payload of
    the metrics endpoint's /debug/buckets (utils.metrics)."""
    with _bucket_cost_lock:
        return {
            f"{g}x{n}": dict(entry)
            for (g, n), entry in sorted(_bucket_costs.items())
        }


def bucket_cost_for(g_bucket: int, n_bucket: int):
    """The analyzed cost entry for one bucket shape, or None while the
    analysis has not landed (it runs on a daemon thread)."""
    with _bucket_cost_lock:
        entry = _bucket_costs.get((int(g_bucket), int(n_bucket)))
        return dict(entry) if entry else None


def _maybe_analyze_bucket(batch_args, progress_args, use_pallas: bool,
                          pack: bool, top_k: int, scan_wave: int,
                          donated: bool = False,
                          scan_topk: int = 0) -> None:
    """Kick one background cost analysis for a bucket shape that just
    compiled on the serving path (at most one per (G, N) shape per
    process). Telemetry only: every failure is recorded, never raised."""
    if os.environ.get("BST_BUCKET_COST", "").strip() == "0":
        return
    key = (int(batch_args[2].shape[0]), int(batch_args[0].shape[0]))
    with _bucket_cost_lock:
        existing = _bucket_costs.get(key)
        if existing is not None and (
            existing.get("used_pallas") == bool(use_pallas)
            and existing.get("wave_width") == int(scan_wave)
            and existing.get("donated", False) == bool(donated)
            and existing.get("scan_topk", 0) == int(scan_topk)
        ):
            return
        # a DIFFERENT variant compiled for this shape (e.g. the wave gate
        # was disabled mid-run and serving fell back to serial): re-analyze
        # so the telemetry describes the variant batches actually run,
        # latest-variant-wins
        if key in _bucket_cost_inflight:
            return
        _bucket_cost_inflight.add(key)
    lanes = int(batch_args[0].shape[1])
    mask_rows = int(batch_args[4].shape[0])
    # lower from shape/dtype structs: no array data is retained, and the
    # lowering is identical to what the serving dispatch compiled
    shapes = tuple(
        jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
        for a in (*batch_args, *progress_args)
    )

    def _run() -> None:
        try:
            from ..parallel.mesh import compiled_cost_summary

            # lower the SAME variant the serving dispatch compiled: the
            # donated jit keeps its own cache, so analyzing the
            # non-donated form on a dispatch-ahead path would pay a
            # second full compile per shape purely for telemetry
            fn = _batch_blob_donated if donated else _batch_blob
            compiled = fn.lower(
                *shapes, use_pallas=use_pallas, pack_assignment=pack,
                top_k=top_k, scan_mesh=None, scan_wave=scan_wave,
                scan_topk=scan_topk,
            ).compile()
            entry = {
                "g_bucket": key[0],
                "n_bucket": key[1],
                "lanes": lanes,
                "mask_rows": mask_rows,
                "wave_width": int(scan_wave),
                "used_pallas": bool(use_pallas),
                "donated": bool(donated),
                "scan_topk": int(scan_topk),
                **compiled_cost_summary(compiled),
            }
        except Exception as e:  # noqa: BLE001 — telemetry only
            entry = {"g_bucket": key[0], "n_bucket": key[1],
                     "error": repr(e)[:200]}
        with _bucket_cost_lock:
            _bucket_costs[key] = entry
            _bucket_cost_inflight.discard(key)

    _spawn_telemetry_thread(_run, "bucket-cost-analysis")


def execute_batch_host(batch_args, progress_args, scan_mesh=None,
                       donate: bool = False, policy=None):
    """Run one fused batch + max-progress selection and fetch ONLY the O(G)
    host vectors (as ONE packed transfer — see _batch_blob); the (G,N)
    tensors come back as device handles for lazy row reads. The single
    batch-execution path shared by the in-process scorer (core.oracle_scorer)
    and the sidecar server (service.server) — one place to change when the
    oracle's outputs change. Synchronous form of dispatch_batch +
    collect_batch; pipelined callers (ops.rescore.ChurnRescorer's
    tick_dispatch/tick_collect) use the split halves directly. ``donate``
    follows dispatch_batch's buffer-donation contract (host numpy args
    only); ``policy`` follows dispatch_batch's policy-rung contract."""
    return collect_batch(
        dispatch_batch(
            batch_args, progress_args, scan_mesh, donate=donate,
            policy=policy,
        )
    )
