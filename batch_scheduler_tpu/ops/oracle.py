"""The bin-packing oracle: jitted JAX kernels scoring all PodGroups × all
nodes in one batch.

This replaces the reference's two serial hot loops — per-pod cluster
feasibility (``findMaxPG`` + ``compareClusterResourceAndRequire``, reference
pkg/scheduler/core/core.go:595-632,701-739) and per-node fit
(``singleNodeResource`` + ``compareResourceAndRequire``, core.go:634-699) —
with dense int32 tensor kernels:

- ``left_resources``      per-node leftover = floor(alloc·percent) − requested
- ``group_capacity``      members-per-node capacity matrix cap[G,N]
- ``gang_feasible``       Σ_n cap[g,n] ≥ remaining[g]  (exact, in member
                          counts, so 5k-node sums stay far inside int32 —
                          and *stronger* than the reference's raw resource-sum
                          check, which ignores per-node fragmentation)
- ``find_max_group``      vectorized group-progress argmax (findMaxPG parity)
- ``score_nodes``         per-(group,node) placement ranks for the Score
                          extension point (a stub in the reference,
                          core.go:263-265)
- ``assign_gangs``        greedy whole-batch gang placement via ``lax.scan``
                          over groups in priority order

All kernels take statically-bucketed shapes (see ops.bucketing) and int32
lanes (see ops.lanes); invalid rows are masked, never branched on, so there
is no data-dependent Python control flow under jit.

Determinism note: the reference's findMaxPG tie-break depends on Go map
iteration order, which is randomised (core.go:701-739). ``find_max_group``
resolves ties deterministically: prefer groups with nothing scheduled yet
(same intent as core.go:725-735), then earlier creation rank.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "left_resources",
    "group_capacity",
    "gang_feasible",
    "find_max_group",
    "score_nodes",
    "assign_gangs",
    "schedule_batch",
    "execute_batch_host",
    "dispatch_batch",
    "collect_batch",
    "PendingBatch",
]

# Plain int (not a device array) so pallas kernels can share these helpers
# without capturing traced constants.
_BIG = 2**30

# Largest admissible gang: keeps every need-clipped capacity cumsum in the
# assignment scan exact in int32 (bound proven in assign_gangs' docstring).
# Enforced at the batch boundary (ops.bucketing.pad_oracle_batch).
GANG_MAX = 2**18

# Best-fit ranking buckets for the gang-placement scan. Nodes are ranked
# tightest-first by min(cap, _BINS-1); all nodes that could hold >= _BINS-1
# members of a gang are equally "loose" and tie-break by node index. 128
# covers every realistic per-node member count (the pods lane alone caps a
# node at ~110 members) while keeping the per-step histogram tiny.
_BINS = 128

# Process-wide gate for the fused pallas assignment kernel; flipped off on
# the first hardware failure (see execute_batch_host) or via env var.
# Pallas enablement is PER MASK MODE: a lowering/runtime failure on one
# kernel variant (e.g. the per-group [G,N] mask path) disables only that
# variant — it must not poison the other, independently proven one.
# Read/written from multiple threads (background refresh + scheduling
# cycles) without a lock: a benign race — the worst interleaving runs one
# extra fallback batch and prints a duplicate warning (ADVICE r3); do not
# add invariants here that assume single-threaded access.
_pallas_enabled = {
    mode: os.environ.get("BST_DISABLE_PALLAS", "") != "1"
    for mode in ("broadcast", "per_group")
}


@jax.jit
def _exact_floordiv(num, den):
    """Exact ``num // den`` for int32 ``0 <= num <= 2**30, 1 <= den <= 2**30``.

    XLA lowers int32 division on TPU to a long scalar expansion; over the
    oracle's (G,N,R) tensor that one op dominates the whole batch. Instead:
    two float32 reciprocal-multiply Newton steps, then an integer fixup.
    Error analysis: the first quotient is within ``0.5 + q*2**-22`` of exact,
    so the int32 residual ``num - q*den`` never overflows given the 2**30
    operand bound (enforced at pack time, ops.lanes.LANE_MAX); the second
    step lands within 1, and the fixups make it exact.
    """
    inv = 1.0 / den.astype(jnp.float32)
    q = jnp.round(num.astype(jnp.float32) * inv).astype(jnp.int32)
    r = num - q * den
    q = q + jnp.round(r.astype(jnp.float32) * inv).astype(jnp.int32)
    r = num - q * den
    q = jnp.where(r < 0, q - 1, q)
    q = jnp.where(num - q * den >= den, q + 1, q)
    return q


def _cumsum(x, axis):
    """Inclusive cumsum via Hillis-Steele doubling (log2(n) shift-adds).

    ``jnp.cumsum`` has no Pallas TPU (Mosaic) lowering; static pad/slice/add
    do. Used by ``_select_best_fit`` on BOTH the lax.scan and pallas paths so
    the two stay bit-identical (int32 addition is associative, so the
    doubling order changes nothing).
    """
    n = x.shape[axis]
    shift = 1
    while shift < n:
        zeros = jnp.zeros_like(jax.lax.slice_in_dim(x, 0, shift, axis=axis))
        shifted = jax.lax.concatenate(
            [zeros, jax.lax.slice_in_dim(x, 0, n - shift, axis=axis)], axis
        )
        x = x + shifted
        shift *= 2
    return x


def _select_best_fit(cap, capc, need):
    """Tightest-first take vector for one gang: the histogram threshold
    selection documented in assign_gangs. Shapes are [1, N] (2-D so the iota
    lowers on TPU inside pallas kernels too); returns (take[1,N], feasible).
    THE single definition of the selection — shared by the lax.scan path and
    the fused pallas kernel (ops.pallas_assign)."""
    feasible = jnp.sum(capc) >= need
    key = jnp.minimum(cap, _BINS - 1)  # tightness bucket (0 = no fit)
    bins = jax.lax.broadcasted_iota(jnp.int32, (_BINS, 1), 0)
    bin_totals = jnp.sum(
        jnp.where(key == bins, capc, 0), axis=1, keepdims=True
    )  # [_BINS, 1]
    cum_bins = _cumsum(bin_totals, axis=0)
    # threshold bucket: first where cumulative capacity covers the gang
    thresh = jnp.minimum(jnp.sum((cum_bins < need).astype(jnp.int32)), _BINS - 1)
    cum_at = jnp.sum(jnp.where(bins == thresh, cum_bins, 0))
    tot_at = jnp.sum(jnp.where(bins == thresh, bin_totals, 0))
    rem_t = need - (cum_at - tot_at)
    in_t = key == thresh
    capc_t = jnp.where(in_t, capc, 0)
    prefix_t = _cumsum(capc_t, axis=1) - capc_t
    take = jnp.where(
        key < thresh, capc, jnp.where(in_t, jnp.clip(rem_t - prefix_t, 0, capc), 0)
    )
    return take * feasible.astype(jnp.int32), feasible


def _member_capacity(left, req):
    """min over resource lanes of floor(left/req), for req-positive lanes —
    how many members of a demand row fit in a leftover row. Broadcasts:
    callers shape ``left``/``req`` to a common [..., R]. Inputs are clamped
    into the ``_exact_floordiv`` domain; the ``_BIG`` ceiling only saturates
    values already rejected at the batch boundary (ops.bucketing LANE_MAX /
    GANG_MAX checks). Shared by the batch kernel and the assignment scan;
    the pallas kernel (ops.pallas_assign) carries the same computation in
    its transposed [R, N] layout — change both together."""
    safe_req = jnp.clip(req, 1, _BIG)
    lpos = jnp.clip(left, 0, _BIG)
    per_lane = jnp.where(req > 0, _exact_floordiv(lpos, safe_req), _BIG)
    return jnp.min(per_lane, axis=-1)


@partial(jax.jit, static_argnames=("percent_num", "percent_den"))
def left_resources(alloc, requested, percent_num: int = 1, percent_den: int = 1):
    """Per-node leftover lanes: floor(alloc·percent) − requested.

    ``percent`` is the reference's reserve fraction (1.0 for the max-progress
    group, 0.7 otherwise — core.go:140,161,656-659), expressed as an exact
    integer ratio. Computed as ``q·num + (r·num)//den`` with ``q,r =
    divmod(alloc, den)`` so nothing overflows int32.
    """
    if percent_num == percent_den:
        scaled = alloc
    else:
        q = alloc // percent_den
        r = alloc - q * percent_den
        scaled = q * percent_num + (r * percent_num) // percent_den
    return scaled - requested


@jax.jit
def group_capacity(left, group_req, fit_mask):
    """cap[G,N]: how many members of group g fit on node n's leftover.

    cap = min over lanes with req>0 of left // req, clamped to >= 0, masked
    by per-(group,node) placement feasibility (selector/taints/validity).
    A node with any overcommitted lane naturally yields 0.
    """
    cap = _member_capacity(left[None, :, :], group_req[:, None, :])  # [G,N]
    return cap.astype(jnp.int32) * fit_mask.astype(jnp.int32)


@jax.jit
def gang_feasible(cap, remaining, group_valid):
    """ok[G]: total member capacity across the cluster covers the gang's
    still-unbound members. Per-node capacity is clipped at the gang's own
    remaining count before summing — equivalent (one node covering the whole
    gang already saturates the test) and it keeps the N-node sum exact in
    int32 even when sparse requests make single-node capacities huge."""
    total = jnp.sum(jnp.minimum(cap, remaining[:, None]), axis=1)
    return (total >= remaining) & group_valid


@jax.jit
def find_max_group(min_member, scheduled, matched, ineligible, creation_rank):
    """Vectorized findMaxPG (reference core.go:701-739).

    progress = (matched + scheduled)·1000 // min_member for eligible groups
    (not yet released, has a representative pod, still needs members), else 0
    when fully satisfied. Returns (best_index, best_exists, progress[G]).

    Tie-break (deterministic, unlike the Go map iteration): prefer groups
    with scheduled == 0, then earlier creation rank.
    """
    g = min_member.shape[0]
    needs = (min_member - scheduled) > 0
    denom = jnp.maximum(min_member, 1)
    progress = jnp.where(needs, (matched + scheduled) * 1000 // denom, 0)
    progress = jnp.clip(progress, 0, 2047)
    eligible = ~ineligible
    key = (
        progress.astype(jnp.int32) * (2 * g + 2)
        + jnp.where(scheduled == 0, g + 1, 0)
        + (g - creation_rank.astype(jnp.int32))
    )
    key = jnp.where(eligible, key, -1)
    best = jnp.argmax(key)
    return best.astype(jnp.int32), key[best] >= 0, progress


@jax.jit
def score_nodes(cap):
    """score[G,N] for the Score extension point: best-fit ranking.

    Higher is better. Nodes that fit at least one member are ranked by
    *tightness* — fewer future members would fit, so gangs pack densely and
    large holes stay available for wide pods. Infeasible nodes score
    INT32_MIN-ish.
    """
    fits = cap > 0
    return jnp.where(fits, _BIG - cap, -_BIG)


@jax.jit
def assign_gangs(left0, group_req, remaining, fit_mask, order):
    """Greedy whole-batch gang placement.

    Walks groups in ``order`` (priority-first, the queue-sort order) with a
    ``lax.scan`` carrying the live leftover lanes; each step places all of a
    gang's remaining members at once — best-fit packing onto the
    tightest-fitting nodes — iff the whole gang fits (all-or-nothing at the
    batch level, which *is* gang semantics). Returns:

    - alloc[G,N]  members of group g placed on node n (rows in group index
      space, not scan order)
    - placed[G]   whether the gang was placed this batch
    - left[N,R]   leftover lanes after all placements

    One jitted call replaces the pod-at-a-time Permit accounting loop for
    batch mode; the reference has no equivalent (it admits gangs pod by pod
    against a TTL cache, core.go:268-309).

    Each scan step selects tightest-first WITHOUT a sort: nodes are bucketed
    by clamped capacity (``_BINS`` histogram). Buckets strictly below the
    threshold bucket (the one where cumulative capacity crosses ``need``)
    contribute every member they can hold; buckets above contribute none; so
    only the threshold bucket needs within-bucket (node-index) ordering —
    one O(N) cumsum. A sort-based selection costs O(N log^2 N) bitonic
    stages on TPU per group; this matches the sorted greedy exactly for
    per-node capacities < _BINS-1 (above that, equally-loose nodes tie-break
    by index instead of by capacity). Exactness bound: cumulative sums use
    capacities clipped at ``need``, so they stay inside int32 for any gang
    with min_member <= 2**18 — far above any real gang.

    ``fit_mask`` may be ``[G,N]`` or a broadcast ``[1,N]`` row (the
    no-selectors/no-taints common case — see ops.snapshot; an 8 MB host
    transfer becomes 8 KB).
    """
    n = left0.shape[0]
    mask_rows = fit_mask.shape[0]

    def body(left, g):
        req = jnp.take(group_req, g, axis=0)
        mask = jnp.take(fit_mask, jnp.minimum(g, mask_rows - 1), axis=0)
        need = jnp.take(remaining, g)

        cap = _member_capacity(left, req[None, :]) * mask  # [N] >= 0
        capc = jnp.minimum(cap, need)  # overflow-safe effective capacity
        take2d, feasible = _select_best_fit(cap[None, :], capc[None, :], need)
        take = take2d[0]
        left = left - take[:, None] * req[None, :]
        return left, (take, feasible)

    left, (takes, placed) = jax.lax.scan(body, left0, order, unroll=4)
    g = group_req.shape[0]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed = jnp.zeros((g,), bool).at[order].set(placed)
    return alloc, placed, left


# Max distinct nodes one gang's compact assignment can report; a gang of M
# members spans <= M nodes, so this only truncates gangs wider than 128
# nodes (the dense `assignment` matrix remains authoritative on device).
ASSIGNMENT_TOP_K = 128


@partial(jax.jit, static_argnames=("use_pallas", "top_k", "scan_mesh"))
def schedule_batch(alloc_lanes, requested, group_req, remaining, fit_mask,
                   group_valid, order, use_pallas: bool = False,
                   top_k: int = ASSIGNMENT_TOP_K, scan_mesh=None):
    """Fused full-batch oracle: leftover -> capacity -> feasibility -> scores
    -> greedy gang assignment, one XLA computation.

    Jitted as ONE computation (``use_pallas`` static): a batch is a single
    dispatch + single async result, so a high-latency host<->device link
    (the axon tunnel) pays one round-trip, not one per sub-kernel — the
    eager ``top_k``/packing tail alone cost ~10x the batch compute there.

    ``use_pallas=True`` (single TPU device) swaps the assignment scan for
    the fused VMEM-resident Pallas kernel (ops.pallas_assign), which
    handles both the broadcast [1,N] mask and the per-group [G,N] mask;
    the GSPMD-sharded path keeps the lax.scan form (a pallas_call is a
    black box to the partitioner).

    This is the ``fit()`` of SURVEY.md §7: everything the control plane needs
    for one scheduling batch in a single device round-trip.

    Output discipline: the (G,N) tensors (capacity/scores/assignment) are
    BIG — fetching them over the host link costs more than computing them
    (measured ~10x the batch time at 5k nodes). Hosts should fetch only the
    O(G) vectors plus the compact top-K assignment, and pull individual
    (G,·) rows on demand (see core.oracle_scorer).
    """
    left = left_resources(alloc_lanes, requested)
    cap = group_capacity(left, group_req, fit_mask)
    feasible = gang_feasible(cap, remaining, group_valid)
    scores = score_nodes(cap)
    if scan_mesh is not None:
        # GSPMD layout for multi-chip batches: the O(G*N*R) scoring above
        # runs sharded, but the greedy gang scan is SEQUENTIAL over groups
        # with a carried [N,R] leftover — partitioned inputs drag
        # collectives through every one of its G steps (measured 6x SLOWER
        # than one device on an 8-way mesh; benchmarks/sharding_scaling.py).
        # Replicating its inputs costs a one-time handful of collectives
        # (5 in the measured module, SHARDING_r03.json), after which every
        # device runs the scan locally with zero per-step traffic.
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(scan_mesh, PartitionSpec())
        scan_left, scan_gr, scan_rem, scan_fm = (
            jax.lax.with_sharding_constraint(x, repl)
            for x in (left, group_req, remaining, fit_mask)
        )
    else:
        scan_left, scan_gr, scan_rem, scan_fm = (
            left, group_req, remaining, fit_mask,
        )
    if use_pallas:
        from .pallas_assign import assign_gangs_pallas

        assignment, placed, left_after = assign_gangs_pallas(
            scan_left, scan_gr, scan_rem, scan_fm, order
        )
    else:
        assignment, placed, left_after = assign_gangs(
            scan_left, scan_gr, scan_rem, scan_fm, order
        )
    placed = placed & group_valid
    # top_k: static width of the compact assignment readback. The default
    # covers any gang; callers that know the batch's max remaining (see
    # execute_batch_host) shrink it — the top-K rows dominate the per-batch
    # host-link bytes, so a tight K is a direct fetch-latency win.
    k = min(top_k, assignment.shape[1])
    assign_counts, assign_nodes = jax.lax.top_k(assignment, k)
    out = {
        "left": left,
        "capacity": cap,
        "gang_feasible": feasible,
        "scores": scores,
        "assignment": assignment,
        "assignment_nodes": assign_nodes,
        "assignment_counts": assign_counts,
        "placed": placed,
        "left_after": left_after,
    }
    if assignment.shape[1] <= 2**15:
        # Compact fetch: (node << 16 | count) halves the host-link bytes for
        # the top-K assignment — the bulk of the per-batch result transfer.
        # Counts saturate at 65535 (far above any per-node member count; the
        # dense `assignment` stays exact on device).
        out["assignment_packed"] = (
            assign_nodes * (2**16) + jnp.minimum(assign_counts, 2**16 - 1)
        )
    return out


def batch_top_k(n_bucket: int, remaining_max: int) -> int:
    """Static top-K width ``execute_batch_host`` uses for a batch.

    A gang's take touches at most ``remaining`` distinct nodes, so the
    batch-wide max bounds the useful readback width. Rounded up to a power
    of two and FLOORED at 16: every batch whose widest gang needs <= 16
    nodes shares one jit signature (a churn loop's remaining_max jitters
    tick to tick; per-value signatures would recompile mid-loop). Exposed so
    tick-loop callers can fold the tier into their recompile accounting and
    warm() the tiers they expect (ops.rescore.ChurnRescorer)."""
    return min(
        ASSIGNMENT_TOP_K,
        n_bucket,
        max(16, 1 << (max(remaining_max, 1) - 1).bit_length()),
    )


@partial(
    jax.jit,
    static_argnames=("use_pallas", "pack_assignment", "top_k", "scan_mesh"),
)
def _batch_blob(alloc_lanes, requested, group_req, remaining, fit_mask,
                group_valid, order, min_member, scheduled, matched,
                ineligible, creation_rank, use_pallas: bool = False,
                pack_assignment: bool = True,
                top_k: int = ASSIGNMENT_TOP_K, scan_mesh=None):
    """One device computation for a whole control-plane batch: the fused
    oracle + findMaxPG, with every O(G) host-needed output concatenated into
    a single int32 blob. On a high-latency host<->device link (the axon
    tunnel) the per-batch cost is then exactly one dispatch + one fetch
    round-trip; the (G,N) tensors stay behind as device handles.

    Blob layout (G = group bucket, K = top-K):
      [0:G)        placed (0/1)
      [G:2G)       gang_feasible (0/1)
      [2G:3G)      progress (findMaxPG per-group progress)
      [3G]         best group index
      [3G+1]       best_exists (0/1)
      [3G+2:...]   assignment top-K: packed (node<<16|count), G*K — or, when
                   ``pack_assignment=False``, nodes then counts, 2*G*K
    """
    out = schedule_batch(alloc_lanes, requested, group_req, remaining,
                         fit_mask, group_valid, order, use_pallas=use_pallas,
                         top_k=top_k, scan_mesh=scan_mesh)
    best, exists, progress = find_max_group(min_member, scheduled, matched,
                                            ineligible, creation_rank)
    if pack_assignment:
        tail = out["assignment_packed"].reshape(-1)
    else:
        tail = jnp.concatenate(
            [out["assignment_nodes"].reshape(-1),
             out["assignment_counts"].reshape(-1)]
        )
    blob = jnp.concatenate(
        [
            out["placed"].astype(jnp.int32),
            out["gang_feasible"].astype(jnp.int32),
            progress.astype(jnp.int32),
            jnp.stack([best, exists.astype(jnp.int32)]),
            tail,
        ]
    )
    return blob, out


class PendingBatch:
    """An in-flight fused batch: dispatched, device->host copy started, not
    yet synced. Produced by ``dispatch_batch``; ``collect_batch`` is the
    sync point. Holding one of these while doing other host work (packing
    the next snapshot, admission bookkeeping, sleeping out a tick interval)
    hides the host<->device link round-trip — the dominant per-batch cost on
    a tunneled TPU — behind that work."""

    __slots__ = (
        "blob", "out", "pack", "used_pallas", "_rerun", "blob_np", "mask_mode"
    )

    def __init__(
        self, blob, out, pack, used_pallas, rerun, blob_np=None,
        mask_mode="broadcast",
    ):
        self.blob = blob
        self.out = out
        self.pack = pack
        self.used_pallas = used_pallas
        self._rerun = rerun
        # already-fetched host copy (a dispatch-side fallback proves the
        # scan path by fetching; don't pay the link round-trip twice)
        self.blob_np = blob_np
        self.mask_mode = mask_mode


def dispatch_batch(batch_args, progress_args, scan_mesh=None) -> PendingBatch:
    """Launch one fused batch + max-progress selection WITHOUT waiting for
    the result, and start an async device->host copy of the packed O(G)
    blob. Compilation (including a Pallas Mosaic lowering failure) surfaces
    here synchronously; device execution and the transfer proceed in the
    background until ``collect_batch``."""
    # The fused Pallas scan is single-device TPU only (both mask modes —
    # broadcast [1,N] and per-group [G,N]), and Mosaic lowering is
    # hardware-path-only (tests exercise interpret mode): if a variant
    # fails to compile/run on this chip, fall back to the lax.scan form
    # permanently for the process FOR THAT VARIANT rather than failing
    # every batch.
    mask_mode = "per_group" if batch_args[4].shape[0] != 1 else "broadcast"
    use_pallas = _pallas_enabled[mask_mode] and jax.default_backend() == "tpu"
    # The packed form saturates per-node counts at 65535; a take can reach
    # the gang's full remaining count on one node, so gate the compact form
    # on the host-side remaining bound and fall back to the exact
    # nodes+counts blob tail for wider gangs (or > 2**15-node buckets, where
    # the node<<16 packing would overflow).
    n_bucket = batch_args[0].shape[0]
    remaining_host = np.asarray(batch_args[3])
    remaining_max = int(remaining_host.max(initial=0))
    pack = n_bucket <= 2**15 and remaining_max <= 2**16 - 1
    top_k = batch_top_k(n_bucket, remaining_max)

    def run(up: bool):
        return _batch_blob(
            *batch_args, *progress_args, use_pallas=up, pack_assignment=pack,
            top_k=top_k, scan_mesh=scan_mesh,
        )

    blob_np = None
    if use_pallas:
        try:
            blob, out = run(True)
        except Exception as e:  # noqa: BLE001 — lowering/compile failure
            # Only blame (and permanently disable) the pallas kernel if the
            # scan path EXECUTES where it failed — a cache-hit dispatch
            # alone proves nothing, so force the device round-trip here (and
            # keep the fetched copy for collect). If that fails too, the
            # problem is the batch/link, not the kernel — surface the
            # original error.
            try:
                blob, out = run(False)
                blob_np = np.asarray(jax.device_get(blob))
            except Exception:
                raise e from None
            _disable_pallas(e, mask_mode)
            use_pallas = False
    else:
        blob, out = run(False)

    # Queue the D2H copy now so it rides behind the computation instead of
    # waiting for the collect call (optional API; device_get works without).
    if blob_np is None:
        try:
            blob.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass
    return PendingBatch(
        blob, out, pack, use_pallas, run, blob_np, mask_mode
    )


def _disable_pallas(e: Exception, mask_mode: str) -> None:
    _pallas_enabled[mask_mode] = False
    import warnings

    warnings.warn(
        f"pallas assignment kernel ({mask_mode} mask) disabled after "
        f"failure: {e!r}; falling back to the lax.scan path for that "
        "mask mode"
    )


def collect_batch(pending: PendingBatch):
    """Sync point for a ``dispatch_batch`` launch: wait for the packed blob,
    unpack the O(G) host vectors, and hand back the (G,N) device handles.
    A device-side kernel failure surfaces here; if the Pallas path was used,
    the batch re-runs once on the lax.scan form before the kernel is blamed
    and permanently disabled (same policy as the synchronous path)."""
    try:
        blob_np = (
            pending.blob_np
            if pending.blob_np is not None
            else np.asarray(jax.device_get(pending.blob))
        )
        out = pending.out
    except Exception as e:  # noqa: BLE001 — device-side runtime failure
        if not pending.used_pallas:
            raise
        # Only blame (and permanently disable) the pallas kernel if the
        # scan path succeeds where it failed; if that fails too, the
        # problem is the batch/link, not the kernel — surface it.
        try:
            blob, out = pending._rerun(False)
            blob_np = np.asarray(jax.device_get(blob))
        except Exception:
            raise e from None
        _disable_pallas(e, pending.mask_mode)

    g = out["assignment_nodes"].shape[0]
    k = out["assignment_nodes"].shape[1]
    pack = pending.pack
    tail = blob_np[3 * g + 2:]
    if pack:
        packed_np = tail.reshape(g, k)
        nodes_np = packed_np >> 16
        counts_np = packed_np & (2**16 - 1)
    else:
        nodes_np = tail[: g * k].reshape(g, k)
        counts_np = tail[g * k:].reshape(g, k)
    host = {
        "placed": blob_np[:g].astype(bool),
        "gang_feasible": blob_np[g:2 * g].astype(bool),
        "progress": blob_np[2 * g:3 * g],
        "best": blob_np[3 * g],
        "best_exists": bool(blob_np[3 * g + 1]),
        "assignment_nodes": nodes_np,
        "assignment_counts": counts_np,
    }
    device_result = {"capacity": out["capacity"], "scores": out["scores"]}
    return host, device_result


def execute_batch_host(batch_args, progress_args, scan_mesh=None):
    """Run one fused batch + max-progress selection and fetch ONLY the O(G)
    host vectors (as ONE packed transfer — see _batch_blob); the (G,N)
    tensors come back as device handles for lazy row reads. The single
    batch-execution path shared by the in-process scorer (core.oracle_scorer)
    and the sidecar server (service.server) — one place to change when the
    oracle's outputs change. Synchronous form of dispatch_batch +
    collect_batch; pipelined callers (ops.rescore.ChurnRescorer's
    tick_dispatch/tick_collect) use the split halves directly."""
    return collect_batch(dispatch_batch(batch_args, progress_args, scan_mesh))
