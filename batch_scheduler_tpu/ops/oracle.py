"""The bin-packing oracle: jitted JAX kernels scoring all PodGroups × all
nodes in one batch.

This replaces the reference's two serial hot loops — per-pod cluster
feasibility (``findMaxPG`` + ``compareClusterResourceAndRequire``, reference
pkg/scheduler/core/core.go:595-632,701-739) and per-node fit
(``singleNodeResource`` + ``compareResourceAndRequire``, core.go:634-699) —
with dense int32 tensor kernels:

- ``left_resources``      per-node leftover = floor(alloc·percent) − requested
- ``group_capacity``      members-per-node capacity matrix cap[G,N]
- ``gang_feasible``       Σ_n cap[g,n] ≥ remaining[g]  (exact, in member
                          counts, so 5k-node sums stay far inside int32 —
                          and *stronger* than the reference's raw resource-sum
                          check, which ignores per-node fragmentation)
- ``find_max_group``      vectorized group-progress argmax (findMaxPG parity)
- ``score_nodes``         per-(group,node) placement ranks for the Score
                          extension point (a stub in the reference,
                          core.go:263-265)
- ``assign_gangs``        greedy whole-batch gang placement via ``lax.scan``
                          over groups in priority order

All kernels take statically-bucketed shapes (see ops.bucketing) and int32
lanes (see ops.lanes); invalid rows are masked, never branched on, so there
is no data-dependent Python control flow under jit.

Determinism note: the reference's findMaxPG tie-break depends on Go map
iteration order, which is randomised (core.go:701-739). ``find_max_group``
resolves ties deterministically: prefer groups with nothing scheduled yet
(same intent as core.go:725-735), then earlier creation rank.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

__all__ = [
    "left_resources",
    "group_capacity",
    "gang_feasible",
    "find_max_group",
    "score_nodes",
    "assign_gangs",
    "schedule_batch",
    "execute_batch_host",
]

_BIG = jnp.int32(2**30)


@partial(jax.jit, static_argnames=("percent_num", "percent_den"))
def left_resources(alloc, requested, percent_num: int = 1, percent_den: int = 1):
    """Per-node leftover lanes: floor(alloc·percent) − requested.

    ``percent`` is the reference's reserve fraction (1.0 for the max-progress
    group, 0.7 otherwise — core.go:140,161,656-659), expressed as an exact
    integer ratio. Computed as ``q·num + (r·num)//den`` with ``q,r =
    divmod(alloc, den)`` so nothing overflows int32.
    """
    if percent_num == percent_den:
        scaled = alloc
    else:
        q = alloc // percent_den
        r = alloc - q * percent_den
        scaled = q * percent_num + (r * percent_num) // percent_den
    return scaled - requested


@jax.jit
def group_capacity(left, group_req, fit_mask):
    """cap[G,N]: how many members of group g fit on node n's leftover.

    cap = min over lanes with req>0 of left // req, clamped to >= 0, masked
    by per-(group,node) placement feasibility (selector/taints/validity).
    A node with any overcommitted lane naturally yields 0.
    """
    req = group_req[:, None, :]  # [G,1,R]
    safe_req = jnp.maximum(req, 1)
    per_lane = jnp.where(req > 0, left[None, :, :] // safe_req, _BIG)  # [G,N,R]
    cap = jnp.min(per_lane, axis=-1)
    return jnp.maximum(cap, 0).astype(jnp.int32) * fit_mask.astype(jnp.int32)


@jax.jit
def gang_feasible(cap, remaining, group_valid):
    """ok[G]: total member capacity across the cluster covers the gang's
    still-unbound members. Exact in int32: capacities are member counts."""
    total = jnp.sum(cap, axis=1)
    return (total >= remaining) & group_valid


@jax.jit
def find_max_group(min_member, scheduled, matched, ineligible, creation_rank):
    """Vectorized findMaxPG (reference core.go:701-739).

    progress = (matched + scheduled)·1000 // min_member for eligible groups
    (not yet released, has a representative pod, still needs members), else 0
    when fully satisfied. Returns (best_index, best_exists, progress[G]).

    Tie-break (deterministic, unlike the Go map iteration): prefer groups
    with scheduled == 0, then earlier creation rank.
    """
    g = min_member.shape[0]
    needs = (min_member - scheduled) > 0
    denom = jnp.maximum(min_member, 1)
    progress = jnp.where(needs, (matched + scheduled) * 1000 // denom, 0)
    progress = jnp.clip(progress, 0, 2047)
    eligible = ~ineligible
    key = (
        progress.astype(jnp.int32) * (2 * g + 2)
        + jnp.where(scheduled == 0, g + 1, 0)
        + (g - creation_rank.astype(jnp.int32))
    )
    key = jnp.where(eligible, key, -1)
    best = jnp.argmax(key)
    return best.astype(jnp.int32), key[best] >= 0, progress


@jax.jit
def score_nodes(cap):
    """score[G,N] for the Score extension point: best-fit ranking.

    Higher is better. Nodes that fit at least one member are ranked by
    *tightness* — fewer future members would fit, so gangs pack densely and
    large holes stay available for wide pods. Infeasible nodes score
    INT32_MIN-ish.
    """
    fits = cap > 0
    return jnp.where(fits, _BIG - cap, -_BIG)


@jax.jit
def assign_gangs(left0, group_req, remaining, fit_mask, order):
    """Greedy whole-batch gang placement.

    Walks groups in ``order`` (priority-first, the queue-sort order) with a
    ``lax.scan`` carrying the live leftover lanes; each step places all of a
    gang's remaining members at once — best-fit packing onto the
    tightest-fitting nodes — iff the whole gang fits (all-or-nothing at the
    batch level, which *is* gang semantics). Returns:

    - alloc[G,N]  members of group g placed on node n (rows in group index
      space, not scan order)
    - placed[G]   whether the gang was placed this batch
    - left[N,R]   leftover lanes after all placements

    One jitted call replaces the pod-at-a-time Permit accounting loop for
    batch mode; the reference has no equivalent (it admits gangs pod by pod
    against a TTL cache, core.go:268-309).
    """
    n = left0.shape[0]

    def body(left, g):
        req = jnp.take(group_req, g, axis=0)
        mask = jnp.take(fit_mask, g, axis=0)
        need = jnp.take(remaining, g)

        safe_req = jnp.maximum(req, 1)
        per_lane = jnp.where(req > 0, left // safe_req, _BIG)
        cap = jnp.maximum(jnp.min(per_lane, axis=-1), 0) * mask

        feasible = jnp.sum(cap) >= need
        # Best-fit: tightest feasible nodes first (stable ties by index).
        rank = jnp.where(cap > 0, cap, _BIG)
        node_order = jnp.argsort(rank, stable=True)
        cap_sorted = jnp.take(cap, node_order)
        before = jnp.cumsum(cap_sorted) - cap_sorted
        take_sorted = jnp.clip(need - before, 0, cap_sorted)
        take = jnp.zeros((n,), jnp.int32).at[node_order].set(
            take_sorted.astype(jnp.int32)
        )
        take = take * feasible.astype(jnp.int32)
        left = left - take[:, None] * req[None, :]
        return left, (take, feasible)

    left, (takes, placed) = jax.lax.scan(body, left0, order)
    g = group_req.shape[0]
    alloc = jnp.zeros((g, n), jnp.int32).at[order].set(takes)
    placed = jnp.zeros((g,), bool).at[order].set(placed)
    return alloc, placed, left


# Max distinct nodes one gang's compact assignment can report; a gang of M
# members spans <= M nodes, so this only truncates gangs wider than 128
# nodes (the dense `assignment` matrix remains authoritative on device).
ASSIGNMENT_TOP_K = 128


@jax.jit
def schedule_batch(alloc_lanes, requested, group_req, remaining, fit_mask,
                   group_valid, order):
    """Fused full-batch oracle: leftover -> capacity -> feasibility -> scores
    -> greedy gang assignment, one XLA computation.

    This is the ``fit()`` of SURVEY.md §7: everything the control plane needs
    for one scheduling batch in a single device round-trip.

    Output discipline: the (G,N) tensors (capacity/scores/assignment) are
    BIG — fetching them over the host link costs more than computing them
    (measured ~10x the batch time at 5k nodes). Hosts should fetch only the
    O(G) vectors plus the compact top-K assignment, and pull individual
    (G,·) rows on demand (see core.oracle_scorer).
    """
    left = left_resources(alloc_lanes, requested)
    cap = group_capacity(left, group_req, fit_mask)
    feasible = gang_feasible(cap, remaining, group_valid)
    scores = score_nodes(cap)
    assignment, placed, left_after = assign_gangs(
        left, group_req, remaining, fit_mask, order
    )
    placed = placed & group_valid
    k = min(ASSIGNMENT_TOP_K, assignment.shape[1])
    assign_counts, assign_nodes = jax.lax.top_k(assignment, k)
    return {
        "left": left,
        "capacity": cap,
        "gang_feasible": feasible,
        "scores": scores,
        "assignment": assignment,
        "assignment_nodes": assign_nodes,
        "assignment_counts": assign_counts,
        "placed": placed,
        "left_after": left_after,
    }


def execute_batch_host(batch_args, progress_args):
    """Run one fused batch + max-progress selection and fetch ONLY the O(G)
    host vectors; the (G,N) tensors come back as device handles for lazy row
    reads. The single batch-execution path shared by the in-process scorer
    (core.oracle_scorer) and the sidecar server (service.server) — one place
    to change when the oracle's outputs change."""
    out = schedule_batch(*batch_args)
    best, exists, progress = find_max_group(*progress_args)
    host = jax.device_get(
        {
            "gang_feasible": out["gang_feasible"],
            "placed": out["placed"],
            "assignment_nodes": out["assignment_nodes"],
            "assignment_counts": out["assignment_counts"],
            "best": best,
            "best_exists": exists,
            "progress": progress,
        }
    )
    device_result = {"capacity": out["capacity"], "scores": out["scores"]}
    return host, device_result
