"""Shape bucketing: pad dynamic pod/node/group counts to a small set of
static shapes so churn re-scores hit the jit cache instead of recompiling.

XLA traces once per distinct input shape; a cluster whose node count drifts
between 4,997 and 5,003 must not trigger six compilations. Buckets are
powers of two (with a smallest bucket of 8 and multiples of the TPU lane
width where it matters), which bounds compilations at O(log max_size) per
rank combination.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "bucket_size",
    "wave_width_bucket",
    "pad_to",
    "pad_rows",
    "pad_oracle_batch",
]

_MIN_BUCKET = 8

# Static widths the wavefront assignment scan compiles for. Powers of two
# between 2 and 32: below 2 the wave degenerates to the serial scan; above
# 32 the batched fast path's [W, N, R] prefix tensors outgrow their win
# (and a single contended wave's serial replay grows linearly with W).
_WAVE_MIN, _WAVE_MAX = 2, 32


def bucket_size(n: int) -> int:
    """Smallest power-of-two bucket >= n (>= 8)."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def wave_width_bucket(w: int) -> int:
    """Static wave-width bucket for the wavefront assignment scan
    (ops.oracle.assign_gangs_wavefront / the BST_SCAN_WAVE knob).

    0 or 1 means "serial scan" and maps to 0; anything else snaps to the
    nearest power of two in [2, 32] so the jitted scan compiles for a
    bounded set of wave shapes no matter what the knob says."""
    if w <= 1:
        return 0
    b = _WAVE_MIN
    while b < w and b < _WAVE_MAX:
        b <<= 1
    return b


def pad_to(arr: np.ndarray, size: int, axis: int = 0, fill=0) -> np.ndarray:
    """Pad ``arr`` along ``axis`` to ``size`` with ``fill``."""
    pad = size - arr.shape[axis]
    if pad < 0:
        raise ValueError(f"array dim {arr.shape[axis]} exceeds bucket {size}")
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths, constant_values=fill)


def pad_rows(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    return pad_to(arr, size, axis=0, fill=fill)


def pad_oracle_batch(
    alloc,
    requested,
    group_req,
    remaining,
    fit_mask,
    group_valid,
    order,
    min_member,
    scheduled,
    matched,
    ineligible,
    creation_rank,
    min_buckets=(0, 0),
):
    """Bucket-pad one oracle batch with the canonical sentinel fills.

    THE single source of truth for what padded rows look like — used by both
    the in-process snapshot packer (ops.snapshot.ClusterSnapshot) and the
    sidecar server (service.server), so the wire path can never drift from
    the local path:

    - padded groups: zero demand, invalid, ineligible for max-progress
      selection, last in creation rank, appended at the tail of the scan
      order (remaining == 0, so they place nothing);
    - padded nodes: zero lanes (capacity 0), masked out of every fit row.

    A broadcast ``[1,N]`` fit mask (uniform-feasibility fast path, see
    ops.snapshot._fit_mask) keeps its single row: padded groups are already
    neutralised by zero demand + group_valid=False, and padded nodes by the
    axis-1 False fill.

    ``min_buckets=(G, N)`` sets floor bucket sizes — churn re-scoring pins
    them to the largest shape seen so a shrinking cluster never triggers a
    fresh compile (ops.rescore sticky buckets).

    Returns ``(batch_args, progress_args)`` ready for
    ``ops.oracle.schedule_batch`` / ``find_max_group``.
    """
    n = alloc.shape[0]
    g = group_req.shape[0]
    nb = max(bucket_size(max(n, 1)), min_buckets[1])
    gb = max(bucket_size(max(g, 1)), min_buckets[0])
    # Enforce the exact-division domain (ops.lanes.LANE_MAX) at the batch
    # boundary: LaneSchema.pack already guards the dict-packing path, but
    # raw-lane snapshots (churn fast path) and the sidecar wire path feed
    # arrays straight through here — out-of-domain lanes would make
    # ops.oracle._exact_floordiv silently wrong, not just imprecise.
    from .lanes import LANE_MAX
    from .oracle import GANG_MAX

    for name, arr in (("alloc", alloc), ("requested", requested),
                      ("group_req", group_req)):
        a = np.asarray(arr)
        if a.size and (np.abs(a.astype(np.int64)) > int(LANE_MAX)).any():
            raise OverflowError(
                f"{name} lanes exceed LANE_MAX (2**30): max abs "
                f"{int(np.abs(a.astype(np.int64)).max())}"
            )
    # The assignment scan and gang_feasible accumulate need-clipped
    # capacities over the node bucket in int32; sum <= need * nb, so the
    # admissible gang size shrinks with the node bucket: need * nb must stay
    # strictly below 2**31. GANG_MAX alone (2**18) is exactly the boundary
    # at an 8192-node bucket and past it for larger buckets.
    gang_bound = min(GANG_MAX, (2**31 - 1) // nb)
    for name, arr, bound in (
        ("remaining", remaining, gang_bound),
        ("min_member", min_member, GANG_MAX),
        ("scheduled", scheduled, GANG_MAX),
        ("matched", matched, GANG_MAX),
    ):
        a = np.asarray(arr)
        if a.size and (np.abs(a.astype(np.int64)) > bound).any():
            raise OverflowError(
                f"{name} exceeds the gang bound ({bound} members at node "
                f"bucket {nb}): max abs {int(np.abs(a.astype(np.int64)).max())}"
            )
    batch_args = (
        pad_rows(np.asarray(alloc, dtype=np.int32), nb),
        pad_rows(np.asarray(requested, dtype=np.int32), nb),
        pad_rows(np.asarray(group_req, dtype=np.int32), gb),
        pad_rows(np.asarray(remaining, dtype=np.int32), gb),
        pad_to(
            np.asarray(fit_mask, dtype=bool)
            if np.asarray(fit_mask).shape[0] == 1
            else pad_rows(np.asarray(fit_mask, dtype=bool), gb, fill=False),
            nb,
            axis=1,
            fill=False,
        ),
        pad_rows(np.asarray(group_valid, dtype=bool), gb, fill=False),
        np.concatenate(
            [np.asarray(order, dtype=np.int32), np.arange(g, gb, dtype=np.int32)]
        ),
    )
    progress_args = (
        pad_rows(np.asarray(min_member, dtype=np.int32), gb),
        pad_rows(np.asarray(scheduled, dtype=np.int32), gb),
        pad_rows(np.asarray(matched, dtype=np.int32), gb),
        pad_rows(np.asarray(ineligible, dtype=bool), gb, fill=True),
        pad_rows(np.asarray(creation_rank, dtype=np.int32), gb, fill=gb - 1),
    )
    return batch_args, progress_args
