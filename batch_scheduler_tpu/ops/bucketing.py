"""Shape bucketing: pad dynamic pod/node/group counts to a small set of
static shapes so churn re-scores hit the jit cache instead of recompiling.

XLA traces once per distinct input shape; a cluster whose node count drifts
between 4,997 and 5,003 must not trigger six compilations. Buckets are
powers of two (with a smallest bucket of 8 and multiples of the TPU lane
width where it matters), which bounds compilations at O(log max_size) per
rank combination.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

__all__ = [
    "bucket_size",
    "wave_width_bucket",
    "topk_bucket",
    "pad_to",
    "pad_rows",
    "pad_oracle_batch",
    "adjacent_bucket_shapes",
    "CompileWarmer",
    "maybe_compile_warmer",
]

_MIN_BUCKET = 8

# Static widths the wavefront assignment scan compiles for. Powers of two
# between 2 and 32: below 2 the wave degenerates to the serial scan; above
# 32 the batched fast path's [W, N, R] prefix tensors outgrow their win
# (and a single contended wave's serial replay grows linearly with W).
_WAVE_MIN, _WAVE_MAX = 2, 32


def bucket_size(n: int) -> int:
    """Smallest power-of-two bucket >= n (>= 8)."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def wave_width_bucket(w: int) -> int:
    """Static wave-width bucket for the wavefront assignment scan
    (ops.oracle.assign_gangs_wavefront / the BST_SCAN_WAVE knob).

    0 or 1 means "serial scan" and maps to 0; anything else snaps to the
    nearest power of two in [2, 32] so the jitted scan compiles for a
    bounded set of wave shapes no matter what the knob says."""
    if w <= 1:
        return 0
    b = _WAVE_MIN
    while b < w and b < _WAVE_MAX:
        b <<= 1
    return b


# Static candidate widths the hierarchical top-K scan compiles for
# (ops.oracle.assign_gangs_topk / the BST_SCAN_TOPK knob). Powers of two
# between 4 and 128: K must at least cover a small gang's node span to be
# useful, and past 128 the candidate slices stop being "K << N" at any
# bucket where the coarse pass pays for itself (a gang of M members spans
# <= M nodes, and ASSIGNMENT_TOP_K already caps the readback at 128).
_TOPK_MIN, _TOPK_MAX = 4, 128


def topk_bucket(k: int) -> int:
    """Static candidate-count bucket for the hierarchical top-K scan.

    <= 0 means "top-K scoring off" and maps to 0; anything else snaps to
    the nearest power of two in [4, 128] so the jitted scan compiles for a
    bounded set of candidate widths no matter what the knob says (the
    wave_width_bucket discipline applied to K)."""
    if k <= 0:
        return 0
    b = _TOPK_MIN
    while b < k and b < _TOPK_MAX:
        b <<= 1
    return b


def pad_to(arr: np.ndarray, size: int, axis: int = 0, fill=0) -> np.ndarray:
    """Pad ``arr`` along ``axis`` to ``size`` with ``fill``."""
    pad = size - arr.shape[axis]
    if pad < 0:
        raise ValueError(f"array dim {arr.shape[axis]} exceeds bucket {size}")
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths, constant_values=fill)


def pad_rows(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    return pad_to(arr, size, axis=0, fill=fill)


def pad_oracle_batch(
    alloc,
    requested,
    group_req,
    remaining,
    fit_mask,
    group_valid,
    order,
    min_member,
    scheduled,
    matched,
    ineligible,
    creation_rank,
    min_buckets=(0, 0),
):
    """Bucket-pad one oracle batch with the canonical sentinel fills.

    THE single source of truth for what padded rows look like — used by both
    the in-process snapshot packer (ops.snapshot.ClusterSnapshot) and the
    sidecar server (service.server), so the wire path can never drift from
    the local path:

    - padded groups: zero demand, invalid, ineligible for max-progress
      selection, last in creation rank, appended at the tail of the scan
      order (remaining == 0, so they place nothing);
    - padded nodes: zero lanes (capacity 0), masked out of every fit row.

    A broadcast ``[1,N]`` fit mask (uniform-feasibility fast path, see
    ops.snapshot._fit_mask) keeps its single row: padded groups are already
    neutralised by zero demand + group_valid=False, and padded nodes by the
    axis-1 False fill.

    ``min_buckets=(G, N)`` sets floor bucket sizes — churn re-scoring pins
    them to the largest shape seen so a shrinking cluster never triggers a
    fresh compile (ops.rescore sticky buckets).

    Returns ``(batch_args, progress_args)`` ready for
    ``ops.oracle.schedule_batch`` / ``find_max_group``.
    """
    n = alloc.shape[0]
    g = group_req.shape[0]
    nb = max(bucket_size(max(n, 1)), min_buckets[1])
    gb = max(bucket_size(max(g, 1)), min_buckets[0])
    # Enforce the exact-division domain (ops.lanes.LANE_MAX) at the batch
    # boundary: LaneSchema.pack already guards the dict-packing path, but
    # raw-lane snapshots (churn fast path) and the sidecar wire path feed
    # arrays straight through here — out-of-domain lanes would make
    # ops.oracle._exact_floordiv silently wrong, not just imprecise.
    from .lanes import LANE_MAX
    from .oracle import GANG_MAX

    for name, arr in (("alloc", alloc), ("requested", requested),
                      ("group_req", group_req)):
        a = np.asarray(arr)
        if a.size and (np.abs(a.astype(np.int64)) > int(LANE_MAX)).any():
            raise OverflowError(
                f"{name} lanes exceed LANE_MAX (2**30): max abs "
                f"{int(np.abs(a.astype(np.int64)).max())}"
            )
    # The assignment scan and gang_feasible accumulate need-clipped
    # capacities over the node bucket in int32; sum <= need * nb, so the
    # admissible gang size shrinks with the node bucket: need * nb must stay
    # strictly below 2**31. GANG_MAX alone (2**18) is exactly the boundary
    # at an 8192-node bucket and past it for larger buckets.
    gang_bound = min(GANG_MAX, (2**31 - 1) // nb)
    for name, arr, bound in (
        ("remaining", remaining, gang_bound),
        ("min_member", min_member, GANG_MAX),
        ("scheduled", scheduled, GANG_MAX),
        ("matched", matched, GANG_MAX),
    ):
        a = np.asarray(arr)
        if a.size and (np.abs(a.astype(np.int64)) > bound).any():
            raise OverflowError(
                f"{name} exceeds the gang bound ({bound} members at node "
                f"bucket {nb}): max abs {int(np.abs(a.astype(np.int64)).max())}"
            )
    batch_args = (
        pad_rows(np.asarray(alloc, dtype=np.int32), nb),
        pad_rows(np.asarray(requested, dtype=np.int32), nb),
        pad_rows(np.asarray(group_req, dtype=np.int32), gb),
        pad_rows(np.asarray(remaining, dtype=np.int32), gb),
        pad_to(
            np.asarray(fit_mask, dtype=bool)
            if np.asarray(fit_mask).shape[0] == 1
            else pad_rows(np.asarray(fit_mask, dtype=bool), gb, fill=False),
            nb,
            axis=1,
            fill=False,
        ),
        pad_rows(np.asarray(group_valid, dtype=bool), gb, fill=False),
        np.concatenate(
            [np.asarray(order, dtype=np.int32), np.arange(g, gb, dtype=np.int32)]
        ),
    )
    progress_args = (
        pad_rows(np.asarray(min_member, dtype=np.int32), gb),
        pad_rows(np.asarray(scheduled, dtype=np.int32), gb),
        pad_rows(np.asarray(matched, dtype=np.int32), gb),
        pad_rows(np.asarray(ineligible, dtype=bool), gb, fill=True),
        pad_rows(np.asarray(creation_rank, dtype=np.int32), gb, fill=gb - 1),
    )
    return batch_args, progress_args


def adjacent_bucket_shapes(g_bucket: int, n_bucket: int) -> list:
    """The (G, N) bucket shapes one transition away from the current
    working set — what the compile warmer precompiles. One dimension moves
    at a time (a cluster crosses one bucket boundary per transition; the
    cross product would quadruple the warm cost for shapes two transitions
    out)."""
    shapes = []
    for gb in (g_bucket // 2, g_bucket * 2):
        if gb >= _MIN_BUCKET:
            shapes.append((gb, n_bucket))
    for nb in (n_bucket // 2, n_bucket * 2):
        if nb >= _MIN_BUCKET:
            shapes.append((g_bucket, nb))
    return shapes


def _resize_rows(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] >= size:
        return np.ascontiguousarray(arr[:size])
    return pad_to(arr, size, axis=0, fill=fill)


class CompileWarmer:
    """Background precompiler for the bucket shapes adjacent to the serving
    working set (docs/pipelining.md, warmer policy).

    A bucket transition on the serving path — the cluster or group count
    crossing a power-of-two boundary — pays a cold XLA compile (~20-40s on
    the accelerator; the stall PR 3's 320s histogram ceiling exists to
    measure). This thread precompiles the adjacent ``(G, N)`` bucket
    shapes around each shape it is shown, at the process's live wave
    width, so the transition lands on a warm executable.

    Warm batches are built from the REAL padded prototype (pad/slice of
    the last served batch's args), so the derived static arguments —
    pack flag, top-K tier, mask mode — match what serving traffic at that
    bucket would compile. XLA compilation releases the GIL, so the compile
    runs concurrently with serving; the tiny dummy execution that seeds
    the jit cache is negligible on a single device, and serialized under
    ``run_lock`` when a mesh is live (two concurrent sharded executions
    interleave collectives — service/server.py's executor rule).

    Hit/miss accounting (``note_batch``): a served batch that compiled a
    new executable is a warmer **miss**; one whose shape this warmer had
    precompiled and that hit the jit cache is a **hit**. Batches on
    long-running steady shapes (cache-hot regardless of the warmer) count
    as neither.
    """

    def __init__(self, scan_mesh=None, run_lock: Optional[threading.Lock] = None,
                 registry=None):
        import queue

        from ..utils.metrics import DEFAULT_REGISTRY

        self.scan_mesh = scan_mesh
        self._run_lock = run_lock
        self._q = queue.SimpleQueue()
        self._state_lock = threading.Lock()
        self._warmed: set = set()  # shapes THIS warmer precompiled; guarded-by: _state_lock
        self._seen: set = set()    # shapes serving traffic already compiled; guarded-by: _state_lock
        self._failed: set = set()  # guarded-by: _state_lock
        self._last_key = None  # guarded-by: _state_lock
        # recent observed-shape prototypes, key -> proto (insertion-ordered,
        # bounded): the warmth-replication feed a warm STANDBY sidecar
        # precompiles from (docs/resilience.md "High availability");
        # guarded-by: _state_lock
        self._protos: dict = {}
        # GIL-atomic one-way flag (single writer: stop()); deliberately
        # lock-free so the worker can observe it mid-compile
        self._stopped = False
        reg = registry or DEFAULT_REGISTRY
        self._hits = reg.counter(
            "bst_compile_warmer_hits_total",
            "Serving batches whose bucket shape the compile warmer had "
            "precompiled (cold compile absorbed off the serving path)",
        )
        self._misses = reg.counter(
            "bst_compile_warmer_misses_total",
            "Serving batches that built a new executable on the serving "
            "path (shape not precompiled in time)",
        )
        self._warms = reg.counter(
            "bst_compile_warmer_precompiles_total",
            "Bucket shapes precompiled by the warmer thread",
        )
        self._thread = threading.Thread(
            target=self._loop, name="compile-warmer", daemon=True
        )
        self._thread.start()

    @staticmethod
    def _key(g_bucket: int, n_bucket: int, lanes: int, mask_rows: int,
             wave: int, donate: bool) -> tuple:
        return (g_bucket, n_bucket, lanes, mask_rows > 1, wave, donate)

    def warmed_shapes(self) -> set:
        with self._state_lock:
            return set(self._warmed)

    def stats(self) -> dict:
        with self._state_lock:
            warmed = len(self._warmed)
        return {
            "warmer_hits": int(self._hits.value()),
            "warmer_misses": int(self._misses.value()),
            "warmer_shapes": warmed,
        }

    def note_batch(self, batch_args, progress_args, telemetry: dict,
                   donate: bool = False) -> None:
        """Account one served batch against the warm set and (on a shape
        change) queue its adjacent shapes for precompilation. ``batch_args``
        must be the HOST-side padded args (pre-sharding)."""
        g_bucket = int(batch_args[2].shape[0])
        n_bucket = int(batch_args[0].shape[0])
        lanes = int(batch_args[0].shape[1])
        mask_rows = int(batch_args[4].shape[0])
        wave = int((telemetry or {}).get("wave_width", 0))
        key = self._key(g_bucket, n_bucket, lanes, mask_rows, wave, donate)
        with self._state_lock:
            in_warmed = key in self._warmed
            is_new = key != self._last_key
            self._last_key = key
            # the served shape is compiled now by definition — recorded so
            # an A->B->A bucket oscillation never re-warms A, but kept out
            # of _warmed: steady cache-hot batches are not warmer hits
            self._seen.add(key)
        if (telemetry or {}).get("compiled"):
            self._misses.inc()
        elif in_warmed and is_new:
            # a bucket TRANSITION landing on a precompiled executable —
            # the cold compile the warmer absorbed; steady cache-hot
            # batches at an already-served shape count as neither
            self._hits.inc()
        if is_new and not self._stopped:
            # snapshot the prototype: the caller keeps mutating its arrays
            proto = (
                tuple(np.array(a) for a in batch_args),
                tuple(np.array(a) for a in progress_args),
                wave,
                donate,
            )
            with self._state_lock:
                self._protos[key] = proto
                while len(self._protos) > 16:  # bounded replication feed
                    self._protos.pop(next(iter(self._protos)))
            self._q.put(proto + (False,))

    def warmth_snapshot(self) -> list:
        """The retained observed-shape prototypes, oldest first — feed
        them to a standby's :meth:`replicate` so promotion lands on warm
        executables instead of paying the cold compiles the primary
        already absorbed."""
        with self._state_lock:
            return list(self._protos.values())

    def replicate(self, protos) -> int:
        """Queue another warmer's :meth:`warmth_snapshot` for
        precompilation — INCLUDING each prototype's own shape, not just
        its adjacents: the standby has served no traffic, so the
        primary's steady shapes are exactly the cold compiles a
        promotion would otherwise pay. Replicated shapes land in the
        warm set, so the first post-failover batch at one counts as a
        warmer hit. Returns the number of prototypes enqueued."""
        n = 0
        for proto in protos or []:
            if self._stopped:
                break
            batch_args, progress_args, wave, donate = proto[:4]
            self._q.put((batch_args, progress_args, wave, donate, True))
            n += 1
        return n

    def stop(self, timeout: float = 60.0) -> bool:
        """Drain the warmer before process teardown (same XLA-daemon-thread
        rule as OracleScorer.drain_background)."""
        self._stopped = True
        self._q.put(None)
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- worker -------------------------------------------------------------

    def _variant(self, batch_args, progress_args, gb: int, nb: int):
        (alloc, requested, group_req, remaining, fit_mask, group_valid,
         order) = batch_args
        min_member, scheduled, matched, ineligible, creation_rank = (
            progress_args
        )
        v_mask = fit_mask
        if v_mask.shape[0] > 1:
            v_mask = _resize_rows(v_mask, gb, fill=False)
        if v_mask.shape[1] != nb:
            if v_mask.shape[1] >= nb:
                v_mask = np.ascontiguousarray(v_mask[:, :nb])
            else:
                v_mask = pad_to(v_mask, nb, axis=1, fill=False)
        vbatch = (
            _resize_rows(alloc, nb),
            _resize_rows(requested, nb),
            _resize_rows(group_req, gb),
            _resize_rows(remaining, gb),
            v_mask,
            _resize_rows(group_valid, gb, fill=False),
            # any permutation compiles the same executable; arange keeps
            # the variant a valid batch on every resize
            np.arange(gb, dtype=np.int32),
        )
        vprogress = (
            _resize_rows(min_member, gb),
            _resize_rows(scheduled, gb),
            _resize_rows(matched, gb),
            _resize_rows(ineligible, gb, fill=True),
            np.arange(gb, dtype=np.int32),
        )
        return vbatch, vprogress

    def _loop(self) -> None:
        from .oracle import collect_batch, dispatch_batch

        while True:
            item = self._q.get()
            if item is None:
                return
            batch_args, progress_args, wave, donate, warm_self = item
            g_bucket = int(batch_args[2].shape[0])
            n_bucket = int(batch_args[0].shape[0])
            lanes = int(batch_args[0].shape[1])
            mask_rows = int(batch_args[4].shape[0])
            # replicated prototypes (warm_self) warm their OWN shape
            # first, then the adjacents; locally observed shapes are
            # already compiled by serving traffic and warm adjacents only
            shapes = (
                [(g_bucket, n_bucket)] if warm_self else []
            ) + list(adjacent_bucket_shapes(g_bucket, n_bucket))
            for gb, nb in shapes:
                key = self._key(gb, nb, lanes, mask_rows, wave, donate)
                with self._state_lock:
                    if (
                        key in self._warmed
                        or key in self._seen
                        or key in self._failed
                    ):
                        continue
                if self._stopped:
                    return
                try:
                    vbatch, vprogress = self._variant(
                        batch_args, progress_args, gb, nb
                    )
                    pending = None
                    if self._run_lock is not None:
                        with self._run_lock:
                            pending = dispatch_batch(
                                vbatch, vprogress, scan_mesh=self.scan_mesh,
                                donate=donate,
                            )
                            collect_batch(pending)
                    else:
                        collect_batch(dispatch_batch(
                            vbatch, vprogress, scan_mesh=self.scan_mesh,
                            donate=donate,
                        ))
                except Exception as e:  # noqa: BLE001 — warm-only, never fatal
                    import sys

                    print(
                        f"compile warmer: shape (G={gb}, N={nb}) failed "
                        f"({e!r}); not retried",
                        file=sys.stderr,
                    )
                    with self._state_lock:
                        self._failed.add(key)
                    continue
                with self._state_lock:
                    self._warmed.add(key)
                self._warms.inc()


def maybe_compile_warmer(scan_mesh=None) -> Optional[CompileWarmer]:
    """A CompileWarmer when warm execution is safe — single device only.
    On a sharded mesh a warm batch would have to serialize with live
    batches (the collective-interleaving rule), stalling them behind the
    warm COMPILE — the exact inversion of the warmer's purpose — so the
    skip is printed and None returned. THE single eligibility rule,
    shared by the sidecar server and the in-process scorer."""
    if scan_mesh is None:
        return CompileWarmer()
    import sys

    print(
        "compile warmer skipped: sharded-mesh warm batches would "
        "stall live batches behind the warm compile",
        file=sys.stderr,
    )
    return None
