"""Shape bucketing: pad dynamic pod/node/group counts to a small set of
static shapes so churn re-scores hit the jit cache instead of recompiling.

XLA traces once per distinct input shape; a cluster whose node count drifts
between 4,997 and 5,003 must not trigger six compilations. Buckets are
powers of two (with a smallest bucket of 8 and multiples of the TPU lane
width where it matters), which bounds compilations at O(log max_size) per
rank combination.
"""

from __future__ import annotations

import numpy as np

__all__ = ["bucket_size", "pad_to", "pad_rows"]

_MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Smallest power-of-two bucket >= n (>= 8)."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def pad_to(arr: np.ndarray, size: int, axis: int = 0, fill=0) -> np.ndarray:
    """Pad ``arr`` along ``axis`` to ``size`` with ``fill``."""
    pad = size - arr.shape[axis]
    if pad < 0:
        raise ValueError(f"array dim {arr.shape[axis]} exceeds bucket {size}")
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths, constant_values=fill)


def pad_rows(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    return pad_to(arr, size, axis=0, fill=fill)
