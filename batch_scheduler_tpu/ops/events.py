"""Bounded host event log: the O(churn) refresh feed (stage 3 of
"Kill the snapshot", docs/pipelining.md "Snapshot-lite & event ingest").

Informer/bind/permit mutations append the NAMES of the entities whose
oracle-visible state changed (a node's requested view, a gang's demand
row) instead of the mutation payloads. The scorer drains the log once
per refresh and re-reads just the named entities from the live cluster
state, so an event that raced the drain window re-folds harmlessly on
the next pack — the fold is idempotent by construction, which is what
lets producers emit outside any scorer lock.

The log is name-coalesced: N mutations to one node are one entry. What
it must track exactly is the NUMBER of cluster version bumps it saw
(``note_bump`` per ``ClusterState._version += 1``) so the scorer can
prove completeness — if ``version_now - version_at_last_pack`` does not
equal the drained bump count, some mutation bypassed the hooks and the
fold falls back to the full O(N+G) scan (always correct, never stale).

Capacity is bounded by ``BST_EVENT_LOG_CAP``; hitting the cap sets the
overflow flag and the next drain reports incomplete (scan fallback),
exactly like a blind mark from an uninstrumented mutation path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import FrozenSet

__all__ = ["EventLog", "EventBatch", "event_log_cap", "event_fold_enabled"]


_FOLD_ENV = "BST_EVENT_FOLD"
_fold_warned = [False]


def event_fold_enabled() -> bool:
    """Parse-guarded BST_EVENT_FOLD read: default ON; ``0``/``off``/
    ``false`` disables the O(churn) event-fold refresh path (every
    refresh then runs the full O(N+G) cluster read — the snapshot-lite
    scan path, kept as the bench comparison baseline). Unrecognised
    values warn once and keep the default (the BST_SCAN_WAVE idiom)."""
    import os

    raw = os.environ.get(_FOLD_ENV, "").strip().lower()
    if raw in ("", "1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no"):
        return False
    if not _fold_warned[0]:
        _fold_warned[0] = True
        import sys

        print(
            f"ignoring unrecognised {_FOLD_ENV}={raw!r}; event fold "
            "stays enabled",
            file=sys.stderr,
        )
    return True


_CAP_ENV = "BST_EVENT_LOG_CAP"
_CAP_DEFAULT = 4096
_cap_warned = [False]


def event_log_cap() -> int:
    """Parse-guarded BST_EVENT_LOG_CAP read (default 4096): the bound on
    distinct names the log coalesces before declaring overflow. A typo'd
    knob warns once and keeps the default (the BST_SCAN_WAVE idiom)."""
    import os

    raw = os.environ.get(_CAP_ENV, "").strip()
    if not raw:
        return _CAP_DEFAULT
    try:
        return max(int(raw), 1)
    except ValueError:
        if not _cap_warned[0]:
            _cap_warned[0] = True
            import sys

            print(
                f"ignoring malformed {_CAP_ENV}={raw!r}; event log cap "
                f"stays {_CAP_DEFAULT}",
                file=sys.stderr,
            )
        return _CAP_DEFAULT


@dataclass(frozen=True)
class EventBatch:
    """One drain's worth of pending events.

    ``complete`` is the fold-eligibility verdict from the log's own side:
    no blind marks, no overflow, no structural (node-object) mutation
    since the last drain. The scorer layers its own checks on top
    (version-bump accounting, status-cache mutation counter, resolvable
    names) before trusting a targeted fold."""

    node_names: FrozenSet[str] = frozenset()
    group_names: FrozenSet[str] = frozenset()
    bumps: int = 0
    blind: bool = False
    structural: bool = False
    overflow: bool = False

    @property
    def complete(self) -> bool:
        return not (self.blind or self.structural or self.overflow)

    @property
    def empty(self) -> bool:
        return not (self.node_names or self.group_names or self.bumps
                    or self.blind or self.structural or self.overflow)


class EventLog:
    """Thread-safe bounded, name-coalescing event accumulator.

    Producers (ClusterState mutators via ``subscribe_events``, the
    operation layer's gang hints, blind ``mark_dirty`` fallbacks) only
    ever append; the single consumer (the scorer's refresh path, under
    its refresh lock) drains. Producers may call under the cluster lock:
    the log takes only its own ``_lock`` and the metrics registry's —
    neither ever takes the cluster lock back, so there is no ordering
    cycle (lock discipline instrumented via BST_LOCKCHECK, the
    guarded-by annotations below).
    """

    def __init__(self, cap: int = 0, label: str = "scorer"):
        self.label = label
        self.cap = int(cap) if cap else event_log_cap()
        self._lock = threading.Lock()
        self._node_names: set = set()  # guarded-by: _lock
        self._group_names: set = set()  # guarded-by: _lock
        self._bumps = 0  # guarded-by: _lock
        self._blind = False  # guarded-by: _lock
        self._structural = False  # guarded-by: _lock
        self._overflow = False  # guarded-by: _lock
        self.appended = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self.drains = 0  # guarded-by: _lock

    # -- internals (lock-held) ---------------------------------------------

    def _depth(self) -> int:  # lock-held: _lock
        return len(self._node_names) + len(self._group_names)

    def _count(self, kind: str, n: int = 1) -> None:  # lock-held: _lock
        self.appended += n
        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_event_appended_total",
            "Mutation events appended to the host event log, by kind",
        ).inc(n, kind=kind)
        DEFAULT_REGISTRY.gauge(
            "bst_event_log_depth",
            "Distinct entity names pending in the host event log",
        ).set(float(self._depth()), log=self.label)

    def _add(self, names, target: set, kind: str) -> None:  # lock-held: _lock
        for name in names:
            if name in target:
                continue
            if self._depth() >= self.cap:
                self._overflow = True
                self.dropped += 1
                from ..utils.metrics import DEFAULT_REGISTRY

                DEFAULT_REGISTRY.counter(
                    "bst_event_dropped_total",
                    "Events dropped at the event-log cap (the next "
                    "refresh falls back to a full scan)",
                ).inc()
                continue
            target.add(name)
        self._count(kind)

    # -- producer API -------------------------------------------------------

    def note_bump(self, kind: str, names=()) -> None:
        """One cluster version bump: ``names`` are the nodes whose
        requested view changed under it (may be empty — e.g. a no-op
        release). ``kind == "node-object"`` marks a structural mutation
        (add/update/remove of the node OBJECT): the packer's lane schema
        may have moved, so the batch reports incomplete and the next
        refresh scans."""
        with self._lock:
            self._bumps += 1
            if kind == "node-object":
                self._structural = True
            self._add(names, self._node_names, kind)

    def note_group(self, full_name: str) -> None:
        """A gang's demand row changed (permit/bind/register progress)."""
        with self._lock:
            self._add((full_name,), self._group_names, "group")

    def note_blind(self) -> None:
        """A mutation with no event attribution (legacy ``mark_dirty``
        callers): the next drain reports incomplete and the refresh falls
        back to the full scan — correctness never depends on coverage."""
        with self._lock:
            self._blind = True
            from ..utils.metrics import DEFAULT_REGISTRY

            DEFAULT_REGISTRY.counter(
                "bst_event_blind_marks_total",
                "Unattributed dirty marks (event fold falls back to a "
                "full scan for that refresh)",
            ).inc()

    # -- consumer API -------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return self._depth()

    def drain(self) -> EventBatch:
        """Snapshot-and-reset the pending events. The single consumer is
        the scorer's refresh path (serialized by its refresh lock)."""
        with self._lock:
            batch = EventBatch(
                node_names=frozenset(self._node_names),
                group_names=frozenset(self._group_names),
                bumps=self._bumps,
                blind=self._blind,
                structural=self._structural,
                overflow=self._overflow,
            )
            self._node_names.clear()
            self._group_names.clear()
            self._bumps = 0
            self._blind = False
            self._structural = False
            self._overflow = False
            self.drains += 1
            from ..utils.metrics import DEFAULT_REGISTRY

            DEFAULT_REGISTRY.gauge(
                "bst_event_log_depth",
                "Distinct entity names pending in the host event log",
            ).set(0.0, log=self.label)
            return batch

    def stats(self) -> dict:
        with self._lock:
            return {
                "label": self.label,
                "cap": self.cap,
                "depth": self._depth(),
                "bumps_pending": self._bumps,
                "appended": self.appended,
                "dropped": self.dropped,
                "drains": self.drains,
                "blind_pending": self._blind,
                "structural_pending": self._structural,
                "overflow_pending": self._overflow,
            }
