"""CLI layer — the equivalent of the reference's ``cmd/scheduler`` entry
point (reference cmd/scheduler/main.go:28-36)."""

from .config import SchedulerConfiguration, load_scheduler_config
from .main import main

__all__ = ["main", "SchedulerConfiguration", "load_scheduler_config"]
