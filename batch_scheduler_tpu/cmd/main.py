"""``python -m batch_scheduler_tpu`` — the framework's CLI entry point.

The reference's entry point registers the plugin into upstream
kube-scheduler's cobra command and defers all flags to it (reference
cmd/scheduler/main.go:28-36, deploy/start.sh:1-3). This framework owns its
whole stack, so the CLI exposes the workflows directly:

  sim           run the full scheduler over a simulated cluster (scenario
                generators or -f Kubernetes manifests), print the outcome
  serve         run the TPU oracle sidecar service (packed-array protocol)
  check-config  validate a scheduler configuration JSON
  version       print the build stamp

``--config`` takes the same JSON shape as the reference's
``KubeSchedulerConfiguration`` (extension points + pluginConfig args).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from .config import load_scheduler_config
from ..utils.labels import POD_GROUP_LABEL


def _add_config_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--config",
        default=None,
        help="scheduler configuration JSON (KubeSchedulerConfiguration shape)",
    )


def _add_metrics_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve Prometheus /metrics (+/healthz, /debug/trace, "
             "/debug/decisions) on this port (0 = ephemeral; the bound "
             "port is printed)",
    )


def _add_profile_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--profile-dir", default=None, metavar="DIR",
        help="directory for on-demand jax.profiler captures "
             "(/debug/profile?seconds=N on --metrics-port writes bounded "
             "trace dirs here; default: a per-process tmpdir — "
             "docs/observability.md 'Device profiling')",
    )


def _start_profiler(args) -> None:
    """Shared sim/serve profiler bring-up: capture dir + the device-memory
    gauge sampler (daemon; a CPU backend has no memory_stats and the
    sampler exits after its first empty pass)."""
    from ..utils import profiler as profiler_mod

    profiler_mod.configure(profile_dir=getattr(args, "profile_dir", None))
    profiler_mod.start_memory_sampler()


def _add_trace_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--trace", action="store_true",
        help="record spans for the full decision path (cycle -> gang "
             "transaction -> oracle batch -> wire -> device scan -> bind) "
             "into a bounded ring; sim exports a Chrome-trace JSON on "
             "exit, and --metrics-port serves the live ring at "
             "/debug/trace (docs/observability.md)",
    )
    p.add_argument(
        "--trace-dir", default=".", metavar="DIR",
        help="directory the Chrome-trace JSON is written to on exit "
             "(sim only; default: current directory)",
    )
    p.add_argument(
        "--trace-sample", type=float, default=1.0, metavar="FRACTION",
        help="fraction of scheduling cycles traced (children follow "
             "their root's fate; 1.0 = every cycle)",
    )


def _add_audit_flags(p: argparse.ArgumentParser, identity: bool = False) -> None:
    p.add_argument(
        "--audit-dir", default=None, metavar="DIR",
        help="record every oracle batch (the exact packed inputs + the "
             "resulting plan digest) into a bounded on-disk audit ring in "
             "DIR, written off the hot path — the black-box flight data "
             "the `replay` subcommand re-executes deterministically "
             "(docs/observability.md)",
    )
    p.add_argument(
        "--audit-cap-mb", type=int, default=256, metavar="MB",
        help="total size cap of the audit ring; oldest segments are "
             "deleted first (default: 256)",
    )
    p.add_argument(
        "--lifecycle-dir", default=None, metavar="DIR",
        help="stream gang lifecycle events (arrival/admission/deny "
             "streaks/eviction/permit/bind — utils.lifecycle) as bounded "
             "JSONL into DIR/events.jsonl, size-rotated to events.jsonl.1 "
             "(cap: BST_LIFECYCLE_EXPORT_MAX_MB); the offline half of "
             "/debug/events (docs/observability.md 'Gang lifecycle')",
    )
    if identity:
        p.add_argument(
            "--identity-audit-every", type=int, default=0, metavar="K",
            help="in-production identity audit: re-verify every Kth "
                 "non-speculative batch bit-for-bit on the CPU fallback "
                 "rung (daemon thread); a mismatch breaches /debug/health "
                 "and flags the audit ring (0 = off)",
        )


def _maybe_audit_log(args):
    if not getattr(args, "audit_dir", None):
        return None
    from ..utils.audit import AuditLog

    log = AuditLog(
        args.audit_dir, cap_bytes=max(args.audit_cap_mb, 1) * 1024 * 1024
    )
    print(
        f"audit ring: {args.audit_dir} (cap {args.audit_cap_mb} MB, "
        f"format {log.fmt})",
        flush=True,
    )
    return log


def _maybe_lifecycle(args, audit_log=None) -> None:
    """Wire the gang lifecycle ledger's sinks: mirror occurrences into
    the audit ring (the `timeline --audit-dir` / slo_gate evidence
    chain) and, with --lifecycle-dir, the bounded JSONL export. MUST run
    AFTER the cluster/operation is constructed — ScheduleOperation
    resets DEFAULT_LEDGER at construction (per-run isolation), which
    detaches sinks."""
    from ..utils.lifecycle import DEFAULT_LEDGER

    if audit_log is not None:
        DEFAULT_LEDGER.attach_audit(audit_log)
    if getattr(args, "lifecycle_dir", None):
        DEFAULT_LEDGER.set_export_dir(args.lifecycle_dir)
        print(
            f"lifecycle export: "
            f"{os.path.join(args.lifecycle_dir, 'events.jsonl')}",
            flush=True,
        )


def _maybe_configure_trace(args) -> bool:
    if not getattr(args, "trace", False):
        return False
    from ..utils import trace as trace_mod

    trace_mod.configure(enabled=True, sample=args.trace_sample)
    return True


def _export_trace(args) -> None:
    from ..utils import trace as trace_mod

    os.makedirs(args.trace_dir, exist_ok=True)
    path = os.path.join(
        args.trace_dir, f"bst-trace-{os.getpid()}.json"
    )
    trace_mod.DEFAULT_RECORDER.export(path)
    n = len(trace_mod.DEFAULT_RECORDER.snapshot())
    print(f"trace written: {path} ({n} spans)", flush=True)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="batch-scheduler-tpu",
        description="TPU-native gang/batch scheduling framework",
    )
    parser.add_argument("--v", type=int, default=0, help="log verbosity (klog-style)")
    sub = parser.add_subparsers(dest="command", required=True)

    sim = sub.add_parser("sim", help="run the scheduler over a simulated cluster")
    _add_config_flag(sim)
    sim.add_argument(
        "-f",
        "--filename",
        action="append",
        default=[],
        help="Kubernetes manifest(s) to apply (PodGroup/Pod/Node/workloads)",
    )
    sim.add_argument(
        "--scenario",
        choices=["race", "synthetic", "spot-vs-guaranteed"],
        default=None,
    )
    sim.add_argument("--scorer", choices=["oracle", "serial"], default=None,
                     help="override the scorer gate (--scorer=tpu north star)")
    sim.add_argument("--oracle-addr", default=None,
                     metavar="HOST:PORT[,HOST:PORT...]",
                     help="score via a remote oracle sidecar (see `serve`) "
                          "instead of the in-process oracle; a comma list "
                          "names warm standbys after the primary — the "
                          "client promotes on DRAINING (graceful drain) or "
                          "breaker-open (crash), see docs/resilience.md "
                          "\"High availability\"")
    sim.add_argument(
        "--oracle-fallback", choices=["deny", "local-cpu"], default="deny",
        help="behavior when the sidecar transport is down (breaker open / "
             "retries exhausted): 'deny' surfaces the error into the cycle "
             "(pods requeue with backoff); 'local-cpu' serves a "
             "conservative host-side batch — deny only provably-infeasible "
             "gangs, admit nothing speculatively (docs/resilience.md)",
    )
    sim.add_argument(
        "--oracle-deadline-ms", type=int, default=None, metavar="MS",
        help="per-request budget propagated to the sidecar: a batch "
             "stalled past it (e.g. an unwarmed jit compile) answers an "
             "in-band deadline error within ~2x the budget instead of "
             "holding the scheduling cycle",
    )
    sim.add_argument("--nodes", type=int, default=0,
                     help="synthetic nodes to add (in addition to manifests)")
    sim.add_argument("--node-cpu", default="32")
    sim.add_argument("--node-memory", default="128Gi")
    sim.add_argument("--groups", type=int, default=10, help="synthetic scenario groups")
    sim.add_argument("--members", type=int, default=5, help="pods per synthetic group")
    sim.add_argument("--timeout", type=float, default=60.0)
    sim.add_argument(
        "--oracle-background-refresh",
        action="store_true",
        help="re-batch the oracle on a daemon thread while cycles keep "
             "reading the stale (known-complete) batch — takes the device "
             "round-trip off the scheduling critical path",
    )
    sim.add_argument(
        "--dispatch-ahead",
        action="store_true",
        help="speculatively pack + dispatch batch N+1 while the control "
             "plane works against batch N; a later refresh publishes it "
             "without a blocking device round-trip iff nothing changed "
             "since it packed (bit-identical plans either way — "
             "docs/pipelining.md). With --oracle-addr the client gets an "
             "in-flight window of 2 connections",
    )
    sim.add_argument(
        "--compile-warmer",
        action="store_true",
        help="precompile the adjacent (G, N) bucket shapes around the "
             "live working set on a daemon thread so a bucket transition "
             "never pays the cold XLA compile on the serving path "
             "(in-process oracle; for --oracle-addr pass --compile-warmer "
             "to `serve` instead)",
    )
    sim.add_argument(
        "--device-state", choices=["on", "off"], default=None,
        help="device-resident cluster state (docs/pipelining.md): keep "
             "the packed [N,R]/[G,R] buffers on device across batches and "
             "apply churned rows as jit'd scatter-updates (with "
             "--oracle-addr: ship only churned-row wire deltas + "
             "generation to the sidecar). Equivalent to BST_DEVICE_STATE; "
             "default on",
    )
    sim.add_argument(
        "--policy", default=None, metavar="TERMS",
        help="enable the vectorized policy engine (docs/policy.md): a "
             "comma list of terms from "
             "{affinity,anti-affinity,spread,preempt}, or 'all'. "
             "Equivalent to BST_POLICY; weights ride the BST_POLICY_* "
             "knobs. Empty/off = the exact pre-policy scan paths",
    )
    sim.add_argument(
        "--multi-client", type=int, default=0, metavar="K",
        help="multi-tenant coalescer mode (docs/multitenancy.md): instead "
             "of the full framework sim, drive K concurrent scheduler "
             "clients' deterministic oracle streams through ONE sidecar "
             "(--oracle-addr, or an in-process coalescing sidecar when "
             "omitted) and print aggregate throughput + per-tenant queue "
             "waits; --nodes/--groups size each tenant's cluster",
    )
    sim.add_argument(
        "--mc-batches", type=int, default=8, metavar="B",
        help="batches per client in --multi-client mode",
    )
    _add_metrics_flag(sim)
    _add_profile_flag(sim)
    _add_trace_flags(sim)
    _add_audit_flags(sim, identity=True)
    sim.add_argument("--settle", type=float, default=3.0,
                     help="finish early once group phases and bound counts "
                          "have been stable this many seconds (a denied gang "
                          "never reaches a terminal phase)")

    serve = sub.add_parser("serve", help="run the TPU oracle sidecar service")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=9090)
    serve.add_argument(
        "--warmup",
        action="store_true",
        help="jit-compile the smallest bucket shape before accepting traffic "
             "(first TPU compile is ~20-40s; warmed shapes answer instantly)",
    )
    serve.add_argument(
        "--compile-warmer",
        action="store_true",
        help="keep a background thread precompiling the adjacent (G, N) "
             "bucket shapes around live traffic so bucket transitions hit "
             "warm executables (hit/miss counters in /metrics and "
             "TRACE_INFO telemetry — docs/pipelining.md)",
    )
    serve.add_argument(
        "--coalesce",
        action="store_true",
        help="multi-tenant cross-client coalescer (docs/multitenancy.md): "
             "merge compatible pending batches from different connections "
             "in a DRF-fair admission order in front of the device "
             "executor (single-device servers only; equivalent to "
             "BST_COALESCE=1 — depth/fairness ride the BST_COALESCE_* "
             "knobs)",
    )
    _add_metrics_flag(serve)
    _add_profile_flag(serve)
    _add_trace_flags(serve)
    _add_audit_flags(serve)

    rep = sub.add_parser(
        "replay",
        help="deterministically re-execute recorded oracle batches from "
             "an audit ring (`sim`/`serve` --audit-dir) and bit-compare "
             "the plans against their recorded digests",
    )
    rep.add_argument(
        "audit_dir",
        help="audit ring directory written by a --audit-dir run",
    )
    sel = rep.add_mutually_exclusive_group()
    sel.add_argument(
        "--batch", type=int, default=None, metavar="K",
        help="replay only the record with seq K",
    )
    sel.add_argument(
        "--all", action="store_true",
        help="replay every reconstructable record (the default)",
    )
    rep.add_argument(
        "--against", default="steady",
        choices=("steady", "wavefront", "cpu-ladder", "topk"),
        help="the rung to re-execute on: 'steady' = exactly what this "
             "process would dispatch now (same-backend bit-identity); "
             "'wavefront' = the wavefront scan forced on; 'cpu-ladder' = "
             "the serial fallback rung pinned to a CPU device (the "
             "cross-backend divergence probe); 'topk' = the hierarchical "
             "top-K scan forced on (the XL-tier rung)",
    )
    rep.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write the summary JSON (with full blame reports) here",
    )

    exp = sub.add_parser(
        "explain",
        help="why is this gang pending — structured denial breakdown "
             "(per-lane deficits, binding lane, near-miss nodes, "
             "preemption candidacy) from a live scheduler's "
             "/debug/explain or offline from an audit ring "
             "(docs/observability.md 'Explain')",
    )
    exp.add_argument("gang", help="the gang's full name (namespace/name)")
    exp_src = exp.add_mutually_exclusive_group(required=True)
    exp_src.add_argument(
        "--addr", metavar="HOST:PORT",
        help="a live scheduler's --metrics-port endpoint "
             "(queries /debug/explain)",
    )
    exp_src.add_argument(
        "--audit-dir", metavar="DIR",
        help="explain offline from a recorded audit ring (the exact "
             "packed inputs of a recorded batch; lane names degrade to "
             "lane<i> — the record carries no schema)",
    )
    exp.add_argument(
        "--batch", type=int, default=None, metavar="K",
        help="with --audit-dir: explain the record with seq K "
             "(default: the newest reconstructable record)",
    )

    wi = sub.add_parser(
        "whatif",
        help="score a counterfactual against a live scheduler's cluster "
             "state on a forked device-resident buffer and print the "
             "placement diff (docs/observability.md 'What-if')",
    )
    wi.add_argument(
        "--addr", required=True, metavar="HOST:PORT",
        help="a live scheduler's --metrics-port endpoint "
             "(queries /debug/whatif)",
    )
    wi_kind = wi.add_mutually_exclusive_group(required=True)
    wi_kind.add_argument("--drain", metavar="NODE",
                         help="remove NODE (and its load) from the cluster")
    wi_kind.add_argument("--cordon", metavar="NODE",
                         help="mark NODE unschedulable, load kept")
    wi_kind.add_argument("--add-nodes", type=int, metavar="N",
                         help="add N nodes of --node-cpu/--node-memory")
    wi_kind.add_argument("--bump-gang", metavar="NS/NAME",
                         help="set a gang's priority tier to --tier")
    wi_kind.add_argument("--remove-gang", metavar="NS/NAME",
                         help="remove a gang from the queue")
    wi.add_argument("--tier", type=int, default=None,
                    help="the priority tier for --bump-gang")
    wi.add_argument("--node-cpu", default="32",
                    help="shape of --add-nodes nodes (default 32)")
    wi.add_argument("--node-memory", default="128Gi",
                    help="shape of --add-nodes nodes (default 128Gi)")
    wi.add_argument("--node-pods", default="110",
                    help="pod capacity of --add-nodes nodes (default 110)")
    wi.add_argument(
        "--rung", default="steady",
        choices=("steady", "wavefront", "cpu-ladder", "topk"),
        help="the scan rung the what-if scores on (non-steady rungs are "
             "thread-locally pinned — the replay discipline; plans are "
             "bit-identical across rungs by construction)",
    )

    cap = sub.add_parser(
        "capacity",
        help="the capacity observatory: per-lane utilization/headroom "
             "spectra, fragmentation index, stranded capacity, tenant "
             "shares — live from a scheduler's /debug/capacity, or "
             "offline by replaying a recorded audit ring through the "
             "same analytics kernel (bit-identical to the live series — "
             "docs/observability.md 'Capacity observatory')",
    )
    cap_src = cap.add_mutually_exclusive_group(required=True)
    cap_src.add_argument(
        "--addr", metavar="HOST:PORT",
        help="a live scheduler's --metrics-port endpoint "
             "(queries /debug/capacity)",
    )
    cap_src.add_argument(
        "--audit-dir", metavar="DIR",
        help="replay a recorded audit ring offline: recompute the "
             "capacity summary of every reconstructable batch and "
             "bit-compare against the ring's recorded capacity_sample "
             "events (exit 1 on divergence)",
    )
    cap.add_argument(
        "--batch", type=int, default=None, metavar="K",
        help="with --audit-dir: only the record with seq K",
    )
    cap.add_argument(
        "--points", type=int, default=None, metavar="K",
        help="with --addr: trim the returned series to the newest K "
             "points",
    )
    cap.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write the summary JSON (offline mode: the replayed "
             "series + comparison verdicts) here",
    )

    tl = sub.add_parser(
        "timeline",
        help="a gang's reconstructed lifecycle story — arrival, "
             "admission, deny streaks, preemption eviction/respawn, "
             "permit, bind — with the phase-decomposed time-to-placement "
             "(queue/scheduling/sidecar/bind waits), live from a "
             "scheduler's /debug/gangs or offline by re-folding a "
             "recorded audit ring's gang_lifecycle events "
             "(docs/observability.md 'Gang lifecycle')",
    )
    tl.add_argument(
        "gang", nargs="?", default=None,
        help="the gang's full name (namespace/name); omit to list every "
             "recorded gang (scope with --tenant/--limit)",
    )
    tl_src = tl.add_mutually_exclusive_group(required=True)
    tl_src.add_argument(
        "--addr", metavar="HOST:PORT",
        help="a live scheduler's --metrics-port endpoint "
             "(queries /debug/gangs)",
    )
    tl_src.add_argument(
        "--audit-dir", metavar="DIR",
        help="reconstruct offline from a recorded audit ring: re-fold "
             "its gang_lifecycle event records through the live ledger's "
             "coalesce rule (byte-identical timelines — the slo_gate "
             "contract)",
    )
    tl.add_argument("--tenant", default=None, metavar="T",
                    help="scope to one tenant's gangs")
    tl.add_argument("--limit", type=int, default=None, metavar="K",
                    help="only the K most recently active gangs")
    tl.add_argument(
        "--json", default=None, metavar="PATH",
        help="also write the timelines JSON here",
    )

    chk = sub.add_parser("check-config", help="validate a scheduler config JSON")
    _add_config_flag(chk)

    sub.add_parser("version", help="print the build stamp")
    return parser


def cmd_version(_args) -> int:
    from ..version import version_string

    print(version_string())
    return 0


def cmd_check_config(args) -> int:
    cfg = load_scheduler_config(args.config)
    print(
        json.dumps(
            {
                "valid": True,
                "scorer": cfg.plugin_config.scorer,
                "max_schedule_minutes": cfg.plugin_config.max_schedule_minutes,
                "enabled_points": sorted(cfg.enabled_points),
                "controller_workers": cfg.plugin_config.controller_workers,
                "min_batch_interval_seconds": (
                    cfg.plugin_config.min_batch_interval_seconds
                ),
                "oracle_background_refresh": (
                    cfg.plugin_config.oracle_background_refresh
                ),
                "oracle_dispatch_ahead": (
                    cfg.plugin_config.oracle_dispatch_ahead
                ),
                "oracle_compile_warmer": (
                    cfg.plugin_config.oracle_compile_warmer
                ),
            }
        )
    )
    return 0


def warm_oracle(nodes=None, groups=None, pods=None, remote_scorer=None) -> float:
    """Compile the oracle for the bucket shapes the given cluster will
    actually hit (falling back to the smallest bucket), so the first real
    batch doesn't pay the jit inside a scheduling callback. Shapes are what
    matter: node/group counts round to the same power-of-two buckets
    (ops.bucketing) and the lane schema must cover the same resource names.
    With ``remote_scorer`` the warm batch is sent through the sidecar wire
    path instead — warming the *server's* jit cache, the only one a remote
    run exercises. Returns elapsed seconds."""
    from ..ops.oracle import execute_batch_host
    from ..ops.snapshot import ClusterSnapshot, GroupDemand
    from ..sim.scenarios import make_sim_node

    t0 = time.perf_counter()
    warm_nodes = list(nodes) if nodes else [
        make_sim_node("warm", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    ]
    rep_pods: Dict[str, object] = {}
    for pod in pods or []:
        label = pod.metadata.labels.get(POD_GROUP_LABEL)
        if label and label not in rep_pods:
            rep_pods[label] = pod
    warm_groups = []
    for pg in groups or []:
        rep = rep_pods.get(pg.metadata.name)
        warm_groups.append(
            GroupDemand(
                f"{pg.metadata.namespace}/{pg.metadata.name}",
                pg.spec.min_member,
                member_request=dict(
                    pg.spec.min_resources
                    or (rep.resource_require() if rep else None)
                    or {"cpu": 1000}
                ),
                # selectors/tolerations decide the fit-mask jit signature
                # ([1,N] broadcast vs full [G,N]) — warm what traffic will hit
                node_selector=dict(rep.spec.node_selector) if rep else {},
                tolerations=list(rep.spec.tolerations) if rep else [],
            )
        )
    warm_groups = warm_groups or [
        GroupDemand("default/warm", 1, member_request={"cpu": 1000})
    ]
    snap = ClusterSnapshot(warm_nodes, {}, warm_groups)
    if remote_scorer is not None:
        remote_scorer._execute(snap)
    else:
        execute_batch_host(snap.device_args(), snap.progress_args())
    return time.perf_counter() - t0


def _maybe_serve_metrics(args):
    """--metrics-port wiring shared by sim and serve: the reference's only
    observability surface is the embedded kube-scheduler's /metrics
    (SURVEY §5); ours exposes the bst_* series over the same protocol."""
    if getattr(args, "metrics_port", None) is None:
        return None
    from ..utils.metrics import serve_metrics

    server = serve_metrics(host="0.0.0.0", port=args.metrics_port)
    print(f"metrics on :{server.server_address[1]}/metrics", flush=True)
    return server


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache for the serving entry points.

    A cold sidecar's first batch pays the full jit compile (~20-40s on
    the accelerator for a new bucket shape) — exactly the stall the PR-1
    deadline budget has to absorb at startup. Persisting compiled
    modules across process restarts turns every warm restart's
    first-batch latency into a cache read. Opt-out/override via
    BST_COMPILATION_CACHE_DIR (empty/"off"/"0" disables); failures
    degrade to no cache, never block serving."""
    cache_dir = os.environ.get(
        "BST_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "bst-xla-cache"),
    )
    if cache_dir.strip().lower() in ("", "0", "off"):
        return
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the oracle's modules are small but expensive to BUILD (the
        # assignment scan unrolls G steps): cache on compile time, not
        # artifact size
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(
            f"persistent compilation cache unavailable ({e!r}); "
            "continuing without",
            file=sys.stderr,
        )


def _resolve_backend_or_degrade() -> None:
    """Probe the accelerator backend before first device use: a hung TPU
    tunnel would otherwise wedge the process inside the first compile with
    no error (utils.backend). On failure the process degrades to CPU and
    keeps serving/scheduling — degradation is printed, not silent."""
    from ..utils.backend import resolve_platform

    platform, err = resolve_platform()
    if err is not None:
        print(
            f"accelerator backend unavailable ({err}); degraded to "
            f"platform={platform}",
            file=sys.stderr,
        )


def cmd_replay(args) -> int:
    """Deterministic replay: reconstruct recorded batches from the audit
    ring, re-execute each on the requested rung, and bit-compare the plan
    digests. Exit 0 = all replayed batches identical; 1 = at least one
    divergence (the structured blame reports are in the summary JSON);
    2 = nothing replayable."""
    from ..core.oracle_scorer import replay_audit_record
    from ..utils.audit import AuditReader

    _resolve_backend_or_degrade()
    _enable_compilation_cache()
    batches, skipped = AuditReader(args.audit_dir).batches()
    if skipped:
        print(
            f"note: {len(skipped)} record(s) unreconstructable (ring "
            "rotated past their keyframe)",
            file=sys.stderr,
        )
    if not batches:
        print(
            f"error: no reconstructable batch records in {args.audit_dir}",
            file=sys.stderr,
        )
        return 2
    if args.batch is not None:
        selected = [r for r in batches if r.get("seq") == args.batch]
        if not selected:
            print(
                f"error: no batch with seq {args.batch} (have seqs "
                f"{batches[0].get('seq')}..{batches[-1].get('seq')})",
                file=sys.stderr,
            )
            return 2
    else:
        selected = batches
    reports, divergent, skipped_degraded = [], 0, 0
    for rec in selected:
        rep = replay_audit_record(rec, against=args.against)
        reports.append(rep)
        if rep.get("skipped"):
            skipped_degraded += 1
            print(
                f"batch seq={rep['seq']} audit_id={rep['audit_id']} "
                f"skipped: {rep['skipped']}",
                flush=True,
            )
            continue
        if rep["identical"]:
            fell_back = (
                " (WARNING: requested rung fell back to serial)"
                if rep.get("rung_fell_back") else ""
            )
            refolded = " (re-folded)" if rep.get("refolded") else ""
            print(
                f"batch seq={rep['seq']} audit_id={rep['audit_id']} "
                f"[{args.against}] identical{refolded}{fell_back}",
                flush=True,
            )
            continue
        divergent += 1
        blame = rep.get("blame") or {}
        print(
            f"batch seq={rep['seq']} audit_id={rep['audit_id']} "
            f"[{args.against}] DIVERGED: field={blame.get('field')} "
            f"gang={blame.get('gang', blame.get('gang_index'))} "
            f"node={blame.get('node', blame.get('node_index'))} "
            f"recorded={blame.get('recorded')} "
            f"replayed={blame.get('replayed')}",
            flush=True,
        )
    summary = {
        "audit_dir": args.audit_dir,
        "against": args.against,
        "replayed": len(selected) - skipped_degraded,
        "identical": len(selected) - divergent - skipped_degraded,
        "divergent": divergent,
        "skipped_degraded": skipped_degraded,
        "unreconstructable": len(skipped),
        # v2 event_batch records reconstructed by re-folding the
        # recorded event stream (docs/observability.md "Audit format v2")
        "refolded": sum(
            1 for r in selected if r.get("record_kind") == "event_batch"
        ),
        "reports": [
            r for r in reports
            if not r.get("skipped") and not r["identical"]
        ],
    }
    print(json.dumps(summary, default=str))
    if args.json:
        # the written artifact (AUDIT_<tag>.json in the capture suite)
        # carries the bench envelope when the repo checkout provides it
        # (make validate-artifacts requires envelopes on new artifacts);
        # an installed package without benchmarks/ writes the bare
        # summary, which the validator's replay-summary recognizer accepts
        doc = summary
        try:
            from benchmarks.artifact import envelope

            doc = envelope(summary)
        except Exception:  # noqa: BLE001 — evidence formatting only
            pass
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, default=str)
    # a steady-rung replay runs UNPINNED, so a fresh compile spawned a
    # bucket-cost-analysis daemon thread; join it before the interpreter
    # (and the XLA runtime) can exit — the same teardown rule as
    # drain_background (this abort made every capture-suite AUDIT step
    # with a cold jit cache report rc=134 as a divergence)
    from ..ops.oracle import drain_telemetry_threads

    drain_telemetry_threads(timeout=60.0)
    if divergent:
        return 1
    if summary["replayed"] == 0:
        # every selected record was a degraded conservative-fallback
        # batch: nothing was actually verified, and exit 0 would let a
        # capture step claim "bit-identical" on zero evidence
        print(
            "error: nothing replayed — every selected record is a "
            "degraded conservative-fallback batch",
            file=sys.stderr,
        )
        return 2
    return 0


def _debug_get(addr: str, path: str, params: Dict[str, str]) -> tuple:
    """GET a /debug endpoint on a live --metrics-port; returns
    (payload dict, http status)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        return {"error": f"--addr {addr!r} is not HOST:PORT"}, 0
    url = (
        f"http://{host or '127.0.0.1'}:{port}{path}"
        f"?{urllib.parse.urlencode(params)}"
    )
    try:
        with urllib.request.urlopen(url, timeout=120) as resp:
            return json.loads(resp.read().decode()), resp.status
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode()), e.code
        except ValueError:
            return {"error": f"HTTP {e.code}"}, e.code
    except (urllib.error.URLError, OSError, ValueError) as e:
        # connection refused / unreachable endpoint: a clean error (and
        # exit 2 in the callers), never a traceback — this is the
        # ready-to-paste command line sim's exit verdict prints
        return {"error": f"cannot reach {addr}: {e}"}, 0


def cmd_explain(args) -> int:
    """Why is this gang pending. Live mode queries /debug/explain on a
    running scheduler; offline mode re-derives the breakdown from a
    recorded audit batch (the replay machinery's inputs). Exit 0 on a
    structured answer, 2 when the gang/record cannot be found."""
    if args.addr:
        payload, status = _debug_get(
            args.addr, "/debug/explain", {"gang": args.gang}
        )
        print(json.dumps(payload, indent=2, default=str))
        return 0 if status == 200 and "error" not in payload else 2
    from ..core.explain import explain_arrays
    from ..utils.audit import AuditReader

    _resolve_backend_or_degrade()
    _enable_compilation_cache()
    batches, _skipped = AuditReader(args.audit_dir).batches()
    if args.batch is not None:
        batches = [r for r in batches if r.get("seq") == args.batch]
    if not batches:
        print(
            f"error: no reconstructable batch record in {args.audit_dir}"
            + (f" with seq {args.batch}" if args.batch is not None else ""),
            file=sys.stderr,
        )
        return 2
    record = batches[-1]
    names = record.get("names") or {}
    groups = names.get("groups") or []
    if args.gang not in groups:
        print(
            f"error: gang {args.gang!r} not in record seq="
            f"{record.get('seq')} ({len(groups)} gangs)",
            file=sys.stderr,
        )
        return 2
    out = explain_arrays(
        record["batch_args"], groups.index(args.gang),
        node_names=names.get("nodes"),
        policy=record.get("policy_args"),
    )
    out["gang"] = args.gang
    out["source"] = {
        "audit_dir": args.audit_dir,
        "seq": record.get("seq"),
        "audit_id": record.get("audit_id"),
    }
    print(json.dumps(out, indent=2, default=str))
    from ..ops.oracle import drain_telemetry_threads

    drain_telemetry_threads(timeout=60.0)  # same teardown rule as replay
    return 0


def cmd_capacity(args) -> int:
    """The capacity observatory's CLI face. Live mode proxies
    /debug/capacity; offline mode replays a recorded audit ring through
    the SAME analytics kernel (ops.capacity.capacity_summary) and
    bit-compares each recomputed summary with the ring's recorded
    ``capacity_sample`` event — the replay discipline applied to the
    analytics series, so a post-mortem sees the identical numbers the
    live process saw. Exit 0 = answered (and, offline, every compared
    sample identical); 1 = divergence; 2 = nothing to answer."""
    if args.addr:
        params: Dict[str, str] = {}
        if args.points is not None:
            params["points"] = str(args.points)
        payload, status = _debug_get(args.addr, "/debug/capacity", params)
        print(json.dumps(payload, indent=2, default=str))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(payload, f, indent=2, default=str)
        # a no-sampler answer is self-describing ({"sampler": null,
        # "hint": ...}) but it is NOT capacity data — honor the exit
        # contract: 2 = nothing to answer
        answered = (
            status == 200
            and "error" not in payload
            and payload.get("sampler", "present") is not None
        )
        return 0 if answered else 2

    from ..ops.capacity import capacity_summary
    from ..utils.audit import AuditReader

    _resolve_backend_or_degrade()
    _enable_compilation_cache()
    recorded: Dict[str, dict] = {}
    batches: List[dict] = []
    for rec in AuditReader(args.audit_dir).records():
        if rec.get("kind") == "batch":
            batches.append(rec)
        elif (
            rec.get("kind") == "event"
            and rec.get("event") == "capacity_sample"
            and rec.get("audit_id")
        ):
            recorded[rec["audit_id"]] = rec.get("summary")
    if args.batch is not None:
        batches = [r for r in batches if r.get("seq") == args.batch]
    if not batches:
        print(
            f"error: no reconstructable batch record in {args.audit_dir}"
            + (f" with seq {args.batch}" if args.batch is not None else ""),
            file=sys.stderr,
        )
        return 2
    series, divergent, compared = [], 0, 0
    for rec in batches:
        names = rec.get("names") or {}
        policy = rec.get("policy_args")
        result = rec["result_arrays"]
        if rec.get("record_kind") == "event_batch":
            # v2 event records keep only the compact plan vectors; the
            # assignment arrays the analytics kernel reads are recovered
            # by re-executing the re-folded inputs, gated on the recorded
            # plan digest (the same identity contract `replay` enforces)
            from ..core.oracle_scorer import replay_batch
            from ..utils.audit import plan_digest

            host, _ = replay_batch(
                rec["batch_args"], rec["progress_args"], against="steady",
                policy=policy,
            )
            if plan_digest(host) != rec.get("plan_digest"):
                divergent += 1
                series.append({
                    "seq": rec.get("seq"),
                    "audit_id": rec.get("audit_id"),
                    "identical": False,
                    "error": "re-executed plan diverges from the recorded "
                             "digest — assignment arrays unrecoverable",
                })
                continue
            result = dict(result)
            for k in ("assignment_nodes", "assignment_counts"):
                result.setdefault(k, host[k])
        summary = capacity_summary(
            rec["batch_args"], result,
            group_names=names.get("groups") or [],
            scheduled=rec["progress_args"][1],
            matched=rec["progress_args"][2],
            policy_prio=policy[0][0] if policy else None,
        )
        # normalize through the same JSON round-trip the recorded event
        # took, so the comparison is representation-for-representation
        summary = json.loads(json.dumps(summary, sort_keys=True))
        entry = {
            "seq": rec.get("seq"),
            "audit_id": rec.get("audit_id"),
            "summary": summary,
        }
        live = recorded.get(rec.get("audit_id"))
        if live is not None:
            compared += 1
            entry["identical"] = live == summary
            if not entry["identical"]:
                divergent += 1
                entry["recorded_summary"] = live
                print(
                    f"batch seq={entry['seq']} audit_id="
                    f"{entry['audit_id']} capacity DIVERGED from the "
                    "recorded live sample",
                    flush=True,
                )
        series.append(entry)
    out = {
        "audit_dir": args.audit_dir,
        "replayed": len(series),
        "compared": compared,
        "divergent": divergent,
        "series": series,
    }
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        doc = out
        try:
            from benchmarks.artifact import envelope

            doc = envelope(out)
        except Exception:  # noqa: BLE001 — evidence formatting only
            pass
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=2, default=str)
    from ..ops.oracle import drain_telemetry_threads

    drain_telemetry_threads(timeout=60.0)  # the replay teardown rule
    return 1 if divergent else 0


def cmd_timeline(args) -> int:
    """A gang's lifecycle timeline. Live mode proxies /debug/gangs on a
    running scheduler; offline mode re-folds the audit ring's
    ``gang_lifecycle`` event records through the ledger's own coalesce
    rule (GangLifecycleLedger.fold) — byte-identical to what the live
    process served (the slo_gate contract). Exit 0 on a structured
    answer, 2 when nothing matches."""
    if args.addr:
        params: Dict[str, str] = {}
        if args.gang:
            params["gang"] = args.gang
        if args.tenant:
            params["tenant"] = args.tenant
        if args.limit is not None:
            params["limit"] = str(args.limit)
        payload, status = _debug_get(args.addr, "/debug/gangs", params)
        print(json.dumps(payload, indent=2, default=str))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(payload, f, indent=2, default=str)
        answered = (
            status == 200
            and "error" not in payload
            and payload.get("count", 0) > 0
        )
        return 0 if answered else 2

    # offline: pure record re-fold — no backend, no device, no drain
    from ..utils.audit import AuditReader
    from ..utils.lifecycle import GangLifecycleLedger

    records = [
        rec
        for rec in AuditReader(args.audit_dir).records()
        if rec.get("kind") == "event"
        and rec.get("event") == "gang_lifecycle"
    ]
    folded = GangLifecycleLedger.fold(records)
    items = [
        (g, rec)
        for g, rec in folded.items()
        if (args.gang is None or g == args.gang)
        and (args.tenant is None or rec.get("tenant") == args.tenant)
    ]
    if args.limit is not None and args.limit >= 0:
        items = items[-args.limit:] if args.limit else []
    gangs = {g: GangLifecycleLedger.timeline_view(rec) for g, rec in items}
    out = {
        "audit_dir": args.audit_dir,
        "records": len(records),
        "gangs": gangs,
        "count": len(gangs),
    }
    print(json.dumps(out, indent=2, default=str))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2, default=str)
    if not gangs:
        print(
            f"error: no gang_lifecycle records in {args.audit_dir}"
            + (f" match gang={args.gang!r}" if args.gang else "")
            + (f" tenant={args.tenant!r}" if args.tenant else ""),
            file=sys.stderr,
        )
        return 2
    return 0


def cmd_whatif(args) -> int:
    """Score one counterfactual against live cluster state (the
    /debug/whatif endpoint's CLI face). Exit 0 on a diff, 2 on error."""
    # `is not None`, not truthiness: argparse guarantees exactly one of
    # the group was provided, and `--add-nodes 0` must reach the server's
    # own range validation (a 400) instead of silently sending nothing
    params: Dict[str, str] = {"rung": args.rung}
    if args.drain is not None:
        params["drain"] = args.drain
    elif args.cordon is not None:
        params["cordon"] = args.cordon
    elif args.add_nodes is not None:
        params.update(
            add_nodes=str(args.add_nodes), node_cpu=args.node_cpu,
            node_memory=args.node_memory, node_pods=args.node_pods,
        )
    elif args.bump_gang is not None:
        if args.tier is None:
            print("error: --bump-gang requires --tier", file=sys.stderr)
            return 2
        params.update(bump_gang=args.bump_gang, tier=str(args.tier))
    elif args.remove_gang is not None:
        params["remove_gang"] = args.remove_gang
    payload, status = _debug_get(args.addr, "/debug/whatif", params)
    print(json.dumps(payload, indent=2, default=str))
    return 0 if status == 200 and "error" not in payload else 2


def cmd_serve(args) -> int:
    from ..parallel.distributed import init_distributed
    from ..service.server import OracleServer

    # multi-host slice bootstrap (no-op unless BST_COORDINATOR is set).
    # MUST precede the backend probe: the probe's degradation path
    # initializes the backend, after which jax.distributed.initialize
    # refuses to run.
    if init_distributed():
        import jax

        print(
            f"jax.distributed initialized: process {jax.process_index()}/"
            f"{jax.process_count()}, {len(jax.devices())} global devices",
            flush=True,
        )
    else:
        _resolve_backend_or_degrade()
    _enable_compilation_cache()

    if args.warmup:
        print(f"warmup compile done in {warm_oracle():.1f}s", flush=True)
    from ..utils.runtime_tuning import freeze_startup

    freeze_startup()

    # server-side local span ring: traced requests' spans land in this
    # process's /debug/trace too (they ALWAYS go back to the client in
    # TRACE_INFO frames, --trace or not)
    _maybe_configure_trace(args)
    _maybe_serve_metrics(args)
    _start_profiler(args)

    server = OracleServer(
        host=args.host, port=args.port, compile_warmer=args.compile_warmer,
        audit_log=_maybe_audit_log(args),
        # flag is sugar over BST_COALESCE; None lets the env decide
        coalesce=True if args.coalesce else None,
    )
    # sidecar-side lifecycle export: nothing flows unless a scheduler
    # runs in-process, but the flag contract is uniform across sim/serve
    _maybe_lifecycle(args)
    host, port = server.address
    print(f"oracle sidecar listening on {host}:{port}", flush=True)

    # SIGTERM = graceful drain (docs/resilience.md "High availability"):
    # stop admitting, finish the in-flight window, flush warmer ->
    # executor -> telemetry -> audit in producer-before-join order, keep
    # answering DRAINING + failover hint meanwhile, THEN exit. Runs on a
    # helper thread so the signal handler returns immediately (drain can
    # legitimately take BST_DRAIN_TIMEOUT_S); shutdown() unblocks
    # serve_forever once the flush is done.
    import signal

    def _drain_and_exit() -> None:
        report = server.drain()
        print(f"drain complete: {json.dumps(report, sort_keys=True)}",
              flush=True)
        server.shutdown()

    def _on_sigterm(signum, frame):  # noqa: ARG001 — signal signature
        print("SIGTERM: draining oracle sidecar", flush=True)
        threading.Thread(
            target=_drain_and_exit, name="drain-sigterm", daemon=True
        ).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        pass  # non-main thread / unsupported platform: abrupt kill remains

    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        server.server_close()
        from ..utils import profiler as profiler_mod

        profiler_mod.shutdown()
    return 0


def _cmd_sim_multi_client(args) -> int:
    """sim --multi-client K: the coalescer acceptance harness as a CLI —
    K concurrent scheduler clients' deterministic oracle streams through
    one sidecar (docs/multitenancy.md "Multi-client sim")."""
    from ..sim.harness import drive_multi_client

    nodes = args.nodes or 256
    gangs = max(args.groups, 1)
    server = None
    addr = args.oracle_addr
    if not addr:
        from ..service.server import serve_background

        # in-process coalescing sidecar: --oracle-addr points the driver
        # at an external `serve --coalesce` instead
        server = serve_background(coalesce=True)
        if server.coalescer is None:
            print(
                "note: in-process sidecar is mesh-backed; coalescing off "
                "(start a single-device `serve --coalesce` and pass "
                "--oracle-addr to exercise the merge queue)",
                file=sys.stderr,
            )
        host, port = server.address
        addr = f"{host}:{port}"
    print(
        f"multi-client sim: {args.multi_client} clients x "
        f"{args.mc_batches} batches, per-tenant [{nodes} nodes, "
        f"{gangs} gangs] via {addr}",
        flush=True,
    )
    try:
        result = drive_multi_client(
            addr,
            clients=args.multi_client,
            batches=args.mc_batches,
            nodes=nodes,
            gangs=gangs,
            deadline_ms=args.oracle_deadline_ms,
        )
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
    wall = result.pop("_wall_s")
    total = sum(len(v["digests"]) for v in result.values())
    busy = sum(v["busy"] for v in result.values())
    print(
        f"aggregate: {total} batches in {wall:.2f}s = "
        f"{total / max(wall, 1e-9):.1f} batches/s"
        + (f", {busy} busy-dropped" if busy else "")
    )
    from ..sim.harness import wait_p95

    for tenant in sorted(result):
        waits = sorted(result[tenant]["waits"])
        if not waits:
            print(f"  {tenant}: no completed batches")
            continue
        p95 = wait_p95(waits)
        print(
            f"  {tenant}: {len(waits)} batches, wait p50 "
            f"{waits[len(waits) // 2] * 1000:.1f}ms p95 {p95 * 1000:.1f}ms"
        )
    return 0


def _terminal(phase) -> bool:
    from ..api import PodGroupPhase

    return phase in (
        PodGroupPhase.RUNNING,
        PodGroupPhase.FINISHED,
        PodGroupPhase.FAILED,
    )


def cmd_sim(args) -> int:
    from ..api.manifest import load_manifest_file
    from ..api.types import Node, Pod, PodGroup
    from ..sim import SimCluster, make_member_pods, make_sim_group, make_sim_node
    from ..sim.scenarios import race_scenario

    cfg = load_scheduler_config(args.config)
    if args.scorer:
        cfg.plugin_config.scorer = args.scorer
    if args.device_state is not None:
        # the flag is sugar over the knob: scorers (and any subprocesses)
        # resolve BST_DEVICE_STATE at construction
        import os

        os.environ["BST_DEVICE_STATE"] = (
            "1" if args.device_state == "on" else "0"
        )

    tracing = _maybe_configure_trace(args)
    _maybe_serve_metrics(args)
    _resolve_backend_or_degrade()
    _enable_compilation_cache()
    _start_profiler(args)

    if args.multi_client > 0:
        return _cmd_sim_multi_client(args)

    scorer = cfg.plugin_config.scorer
    oracle_client = None
    remote_scorer = None
    want_bg_refresh = (
        args.oracle_background_refresh
        or cfg.plugin_config.oracle_background_refresh
    )
    want_dispatch_ahead = (
        args.dispatch_ahead or cfg.plugin_config.oracle_dispatch_ahead
    )
    want_warmer = (
        args.compile_warmer or cfg.plugin_config.oracle_compile_warmer
    )
    if args.oracle_addr:
        from ..service.client import RemoteScorer, ResilientOracleClient

        # resilient transport: reconnect + retry + breaker + deadline —
        # connections are lazy, so a sidecar that is still coming up (or
        # briefly gone) no longer kills the whole run at construction.
        # A comma list configures a warm-standby pool (the client parses
        # the spec itself — parse_oracle_addresses).
        # Dispatch-ahead widens the in-flight window to 2 connection
        # slots so the speculative batch never contends with row reads
        # on the served batch (docs/pipelining.md).
        oracle_client = ResilientOracleClient(
            args.oracle_addr,
            deadline_ms=args.oracle_deadline_ms, name="fg",
            window=2 if want_dispatch_ahead else 1,
        )
        # background refresh needs a second connection so row reads on the
        # current batch never contend with the in-flight background batch
        bg_client = None
        if want_bg_refresh:
            bg_client = ResilientOracleClient(
                args.oracle_addr,
                deadline_ms=args.oracle_deadline_ms, name="bg",
            )
        scorer = RemoteScorer(
            oracle_client,
            background_client=bg_client,
            fallback=args.oracle_fallback,
        )
        remote_scorer = scorer
        if want_warmer:
            print(
                "note: --compile-warmer warms the LOCAL jit cache; with "
                "--oracle-addr batches compile on the sidecar — start "
                "`serve --compile-warmer` there instead",
                file=sys.stderr,
            )

    policy_cfg = None
    if args.policy:
        # CLI form of BST_POLICY: the env var keeps working (PolicyConfig
        # reads it when no explicit config is passed); the flag wins
        import os as _os

        from ..policy.engine import PolicyConfig

        _os.environ["BST_POLICY"] = args.policy
        policy_cfg = PolicyConfig.from_env()
        print(
            f"policy engine: terms={list(policy_cfg.terms)} "
            f"fingerprint={policy_cfg.fingerprint()['fingerprint']}",
            file=sys.stderr,
        )

    audit_log = _maybe_audit_log(args)
    cluster = SimCluster(
        scorer=scorer,
        max_schedule_minutes=cfg.plugin_config.max_schedule_minutes,
        enabled_points=cfg.enabled_points,
        min_batch_interval=cfg.plugin_config.min_batch_interval_seconds,
        oracle_background_refresh=want_bg_refresh,
        oracle_dispatch_ahead=want_dispatch_ahead,
        oracle_compile_warmer=want_warmer and oracle_client is None,
        audit_log=audit_log,
        identity_audit_every=args.identity_audit_every,
        policy=policy_cfg,
    )
    # after SimCluster: the operation's construction reset the ledger
    _maybe_lifecycle(args, audit_log)

    nodes: List[Node] = []
    groups: List[PodGroup] = []
    pods: List[Pod] = []

    for path in args.filename:
        for obj in load_manifest_file(path):
            if isinstance(obj, Node):
                nodes.append(obj)
            elif isinstance(obj, PodGroup):
                groups.append(obj)
            elif isinstance(obj, Pod):
                pods.append(obj)

    if args.scenario == "race":
        rnodes, rgroups, rpods = race_scenario()
        nodes += rnodes
        groups += rgroups
        for plist in rpods.values():
            pods += plist
    elif args.scenario == "synthetic":
        for g in range(args.groups):
            name = f"group-{g:03d}"
            groups.append(make_sim_group(name, args.members))
            pods += make_member_pods(name, args.members, {"cpu": "1"})
    elif args.scenario == "spot-vs-guaranteed":
        from ..sim.scenarios import spot_vs_guaranteed_scenario

        snodes, sgroups, spods = spot_vs_guaranteed_scenario()
        nodes += snodes
        groups += sgroups
        for plist in spods.values():
            pods += plist
        # the operation reads BST_POLICY itself when no explicit config is
        # passed — check the EFFECTIVE config before warning
        from ..policy.engine import PolicyConfig as _PC

        effective = policy_cfg if policy_cfg is not None else _PC.from_env()
        if not effective.preemption:
            print(
                "note: spot-vs-guaranteed without the preempt term "
                "(--policy preempt / BST_POLICY) — the guaranteed gang "
                "will queue-jump but cannot evict spot capacity",
                file=sys.stderr,
            )
        if args.settle <= 3.0:
            # permit-parked quorums and deny-cache retries produce no
            # observable change for up to a 20s TTL window; the default
            # settle would conclude "stuck" mid-transaction
            args.settle = 30.0
            print(
                "note: --settle raised to 30s for this scenario (permit "
                "parks + deny-TTL retries look idle to a shorter window)",
                file=sys.stderr,
            )

    for i in range(args.nodes):
        nodes.append(
            make_sim_node(
                f"sim-node-{i:04d}",
                {"cpu": args.node_cpu, "memory": args.node_memory, "pods": "110"},
            )
        )

    if not nodes:
        print("error: no nodes (use -f with Node manifests or --nodes N)", file=sys.stderr)
        return 2
    if not groups:
        print("error: no PodGroups (use -f or --scenario)", file=sys.stderr)
        return 2

    if scorer == "oracle" or oracle_client is not None:
        # Compile this cluster's bucket shapes before admitting traffic: the
        # first jit otherwise lands inside the first scheduling callback, and
        # on a short --settle the run can conclude "nothing is moving" while
        # XLA is still compiling. For --oracle-addr the warm batch goes over
        # the wire so the *sidecar's* jit cache (the one real traffic hits)
        # is what warms.
        elapsed = warm_oracle(
            nodes=nodes, groups=groups, pods=pods,
            remote_scorer=scorer if oracle_client is not None else None,
        )
        print(f"oracle warmup compile: {elapsed:.1f}s", flush=True)
    from ..utils.runtime_tuning import freeze_startup

    freeze_startup()

    cluster.add_nodes(nodes)
    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    try:
        if args.scenario == "spot-vs-guaranteed":
            # staged arrival: the scenario demos PREEMPTION, which needs
            # the spot tier bound BEFORE the guaranteed tier arrives
            # (simultaneous arrival just demos queue priority). Hold the
            # guaranteed pods back until spot stops making progress.
            guar = [
                p for p in pods
                if p.metadata.labels.get(POD_GROUP_LABEL, "").startswith(
                    "guaranteed"
                )
            ]
            spot = [p for p in pods if p not in guar]
            cluster.create_pods(spot)
            spot_deadline = time.monotonic() + min(args.timeout / 2, 90)
            last_bound, stable = -1, time.monotonic()
            while time.monotonic() < spot_deadline:
                bound = sum(
                    1
                    for p in spot
                    if (cluster.clientset.pods(p.metadata.namespace)
                        .get(p.metadata.name).spec.node_name)
                )
                if bound >= len(spot):
                    last_bound = bound
                    break
                if bound != last_bound:
                    last_bound, stable = bound, time.monotonic()
                elif time.monotonic() - stable > 25.0:
                    # a full deny-cache TTL with no progress: the spot
                    # tier is as bound as it gets
                    break
                time.sleep(0.2)
            print(
                f"spot tier settled ({last_bound} bound); releasing "
                f"guaranteed tier",
                flush=True,
            )
            cluster.create_pods(guar)
        else:
            cluster.create_pods(pods)

        deadline = time.monotonic() + args.timeout
        names = [(pg.metadata.namespace, pg.metadata.name) for pg in groups]
        last_state, stable_since = None, time.monotonic()
        while time.monotonic() < deadline:
            state = tuple(
                (
                    cluster.group_phase(n, ns),
                    sum(1 for p in cluster.member_pods(n, ns) if p.spec.node_name),
                )
                for ns, n in names
            )
            if all(_terminal(p) for p, _ in state):
                break
            now = time.monotonic()
            if state != last_state:
                last_state, stable_since = state, now
            elif now - stable_since >= args.settle:
                # nothing has moved for a while: denied gangs never reach a
                # terminal phase, so this is the settled outcome
                break
            time.sleep(0.2)

        print(f"{'GROUP':<28} {'PHASE':<14} {'MINMEMBER':>9} {'BOUND':>6} MEMBERS")
        for ns, name in names:
            pg = cluster.group(name, ns)
            members = cluster.member_phase_counts(name, ns)
            bound = sum(
                1 for p in cluster.member_pods(name, ns) if p.spec.node_name
            )
            print(
                f"{ns + '/' + name:<28} {pg.status.phase.value or 'Pending':<14} "
                f"{pg.spec.min_member:>9} {bound:>6} {members}"
            )
        stats = cluster.scheduler.stats
        print(f"scheduler stats: {dict(stats)}")
        oracle = getattr(cluster.runtime.operation, "oracle", None)
        if oracle is not None and getattr(oracle, "batches_run", 0):
            print(f"oracle stats: {oracle.stats()}")
        if audit_log is not None:
            audit_log.flush()
            print(f"audit stats: {audit_log.stats()}")
            print(
                "replay with: python -m batch_scheduler_tpu replay "
                f"{args.audit_dir}"
            )
        # the SLO health verdict on exit: "degraded and why" without an
        # operator asking (live form: /debug/health on --metrics-port)
        health = cluster.health()
        bad = {
            name: sig.get("reason") or f"p95 {sig.get('p95_s')}s"
            for name, sig in health["signals"].items()
            if sig["verdict"] != "ok"
        }
        print(
            f"slo health: {health['verdict']}"
            + (f" ({bad})" if bad else "")
        )
        # capacity observatory verdict beside the health line: how full,
        # how fragmented, who is consuming it (live form: /debug/capacity)
        from ..ops.capacity import active_sampler, format_capacity_verdict

        sampler = active_sampler()
        cap_last = sampler.last() if sampler is not None else None
        if cap_last is not None:
            print(format_capacity_verdict(cap_last, sampler.lane_names()))
            burn = health["signals"].get("burn:capacity") or {}
            if burn.get("verdict") not in (None, "ok"):
                print(
                    f"capacity burn: {burn['verdict']} ({burn['reason']})"
                )
        # pending-gang aging in the exit verdict: who is starving and how
        # long (the live form is the /debug/health "pending" signal)
        pend = health["signals"].get("pending") or {}
        if pend.get("pending_gangs"):
            print(
                f"pending gangs: {pend['pending_gangs']} "
                f"(oldest {pend.get('oldest_gang')} "
                f"{pend.get('oldest_age_s', 0):.1f}s, max deny streak "
                f"{pend.get('max_deny_streak', 0)}) — explain with: "
                f"python -m batch_scheduler_tpu explain "
                f"{pend.get('oldest_gang')} --addr <metrics-port>"
            )
        # per-tenant placement verdict: p99 time-to-placement from the
        # gang lifecycle ledger (live forms: /debug/gangs timelines and
        # the /debug/health burn:ttp signal)
        from ..utils.lifecycle import DEFAULT_LEDGER

        life = DEFAULT_LEDGER.report()
        if life.get("tenants"):
            parts = ", ".join(
                f"{t} p99 {d['p99_ttp_s']:.2f}s/{d['count']}"
                for t, d in sorted(life["tenants"].items())
            )
            print(f"placement ttp (tenant p99/gangs): {parts}")
            ttp_burn = health["signals"].get("burn:ttp") or {}
            if ttp_burn.get("verdict") not in (None, "ok"):
                print(
                    f"ttp burn: {ttp_burn['verdict']} "
                    f"({ttp_burn['reason']})"
                )
        if tracing:
            from ..utils.trace import DEFAULT_FLIGHT_RECORDER

            _export_trace(args)
            verdicts: Dict[str, int] = {}
            for recs in DEFAULT_FLIGHT_RECORDER.snapshot().values():
                for r in recs:
                    verdicts[r["verdict"]] = verdicts.get(r["verdict"], 0) + 1
            print(f"flight recorder decisions: {verdicts}")
    finally:
        cluster.stop()
        if audit_log is not None:
            audit_log.stop()
        if remote_scorer is not None:
            remote_scorer.close()  # closes both connections
        from ..utils import profiler as profiler_mod

        profiler_mod.shutdown()
    return 0


COMMANDS = {
    "version": cmd_version,
    "check-config": cmd_check_config,
    "serve": cmd_serve,
    "sim": cmd_sim,
    "replay": cmd_replay,
    "explain": cmd_explain,
    "whatif": cmd_whatif,
    "capacity": cmd_capacity,
    "timeline": cmd_timeline,
}


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # klog-style: --v 0 warnings only, 1-2 info, >=3 debug
    import logging

    level = (
        logging.WARNING if args.v <= 0 else logging.INFO if args.v <= 2 else logging.DEBUG
    )
    logging.basicConfig(level=level)
    # Runtime tuning (the knob the Go reference reaches via GOMAXPROCS):
    # the scheduler is one compute-bound cycle thread beside ~25 mostly-
    # idle service threads; CPython's default 5ms GIL switch interval
    # costs measurable handoff time under a 10k-pod drain (ladder config
    # 6: cycle_total 0.77s -> ~0.4-0.6s at 20ms). BST_GIL_SWITCH_INTERVAL
    # overrides; 0 keeps the interpreter default.
    try:
        interval = float(os.environ.get("BST_GIL_SWITCH_INTERVAL", "0.02"))
    except ValueError:
        logging.warning(
            "ignoring malformed BST_GIL_SWITCH_INTERVAL=%r; using 0.02",
            os.environ.get("BST_GIL_SWITCH_INTERVAL"),
        )
        interval = 0.02
    if interval > 0:
        sys.setswitchinterval(interval)
    # GC thresholds are runtime tuning of the same kind (see
    # utils.runtime_tuning); freeze_startup runs after each command's
    # warmup so jit caches land in the frozen set too
    from ..utils.runtime_tuning import apply_gc_tuning

    apply_gc_tuning()
    return COMMANDS[args.command](args)
