"""Scheduler configuration file: the analog of ``KubeSchedulerConfiguration``.

The reference is configured by a three-layer stack — kube-scheduler flags,
a scheduler-config JSON choosing extension points, and ``pluginConfig.args``
unmarshalled into the plugin's ``Configuration`` struct (reference
deploy/scheduler/config/batch_scheduler_config.json:7-44,
pkg/scheduler/batch/batchscheduler.go:71-75,377-383). This module parses the
same JSON shape (and our superset) into the internal
:class:`~batch_scheduler_tpu.plugin.factory.PluginConfig` plus the enabled
extension-point set consumed by
:class:`~batch_scheduler_tpu.plugin.gate.ExtensionPointGate`.

``max_schedule_time`` keeps the reference's **minutes** interpretation
(batchscheduler.go:406). The ``scorer`` arg is the north-star ``--scorer=tpu``
gate: "oracle" (TPU batch) or "serial" (reference-parity in-process path).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import FrozenSet, Optional

from ..plugin.factory import PluginConfig
from ..plugin.gate import ALL_EXTENSION_POINTS, DEFAULT_ENABLED

__all__ = ["SchedulerConfiguration", "load_scheduler_config", "PLUGIN_NAME"]

PLUGIN_NAME = "batch-scheduler"

_ACCEPTED_KINDS = {"SchedulerConfiguration", "KubeSchedulerConfiguration"}


def _require_bool(args: dict, key: str, default: bool = False) -> bool:
    """Strict JSON-boolean read: ``bool("false")`` is True, so a string here
    would silently mean the opposite of what the operator wrote."""
    value = args.get(key, default)
    if not isinstance(value, bool):
        raise ValueError(
            f"pluginConfig args.{key} must be a JSON boolean, got {value!r}"
        )
    return value


@dataclass
class SchedulerConfiguration:
    plugin_config: PluginConfig = field(default_factory=PluginConfig)
    enabled_points: FrozenSet[str] = DEFAULT_ENABLED
    # Accepted for reference parity; unused (no external API server here).
    kubeconfig: str = ""

    @classmethod
    def from_dict(cls, doc: dict) -> "SchedulerConfiguration":
        kind = doc.get("kind", "SchedulerConfiguration")
        if kind not in _ACCEPTED_KINDS:
            raise ValueError(f"unsupported config kind: {kind!r}")

        enabled = set()
        plugins = doc.get("plugins")
        if plugins is None:
            enabled = set(DEFAULT_ENABLED)
        else:
            for point, spec in plugins.items():
                if point not in ALL_EXTENSION_POINTS:
                    raise ValueError(f"unknown extension point: {point!r}")
                names = [e.get("name") for e in (spec or {}).get("enabled", [])]
                if PLUGIN_NAME in names:
                    enabled.add(point)

        args = {}
        for entry in doc.get("pluginConfig", []):
            if entry.get("name") == PLUGIN_NAME:
                args = entry.get("args") or {}

        max_minutes: Optional[float] = None
        if args.get("max_schedule_time") is not None:
            max_minutes = float(args["max_schedule_time"])

        plugin_config = PluginConfig(
            max_schedule_minutes=max_minutes,
            scorer=args.get("scorer", "oracle"),
            controller_workers=int(args.get("controller_workers", 10)),
            leader_poll_seconds=float(args.get("leader_poll_seconds", 1.0)),
            min_batch_interval_seconds=float(
                args.get("min_batch_interval_seconds", 0.0)
            ),
            oracle_background_refresh=_require_bool(
                args, "oracle_background_refresh"
            ),
            oracle_dispatch_ahead=_require_bool(args, "oracle_dispatch_ahead"),
            oracle_compile_warmer=_require_bool(args, "oracle_compile_warmer"),
        )
        return cls(
            plugin_config=plugin_config,
            enabled_points=frozenset(enabled),
            kubeconfig=(doc.get("clientConnection") or {}).get("kubeconfig", "")
            or args.get("kube_config", ""),
        )


def load_scheduler_config(path: Optional[str]) -> SchedulerConfiguration:
    """Load a scheduler config JSON; None -> all defaults (the reference's
    shipped extension points + oracle scorer)."""
    if path is None:
        return SchedulerConfiguration()
    with open(path, "r", encoding="utf-8") as fh:
        return SchedulerConfiguration.from_dict(json.load(fh))
