"""Multi-tenant oracle coalescer: one sidecar, many schedulers.

The paper's oracle serves exactly one scheduler per sidecar; the north
star's fleets want K clusters (or shards of one huge cluster) each running
a plugin, all hitting one shared TPU oracle pool. This module is the
cross-client batching subsystem in front of the ``DeviceExecutor``
(docs/multitenancy.md): pending schedule requests from different
connections are admitted in a DRF-fair order, merged into groups, and
executed so the device never idles between tenants — the inference-server
continuous-batching pattern (Orca) applied to scheduling batches, with the
datacenter-scheduling fairness half (Dominant Resource Fairness, Ghodsi et
al.) deciding who goes first.

Two merge lowerings, selected per group (``BST_COALESCE_MODE``; the gate
``make bench-coalesce`` measures both):

- **span** — per-span re-dispatch: each tenant's already-padded batch is
  submitted to the executor back-to-back in admission order, so batch
  N+1's dispatch overlaps batch N's device compute (the executor's
  in-flight window). Bit-identity to a dedicated sidecar is trivial —
  it IS the dedicated dispatch, pipelined.
- **mega** — block-diagonal mega-batch: tenants' unpadded arrays
  concatenate along G *and* N (each tenant's gangs are fit-masked to its
  own node block), pad once, ONE device batch. The serial scan is
  order-dependent through the carried [N,R] leftover, but the mega-batch
  is **block-diagonal over node state, never a shared leftover**: a
  tenant's gangs can only take (and only see capacity in) its own node
  rows, so each tenant's sub-scan runs against exactly the leftover its
  dedicated run would carry — per-tenant plans equal the dedicated
  sidecar's BY CONSTRUCTION, on every scan rung (they are all
  bit-identical to the serial scan). The demux slices each tenant's G
  span, maps assignment indices back by its node offset
  (ops.oracle.repack_assignment_span re-derives the dedicated compact
  row exactly, including the top-k zero-count backfill), and recomputes
  the per-tenant max-progress ``best`` from the tenant's own padded
  progress args (ops.oracle.find_max_group_host — progress args are pure
  inputs, untouched by the scan). The mega scan's cost is
  O(G_tot·N_tot·R) — quadratically wasteful at large shapes — so
  ``auto`` mode uses it only below ``BST_COALESCE_MEGA_CELLS``, where
  per-batch fixed overhead (queue hops, O(G) readback, host sync)
  dominates the extra cells.

**DRF admission order**: among tenants with pending work, the one with
the lowest dominant share dequeues first. The share has two live
components: the capacity observatory's per-tenant dominant-resource
share (``bst_capacity_tenant_share`` — what the tenant already holds of
the cluster) fed through ``weights_fn``, plus the coalescer's own
exponentially-decayed serviced-work fraction (gangs×nodes dispatched;
half-life ``BST_COALESCE_FAIR_HALFLIFE_S``) — so a whale flooding the
queue accumulates serviced share and a starved small tenant sorts ahead
of it within one merge group: its p95 queue wait is bounded by a couple
of group service times, not by the whale's backlog (gated by ``make
bench-coalesce``).

**Admission control**: the merge queue is bounded (``BST_COALESCE_DEPTH``
jobs). A submit over the bound raises :class:`CoalesceSaturated` and the
server answers an in-band ``BUSY`` frame with a retry-after hint derived
from the live service rate — the resilient client waits it out and
retries through its existing retry machinery, never a silent hang
(docs/multitenancy.md "Admission control").
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.metrics import DEFAULT_REGISTRY

__all__ = [
    "CoalesceJob",
    "CoalesceResult",
    "CoalesceSaturated",
    "OracleCoalescer",
    "build_mega_batch",
    "coalesce_enabled",
    "coalesce_depth",
    "coalesce_mode",
    "coalesce_span_max",
    "coalesce_mega_cells",
    "coalesce_fair_halflife",
]


# ---------------------------------------------------------------------------
# env knobs (all parse-guarded — the BST_SCAN_WAVE idiom)
# ---------------------------------------------------------------------------

_env_warned = [False]


def coalesce_enabled() -> bool:
    """Parse-guarded BST_COALESCE read: default OFF (the single-scheduler
    deployment stays byte-identical); ``1``/``on`` enables the coalescer
    in front of the sidecar executor; unrecognised values warn once and
    keep the default."""
    raw = os.environ.get("BST_COALESCE", "").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return False
    if raw in ("1", "on", "true", "yes"):
        return True
    if not _env_warned[0]:
        _env_warned[0] = True
        print(
            f"ignoring unrecognised BST_COALESCE={raw!r}; coalescing stays "
            "off",
            file=sys.stderr,
        )
    return False


def _int_knob(name: str, default: int, lo: int, hi: int) -> int:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return min(max(int(raw), lo), hi)
        except ValueError:
            pass
    return default


def coalesce_depth() -> int:
    """BST_COALESCE_DEPTH: bounded admission-queue depth (pending jobs
    across all tenants) before submits answer BUSY."""
    return _int_knob("BST_COALESCE_DEPTH", 64, 1, 4096)


def coalesce_span_max() -> int:
    """BST_COALESCE_SPAN_MAX: max tenant spans merged into one group."""
    return _int_knob("BST_COALESCE_SPAN_MAX", 8, 1, 64)


def coalesce_mega_cells() -> int:
    """BST_COALESCE_MEGA_CELLS: auto mode builds a block-diagonal
    mega-batch only while the merged G_tot*N_tot stays under this (the
    mega scan pays O(G_tot*N_tot*R); past this bound the per-span
    pipeline wins — the bench-coalesce measurement)."""
    return _int_knob("BST_COALESCE_MEGA_CELLS", 1 << 21, 1 << 10, 1 << 30)


def coalesce_mode() -> str:
    """BST_COALESCE_MODE: ``span`` | ``mega`` | ``auto`` (default)."""
    raw = os.environ.get("BST_COALESCE_MODE", "").strip().lower()
    if raw in ("span", "mega", "auto"):
        return raw
    return "auto"


def coalesce_fair_halflife() -> float:
    """BST_COALESCE_FAIR_HALFLIFE_S: decay half-life of the serviced-work
    share the DRF order consumes (seconds)."""
    raw = os.environ.get("BST_COALESCE_FAIR_HALFLIFE_S", "").strip()
    if raw:
        try:
            return min(max(float(raw), 0.1), 3600.0)
        except ValueError:
            pass
    return 10.0


# ---------------------------------------------------------------------------
# jobs and results
# ---------------------------------------------------------------------------


class CoalesceSaturated(RuntimeError):
    """The bounded admission queue is full — answered in-band as a BUSY
    frame (service.protocol), never a silent hang."""

    def __init__(self, retry_after_ms: int):
        super().__init__(
            f"coalescer queue saturated; retry after {retry_after_ms}ms"
        )
        self.retry_after_ms = int(retry_after_ms)


class CoalesceResult:
    """One tenant's demuxed outcome: the per-tenant O(G) host dict (equal
    to a dedicated sidecar's), a row view for ROW_REQ gathers in the
    tenant's own node space, the tenant's dedicated-equivalent padded
    audit args (when requested), and the timing split."""

    __slots__ = ("host", "rows", "queue_wait", "run_seconds", "audit_args")

    def __init__(self, host, rows, queue_wait, run_seconds, audit_args=None):
        self.host = host
        self.rows = rows
        self.queue_wait = queue_wait
        self.run_seconds = run_seconds
        self.audit_args = audit_args


class _RowView:
    """Lazy (G,N)-row gathers for one tenant span. ``gather`` issues the
    device read through the executor queue (the same total-order rule row
    requests always followed) and slices the row back into the tenant's
    node space."""

    __slots__ = ("_executor", "_device", "_goff", "_noff", "_n")

    def __init__(self, executor, device_result, goff: int, noff: int, n: int):
        self._executor = executor
        self._device = device_result
        self._goff = goff
        self._noff = noff
        self._n = n

    def gather(self, kind: str, gidx: int) -> np.ndarray:
        import jax

        device = self._device
        goff, noff, n = self._goff, self._noff, self._n

        def _g():
            row = np.asarray(jax.device_get(device[kind][goff + gidx]))
            return row.astype("<i4")[noff:noff + n]

        return self._executor.run(_g)


class CoalesceJob:
    """One pending tenant batch. ``padded_args``/``progress_args`` are the
    tenant's OWN canonically padded batch (host numpy for full requests;
    the device-resident mirror's buffers for wire deltas — those pin
    ``donate=False``), ready for per-span dispatch. ``raw_fn`` lazily
    materialises the unpadded host arrays the mega merge concatenates
    (for mirror batches this is a device readback, paid only when a mega
    group actually forms)."""

    __slots__ = ("tenant", "wire_tenant", "n", "g", "r", "padded_args",
                 "progress_args", "raw_fn", "donate", "want_audit",
                 "enqueued", "_done", "_result", "_error", "_dispatched")

    def __init__(self, tenant: str, n: int, g: int, r: int, padded_args,
                 progress_args, raw_fn: Callable[[], tuple],
                 donate: Optional[bool] = None, want_audit: bool = False):
        # the DRF queue key: unannounced clients share the "other"
        # fairness bucket (the capacity observatory's overflow label, so
        # its weights apply); wire_tenant keeps the raw announcement for
        # scan-counter attribution — an unannounced client must label
        # "-" exactly as it does on the direct (non-coalescing) path
        self.tenant = tenant or "other"
        self.wire_tenant = tenant or None
        self.n = int(n)
        self.g = int(g)
        self.r = int(r)
        self.padded_args = padded_args
        self.progress_args = progress_args
        self.raw_fn = raw_fn
        self.donate = donate
        self.want_audit = want_audit
        self.enqueued = time.perf_counter()
        self._done = threading.Event()
        self._result: Optional[CoalesceResult] = None
        self._error: Optional[BaseException] = None
        self._dispatched = False

    def finish(self, result=None, error: Optional[BaseException] = None):
        self._result, self._error = result, error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> CoalesceResult:
        if not self._done.wait(timeout):
            raise TimeoutError("coalesced batch still running")
        if self._error is not None:
            raise self._error
        return self._result

    def audit_copy(self):
        """Host-side copy of the tenant's padded args for the audit
        record (mirror batches hold device arrays — the record must
        replay on any backend)."""
        if not self.want_audit:
            return None
        return (
            tuple(np.asarray(a) for a in self.padded_args),
            tuple(np.asarray(a) for a in self.progress_args),
        )


# ---------------------------------------------------------------------------
# the block-diagonal merge (pure host side)
# ---------------------------------------------------------------------------


def build_mega_batch(raws):
    """Pure host-side block-diagonal merge of K tenants' raw (unpadded)
    oracle arrays — the mega lowering's concatenation + pad, factored out
    of the worker so the perf-regression gate can probe the merge hot
    path without an executor (benchmarks/perf_regress.py
    ``coalesce_merge_s``).

    ``raws`` is a list of 12-tuples in ScheduleRequest field order
    (alloc, requested, group_req, remaining, fit_mask, group_valid,
    order, min_member, scheduled, matched, ineligible, creation_rank);
    each tenant's n/g derive from its own array shapes. Returns
    ``(batch_args, progress_args, noffs, goffs)`` — the padded mega
    batch plus each tenant's node/gang offset for the demux. The fit
    mask is the block-diagonal construction: tenant i's gangs see ONLY
    tenant i's node rows — everything else stays False, so its capacity
    there is zero and its sub-scan carries exactly the leftover a
    dedicated run would."""
    from ..ops.bucketing import pad_oracle_batch

    ns = [int(np.asarray(r[0]).shape[0]) for r in raws]
    gs = [int(np.asarray(r[2]).shape[0]) for r in raws]
    n_tot, g_tot = sum(ns), sum(gs)
    noffs, goffs = [], []
    noff = goff = 0
    for n, g in zip(ns, gs):
        noffs.append(noff)
        goffs.append(goff)
        noff += n
        goff += g
    (alloc, requested, group_req, remaining, group_valid, order,
     min_member, scheduled, matched, ineligible, creation_rank) = (
        [], [], [], [], [], [], [], [], [], [], []
    )
    fit_mask = np.zeros((g_tot, n_tot), dtype=bool)
    for i, raw in enumerate(raws):
        (r_alloc, r_req, r_greq, r_rem, r_mask, r_valid, r_order,
         r_minm, r_sched, r_match, r_inel, r_rank) = raw
        alloc.append(np.asarray(r_alloc))
        requested.append(np.asarray(r_req))
        group_req.append(np.asarray(r_greq))
        remaining.append(np.asarray(r_rem))
        group_valid.append(np.asarray(r_valid))
        order.append(np.asarray(r_order, dtype=np.int32) + goffs[i])
        min_member.append(np.asarray(r_minm))
        scheduled.append(np.asarray(r_sched))
        matched.append(np.asarray(r_match))
        ineligible.append(np.asarray(r_inel))
        creation_rank.append(np.asarray(r_rank))
        mask = np.asarray(r_mask, dtype=bool)
        if mask.shape[0] == 1:
            mask = np.broadcast_to(mask, (gs[i], ns[i]))
        fit_mask[
            goffs[i]:goffs[i] + gs[i], noffs[i]:noffs[i] + ns[i]
        ] = mask[:gs[i], :ns[i]]
    batch_args, progress_args = pad_oracle_batch(
        alloc=np.concatenate(alloc, axis=0),
        requested=np.concatenate(requested, axis=0),
        group_req=np.concatenate(group_req, axis=0),
        remaining=np.concatenate(remaining, axis=0),
        fit_mask=fit_mask,
        group_valid=np.concatenate(group_valid, axis=0),
        order=np.concatenate(order, axis=0),
        min_member=np.concatenate(min_member, axis=0),
        scheduled=np.concatenate(scheduled, axis=0),
        matched=np.concatenate(matched, axis=0),
        ineligible=np.concatenate(ineligible, axis=0),
        creation_rank=np.concatenate(creation_rank, axis=0),
    )
    return batch_args, progress_args, noffs, goffs


# ---------------------------------------------------------------------------
# the coalescer
# ---------------------------------------------------------------------------


class OracleCoalescer:
    """Cross-client merge queue in front of a ``DeviceExecutor``.

    One worker thread owns group formation: it admits pending jobs in DRF
    order (see module docstring), merges up to ``span_max`` of them, and
    executes the group — per-span pipelined dispatches or one
    block-diagonal mega-batch — completing each job with its demuxed,
    dedicated-sidecar-identical result. Submission is bounded
    (:class:`CoalesceSaturated` -> BUSY).

    ``weights_fn`` supplies the capacity observatory's per-tenant
    dominant shares ({tenant: share in [0,1]}); None (or an empty answer)
    degrades to the serviced-work share alone.
    """

    def __init__(self, executor, weights_fn: Optional[Callable] = None,
                 depth: Optional[int] = None,
                 span_max: Optional[int] = None,
                 mode: Optional[str] = None,
                 mega_cells: Optional[int] = None,
                 registry=None):
        self._executor = executor
        self._weights_fn = weights_fn
        self.depth = depth if depth is not None else coalesce_depth()
        self.span_max = (
            span_max if span_max is not None else coalesce_span_max()
        )
        self.mode = mode if mode is not None else coalesce_mode()
        self.mega_cells = (
            mega_cells if mega_cells is not None else coalesce_mega_cells()
        )
        self._cv = threading.Condition()
        self._queues: Dict[str, deque] = {}  # guarded-by: _cv
        self._pending = 0  # guarded-by: _cv
        self._served: Dict[str, float] = {}  # guarded-by: _cv
        self._served_at = time.monotonic()  # guarded-by: _cv
        self._service_s = 0.05  # EWMA group service time; guarded-by: _cv
        self._stopped = False  # guarded-by: _cv
        self.groups_run = 0  # guarded-by: _cv
        self.mega_groups = 0  # guarded-by: _cv
        reg = registry or DEFAULT_REGISTRY
        self._merged = reg.counter(
            "bst_coalesce_merged_batches_total",
            "Coalesced merge groups executed, by lowering (span = "
            "per-span pipelined re-dispatch; mega = one block-diagonal "
            "mega-batch)",
        )
        self._width = reg.histogram(
            "bst_coalesce_span_width",
            "Tenant spans per executed merge group (1 = nothing to merge "
            "with)",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64),
        )
        self._wait = reg.histogram(
            "bst_coalesce_queue_wait_seconds",
            "Per-request wait in the coalescer admission queue, by tenant "
            "(the DRF starvation bound's observable)",
        )
        self._busy = reg.counter(
            "bst_coalesce_busy_total",
            "Requests refused with BUSY because the bounded coalescer "
            "queue was saturated (the client retries after the hint)",
        )
        self._depth_gauge = reg.gauge(
            "bst_coalesce_queue_depth",
            "Jobs waiting in the coalescer admission queue",
        )
        self._thread = threading.Thread(
            target=self._loop, name="oracle-coalescer", daemon=True
        )
        self._thread.start()

    # -- submission ----------------------------------------------------------

    def _retry_after_ms_locked(self) -> int:  # lock-held: _cv
        # pending jobs drain span_max per group at the live group service
        # rate — tell the client roughly when a slot frees up
        groups_queued = max(self._pending // max(self.span_max, 1), 1)
        est = self._service_s * groups_queued
        return int(min(max(est * 1000.0, 25.0), 5000.0))

    def check_admission(self) -> None:
        """Raise :class:`CoalesceSaturated` if a submit right now would be
        refused. The delta wire path calls this BEFORE applying churned
        rows to its mirror, so a BUSY answer normally leaves the client's
        generation cursor valid for a plain retry (a fill-up between this
        check and the submit converges through DELTA_RESYNC -> keyframe)."""
        with self._cv:
            if self._stopped:
                raise RuntimeError("coalescer stopped")
            if self._pending >= self.depth:
                self._busy.inc()
                raise CoalesceSaturated(self._retry_after_ms_locked())

    def schedule(self, job: CoalesceJob) -> CoalesceResult:
        """Enqueue one tenant batch and block for its demuxed result.
        Raises :class:`CoalesceSaturated` (queue full — answer BUSY) or
        the batch's own execution error."""
        with self._cv:
            if self._stopped:
                raise RuntimeError("coalescer stopped")
            if self._pending >= self.depth:
                self._busy.inc()
                raise CoalesceSaturated(self._retry_after_ms_locked())
            self._queues.setdefault(job.tenant, deque()).append(job)
            self._pending += 1
            self._depth_gauge.set(float(self._pending))
            self._cv.notify()
        return job.wait()

    def stop(self, timeout: float = 30.0) -> bool:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout)
        # fail anything still queued: blocked waiters get an error, never
        # a hang (the executor-stop discipline)
        with self._cv:
            for q in self._queues.values():
                while q:
                    q.popleft().finish(
                        error=RuntimeError("coalescer stopped")
                    )
            self._pending = 0
        return not self._thread.is_alive()

    def stats(self) -> dict:
        with self._cv:
            return {
                "pending": self._pending,
                "groups_run": self.groups_run,
                "mega_groups": self.mega_groups,
                "service_s_ewma": round(self._service_s, 6),
                "served_share": dict(self._served),
                "depth": self.depth,
                "span_max": self.span_max,
                "mode": self.mode,
            }

    # -- DRF admission order -------------------------------------------------

    def _decay_served_locked(self) -> None:  # lock-held: _cv
        now = time.monotonic()
        dt = now - self._served_at
        if dt <= 0:
            return
        factor = 0.5 ** (dt / coalesce_fair_halflife())
        for t in list(self._served):
            v = self._served[t] * factor
            if v < 1e-6:
                del self._served[t]
            else:
                self._served[t] = v
        self._served_at = now

    def _tenant_order_locked(self) -> List[str]:  # lock-held: _cv
        """Tenants with pending work, lowest dominant share first: the
        observatory's cluster share (weights_fn) plus this queue's
        decayed serviced-work fraction; ties break toward the oldest
        waiting head job (FIFO aging)."""
        self._decay_served_locked()
        weights: Dict[str, float] = {}
        if self._weights_fn is not None:
            try:
                weights = dict(self._weights_fn() or {})
            except Exception:  # noqa: BLE001 — fairness hint, never fatal
                weights = {}
        total = sum(self._served.values()) or 1.0
        out = []
        for tenant, q in self._queues.items():
            if not q:
                continue
            share = (
                self._served.get(tenant, 0.0) / total
                + float(weights.get(tenant, 0.0))
            )
            out.append((share, q[0].enqueued, tenant))
        out.sort()
        return [t for _, _, t in out]

    def _select_group_locked(self) -> List[CoalesceJob]:  # lock-held: _cv
        """Pop up to ``span_max`` jobs, round-robin over tenants in DRF
        order (one job per tenant per pass) — the pop order IS the
        deterministic admission order the mega concatenation uses."""
        order = self._tenant_order_locked()
        group: List[CoalesceJob] = []
        while len(group) < self.span_max:
            took = False
            for tenant in order:
                q = self._queues.get(tenant)
                if not q:
                    continue
                job = q.popleft()
                self._pending -= 1
                group.append(job)
                # charge the serviced work (scan cells ~ gangs x nodes)
                # at ADMISSION: the next selection already sees it
                self._served[tenant] = (
                    self._served.get(tenant, 0.0)
                    + float(job.g * max(job.n, 1))
                )
                took = True
                if len(group) >= self.span_max:
                    break
            if not took:
                break
        self._depth_gauge.set(float(self._pending))
        return group

    # -- the worker ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait(0.5)
                if self._stopped:
                    return
                group = self._select_group_locked()
            if not group:
                continue
            t0 = time.perf_counter()
            try:
                self._run_group(group)
            except BaseException as e:  # noqa: BLE001 — deliver, never die
                for job in group:
                    if not job._done.is_set():
                        job.finish(error=e)
            dt = time.perf_counter() - t0
            with self._cv:
                self.groups_run += 1
                self._service_s = 0.7 * self._service_s + 0.3 * dt

    def _run_group(self, group: List[CoalesceJob]) -> None:
        mode = self.mode
        use_mega = (
            len(group) > 1
            and mode != "span"
            and len({job.r for job in group}) == 1
            # audited jobs pin the span lowering: the audit record pairs
            # the tenant's padded args with the result arrays, and a
            # mega demux's arrays are sliced to the tenant's real span —
            # an offline replay of the padded args would stamp
            # padded-shape arrays and plan_digest (which hashes shapes)
            # could never match. Span IS the dedicated dispatch, so its
            # record replays bit-identically by construction.
            and not any(job.want_audit for job in group)
            and (
                mode == "mega"
                or sum(j.g for j in group) * sum(j.n for j in group)
                <= self.mega_cells
            )
        )
        if use_mega:
            try:
                self._run_mega(group)
                self._note_group("mega", group)
                return
            except Exception:  # noqa: BLE001 — mega is an optimisation:
                # any failure (pad overflow, shape trouble) falls back to
                # the per-span dispatch, which IS the dedicated path
                remaining = [j for j in group if not j._done.is_set()]
                if not remaining:
                    # every job already finished before the failure: the
                    # group still merged at its full width
                    self._note_group("mega", group)
                    return
                group = remaining
        self._run_span(group)
        self._note_group("span", group)

    def _note_group(self, mode: str, group: List[CoalesceJob]) -> None:
        self._merged.inc(mode=mode)
        self._width.observe(float(max(len(group), 1)))
        if mode == "mega":
            with self._cv:
                self.mega_groups += 1

    # -- span lowering: per-span pipelined re-dispatch -----------------------

    def _run_span(self, group: List[CoalesceJob]) -> None:
        submitted = []
        for job in group:
            try:
                ej = self._executor.submit_batch(
                    job.padded_args, job.progress_args, donate=job.donate,
                    tenant=job.wire_tenant,
                )
            except BaseException as e:  # noqa: BLE001
                job.finish(error=e)
                continue
            submitted.append((job, ej))
        for job, ej in submitted:
            try:
                host, batch = ej.wait()
            except BaseException as e:  # noqa: BLE001
                job.finish(error=e)
                continue
            wait_s = time.perf_counter() - job.enqueued - ej.run_seconds
            self._wait.observe(max(wait_s, 0.0), tenant=job.tenant)
            host = dict(host)
            tel = dict(host.get("telemetry") or {})
            tel["coalesce"] = {
                "mode": "span", "width": len(group), "tenant": job.tenant,
                # explicit per-request admission-queue wait: the gang
                # lifecycle ledger's sidecar_wait phase attribution
                # (rides TRACE_INFO back to the client's timeline)
                "queue_wait_seconds": round(max(wait_s, 0.0), 6),
            }
            host["telemetry"] = tel
            job.finish(
                result=CoalesceResult(
                    host=host,
                    rows=_RowView(self._executor, batch, 0, 0, job.n),
                    queue_wait=max(wait_s, 0.0),
                    run_seconds=ej.run_seconds,
                    audit_args=job.audit_copy(),
                )
            )

    # -- mega lowering: block-diagonal mega-batch ----------------------------

    def _run_mega(self, group: List[CoalesceJob]) -> None:
        from ..ops.oracle import (
            batch_top_k,
            find_max_group_host,
            repack_assignment_span,
        )

        raws = [job.raw_fn() for job in group]
        batch_args, progress_args, noffs, goffs = build_mega_batch(raws)
        # attribute the merged device batch to its widest span's tenant
        dominant = max(group, key=lambda j: j.g * max(j.n, 1)).wire_tenant
        host, batch, queue_wait, run_s = self._executor.run_batch(
            batch_args, progress_args, tenant=dominant,
        )
        mega_tel = dict(host.get("telemetry") or {})
        feas = np.asarray(host["gang_feasible"])
        placed = np.asarray(host["placed"])
        progress = np.asarray(host["progress"])
        a_nodes = np.asarray(host["assignment_nodes"])
        a_counts = np.asarray(host["assignment_counts"])
        for i, job in enumerate(group):
            g, n = job.g, job.n
            gs, ns = goffs[i], noffs[i]
            # the tenant's dedicated run would size its compact readback
            # from ITS padded shapes — re-derive identically
            span_nb = int(np.asarray(job.padded_args[0]).shape[0])
            span_rem_max = int(
                np.asarray(job.padded_args[3]).max(initial=0)
            )
            k = batch_top_k(span_nb, span_rem_max)
            t_nodes = np.zeros((g, k), dtype=np.int32)
            t_counts = np.zeros((g, k), dtype=np.int32)
            for gi in range(g):
                t_nodes[gi], t_counts[gi] = repack_assignment_span(
                    a_nodes[gs + gi], a_counts[gs + gi], ns, span_nb, k
                )
            best, exists, _prog = find_max_group_host(*job.progress_args)
            wait_s = time.perf_counter() - job.enqueued - run_s
            tel = dict(mega_tel)
            tel["coalesce"] = {
                "mode": "mega", "width": len(group), "tenant": job.tenant,
                "node_offset": ns, "gang_offset": gs,
                # per-request admission-queue wait (lifecycle sidecar_wait
                # attribution, the span path's contract)
                "queue_wait_seconds": round(max(wait_s, 0.0), 6),
            }
            host_t = {
                "gang_feasible": feas[gs:gs + g],
                "placed": placed[gs:gs + g],
                "progress": progress[gs:gs + g],
                "best": best,
                "best_exists": exists,
                "assignment_nodes": t_nodes,
                "assignment_counts": t_counts,
                "telemetry": tel,
            }
            self._wait.observe(max(wait_s, 0.0), tenant=job.tenant)
            job.finish(
                result=CoalesceResult(
                    host=host_t,
                    rows=_RowView(self._executor, batch, gs, ns, n),
                    queue_wait=max(wait_s, 0.0),
                    run_seconds=run_s,
                    audit_args=job.audit_copy(),
                )
            )
