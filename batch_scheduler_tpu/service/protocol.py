"""Wire protocol for the oracle sidecar: framed packed arrays.

The north star calls for a data plane carrying packed pod/node resource
vectors from the control plane to a JAX sidecar (BASELINE.json north_star;
SURVEY.md §7 notes "packed arrays, not protobuf-per-pod" is required for the
<1s budget). The protocol is deliberately dumb and fast:

    frame  := magic "BSO2" | u32 msg_type | u64 payload_len | payload
    arrays := raw little-endian buffers in fixed order, counts up front

No per-pod messages, no schema negotiation, no string tables in the hot
path — names stay host-side in the caller's index maps. A C++ client
(native/) speaks the same bytes.

Message types:
  SCHEDULE_REQ  : one full oracle batch (counts + 7 arrays)
  SCHEDULE_RESP : O(G) vectors + compact top-K assignment
  ROW_REQ       : fetch one (G,N) row ("capacity" or "scores") from the
                  connection's last batch
  ROW_RESP      : the row, int32[N]
  PING/PONG     : liveness
  ERROR         : UTF-8 message
  DEADLINE      : u32 budget in ms, annotating the NEXT request on this
                  connection (no reply); the server answers that request
                  with DEADLINE_ERROR if its budget elapses first. A
                  separate annotation frame instead of a request-header
                  field so every existing layout (and the native C++
                  client, which never sends deadlines) stays bit-for-bit
                  unchanged. Ship client and server together: a pre-BSO2.1
                  server answers DEADLINE with an ERROR frame and desyncs.
  DEADLINE_ERROR: UTF-8 message — the annotated request's budget elapsed
                  server-side (the batch keeps running; its result is
                  dropped). Deliberately distinct from ERROR so clients
                  can tell "sidecar alive but slow" from a real failure.
  TRACE         : 16-hex trace ID + 8-hex parent span ID, annotating the
                  NEXT request on this connection (no reply; same
                  annotation-frame pattern as DEADLINE, so every
                  existing request/response layout — and the native C++
                  client, which never traces — stays bit-for-bit
                  unchanged). The server times the annotated request's
                  phases and answers a TRACE_INFO frame BEFORE the
                  normal response.
  AUDIT_ID      : 16-hex audit record ID, annotating the NEXT request on
                  this connection (no reply; the same annotation-frame
                  pattern as DEADLINE/TRACE, so every existing
                  request/response layout — and the native C++ client,
                  which never audits — stays bit-for-bit unchanged). The
                  server's own batch audit record (utils.audit) is
                  stamped with the client's ID, so the sidecar-side and
                  client-side records of one batch correlate into a
                  single evidence chain with the stitched trace spans
                  and flight-recorder decisions (docs/observability.md).
                  Sent only by auditing clients; a pre-audit server
                  answers it with an ERROR frame and desyncs — ship
                  client and server together, as with DEADLINE/TRACE.
  TRACE_INFO    : JSON {trace_id, spans: [...], telemetry: {...}} — the
                  server-side spans (stamped with the client's trace ID,
                  so both sides stitch into one Chrome-trace timeline)
                  plus per-batch oracle device telemetry: compile-cache
                  hit/miss, bucket shape, wave count/demotions, device
                  wall-clock (docs/observability.md). Sent ONLY to a
                  peer that sent TRACE, so pre-trace clients never see
                  it; as with DEADLINE, ship client and server together
                  (a pre-trace server answers TRACE with an ERROR frame
                  and desyncs).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Tuple

import numpy as np

__all__ = [
    "MAGIC",
    "MsgType",
    "ScheduleRequest",
    "ScheduleResponse",
    "DeltaScheduleRequest",
    "DELTA_KEYFRAME",
    "DELTA_ROWS",
    "pack_delta_keyframe",
    "pack_delta_rows",
    "unpack_delta_schedule_request",
    "pack_delta_resync",
    "unpack_delta_resync",
    "write_frame",
    "read_frame",
    "pack_schedule_request",
    "unpack_schedule_request",
    "pack_schedule_response",
    "unpack_schedule_response",
    "pack_row_request",
    "unpack_row_request",
    "pack_deadline",
    "unpack_deadline",
    "pack_trace",
    "unpack_trace",
    "pack_trace_info",
    "unpack_trace_info",
    "pack_audit_id",
    "unpack_audit_id",
    "pack_tenant",
    "unpack_tenant",
    "pack_busy",
    "unpack_busy",
    "pack_draining",
    "unpack_draining",
    "TENANT_LABEL_MAX_BYTES",
    "is_stale_batch_message",
]

# bumped BSO1 -> BSO2 when the request header grew mask_rows: the layout
# change would otherwise misparse silently between mismatched peers
MAGIC = b"BSO2"
_HEADER = struct.Struct("<4sIQ")

# A realistic max batch (8k-node/2k-group buckets) is tens of MB; anything
# near this bound is a desynced or hostile peer, not a bigger cluster.
MAX_FRAME_BYTES = 256 * 1024 * 1024


class MsgType:
    SCHEDULE_REQ = 1
    SCHEDULE_RESP = 2
    ROW_REQ = 3
    ROW_RESP = 4
    PING = 5
    PONG = 6
    ERROR = 7
    DEADLINE = 8
    DEADLINE_ERROR = 9
    TRACE = 10
    TRACE_INFO = 11
    AUDIT_ID = 12
    POLICY_INFO = 13
    # Device-resident state deltas (docs/pipelining.md "Device-resident
    # state"): a DELTA_SCHEDULE_REQ is a SCHEDULE_REQ whose big [N,R]/[G,R]
    # buffers are already resident in the server's per-connection device
    # mirror — the payload carries only churned rows + generations (or a
    # full keyframe installing/refreshing the mirror). Answered with a
    # normal SCHEDULE_RESP, or DELTA_RESYNC when the mirror cannot apply
    # it (no state, generation gap, shape mismatch) — the client then
    # resends a keyframe. Old servers answer MsgType 14 with an in-band
    # ERROR ("unknown message type"); the client detects that and falls
    # back to full SCHEDULE_REQ snapshots permanently — bit-identical
    # plans either way, so mixed fleets stay correct (the
    # AUDIT_ID/POLICY_INFO compatibility pattern: new frames are opt-in
    # and never change existing layouts).
    DELTA_SCHEDULE_REQ = 14
    DELTA_RESYNC = 15
    # Tenant identity annotation (docs/multitenancy.md): a cardinality-
    # capped tenant label (utils.tenancy — the client's dominant
    # namespace) annotating the NEXT request on this connection, the
    # AUDIT_ID/POLICY_INFO pattern: no reply, old peers never see it
    # (clients send it only when they have a tenant identity), every
    # existing request/response layout — and the native C++ client,
    # which never announces tenants — stays bit-for-bit unchanged. The
    # sidecar sees packed arrays, never names, so without this frame its
    # capacity summary and scan counters attribute everything to
    # "other"/"-"; with it, sidecar-side capacity/metrics attribute
    # truthfully and the coalescer's DRF admission order has a tenant
    # to be fair BETWEEN.
    TENANT = 16
    # Admission-control refusal (docs/multitenancy.md): the coalescer's
    # bounded merge queue is saturated — the request was NOT executed and
    # nothing server-side changed (a delta's mirror generation is
    # untouched). Carries a retry-after hint in ms; the resilient client
    # waits it out and retries (never a breaker failure — the sidecar is
    # alive and telling the client exactly when to come back, never a
    # silent hang). Sent only by a coalescing server, which only clients
    # shipping this PR's frames talk to — the DEADLINE ship-together rule.
    BUSY = 17
    # Graceful-drain refusal (docs/resilience.md "High availability"): the
    # server received SIGTERM (or /debug/drain) and is finishing its
    # in-flight window before exit — the request was NOT executed and
    # nothing server-side changed. Carries a retry-after hint in ms plus a
    # failover hint string (the standby address list when the operator
    # supplied one). A pooled client promotes its standby PROACTIVELY on
    # this answer — the transport worked, so it never advances the circuit
    # breaker (the BUSY discipline). Old servers answer MsgType 18 with an
    # in-band ERROR and old clients never see it (a draining old server
    # just closes) — the BUSY/AUDIT_ID compatibility pattern: existing
    # layouts stay bit-for-bit unchanged.
    DRAINING = 18


ROW_KINDS = ("capacity", "scores")


@dataclass
class ScheduleRequest:
    alloc: np.ndarray  # i32 [N,R]
    requested: np.ndarray  # i32 [N,R]
    group_req: np.ndarray  # i32 [G,R]
    remaining: np.ndarray  # i32 [G]
    fit_mask: np.ndarray  # bool [1,N] broadcast row or [G,N] per-group
    group_valid: np.ndarray  # bool [G]
    order: np.ndarray  # i32 [G]
    # max-progress selection inputs (reference findMaxPG semantics)
    min_member: np.ndarray  # i32 [G]
    scheduled: np.ndarray  # i32 [G]
    matched: np.ndarray  # i32 [G]
    ineligible: np.ndarray  # bool [G]
    creation_rank: np.ndarray  # i32 [G]


@dataclass
class ScheduleResponse:
    gang_feasible: np.ndarray  # bool [G]
    placed: np.ndarray  # bool [G]
    progress: np.ndarray  # i32 [G]
    best: int
    best_exists: bool
    assignment_nodes: np.ndarray  # i32 [G,K]
    assignment_counts: np.ndarray  # i32 [G,K]
    # per-connection batch token; row requests must present it so a stale
    # reader can never be served rows from a newer batch
    batch_seq: int = 0


def write_frame(sock, msg_type: int, payload: bytes) -> None:
    sock.sendall(_HEADER.pack(MAGIC, msg_type, len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> Tuple[int, bytes]:
    header = _recv_exact(sock, _HEADER.size)
    magic, msg_type, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic: {magic!r}")
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"oversized frame: {length}")
    return msg_type, _recv_exact(sock, length)


# -- schedule request ------------------------------------------------------

# N, G, R, MASK_ROWS — mask_rows is 1 (broadcast row, the no-selector fast
# path) or G (per-group [G,N] selector masks). Shipping the broadcast row
# as ONE row instead of expanding it to [G,N] at the encoder cuts the
# north-star request frame from ~8.8 MB to ~0.4 MB (the mask was 96% of
# the bytes for a workload with no selectors at all).
_REQ_COUNTS = struct.Struct("<IIII")


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype="<i4")


def _u8(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype=np.uint8)


def pack_schedule_request(req: ScheduleRequest) -> bytes:
    n, r = req.alloc.shape
    g = req.group_req.shape[0]
    mask = np.asarray(req.fit_mask)
    if mask.shape[0] not in (1, g):
        raise ValueError(
            f"fit_mask rows must be 1 or G={g}, got {mask.shape[0]}"
        )
    parts = [
        _REQ_COUNTS.pack(n, g, r, mask.shape[0]),
        _i32(req.alloc).tobytes(),
        _i32(req.requested).tobytes(),
        _i32(req.group_req).tobytes(),
        _i32(req.remaining).tobytes(),
        _u8(mask).tobytes(),
        _u8(req.group_valid).tobytes(),
        _i32(req.order).tobytes(),
        _i32(req.min_member).tobytes(),
        _i32(req.scheduled).tobytes(),
        _i32(req.matched).tobytes(),
        _u8(req.ineligible).tobytes(),
        _i32(req.creation_rank).tobytes(),
    ]
    return b"".join(parts)


def unpack_schedule_request(payload: bytes) -> ScheduleRequest:
    n, g, r, mask_rows = _REQ_COUNTS.unpack_from(payload, 0)
    if mask_rows not in (1, g):
        raise ValueError(f"fit_mask rows must be 1 or G={g}, got {mask_rows}")
    off = _REQ_COUNTS.size

    def take(count, dtype, shape):
        nonlocal off
        size = count * np.dtype(dtype).itemsize
        arr = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
        off += size
        return arr.reshape(shape)

    alloc = take(n * r, "<i4", (n, r))
    requested = take(n * r, "<i4", (n, r))
    group_req = take(g * r, "<i4", (g, r))
    remaining = take(g, "<i4", (g,))
    fit_mask = take(mask_rows * n, np.uint8, (mask_rows, n)).astype(bool)
    group_valid = take(g, np.uint8, (g,)).astype(bool)
    order = take(g, "<i4", (g,))
    min_member = take(g, "<i4", (g,))
    scheduled = take(g, "<i4", (g,))
    matched = take(g, "<i4", (g,))
    ineligible = take(g, np.uint8, (g,)).astype(bool)
    creation_rank = take(g, "<i4", (g,))
    if off != len(payload):
        raise ValueError(f"trailing bytes in schedule request: {len(payload) - off}")
    return ScheduleRequest(
        alloc, requested, group_req, remaining, fit_mask, group_valid, order,
        min_member, scheduled, matched, ineligible, creation_rank,
    )


# -- schedule response -----------------------------------------------------

_RESP_COUNTS = struct.Struct("<IIiBI")  # G, K, best, best_exists, batch_seq


def pack_schedule_response(resp: ScheduleResponse) -> bytes:
    g = resp.gang_feasible.shape[0]
    k = resp.assignment_nodes.shape[1]
    return b"".join(
        [
            _RESP_COUNTS.pack(g, k, resp.best, 1 if resp.best_exists else 0, resp.batch_seq),
            _u8(resp.gang_feasible).tobytes(),
            _u8(resp.placed).tobytes(),
            _i32(resp.progress).tobytes(),
            _i32(resp.assignment_nodes).tobytes(),
            _i32(resp.assignment_counts).tobytes(),
        ]
    )


def unpack_schedule_response(payload: bytes) -> ScheduleResponse:
    g, k, best, best_exists, batch_seq = _RESP_COUNTS.unpack_from(payload, 0)
    off = _RESP_COUNTS.size

    def take(count, dtype, shape):
        nonlocal off
        arr = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
        off += count * np.dtype(dtype).itemsize
        return arr.reshape(shape)

    return ScheduleResponse(
        gang_feasible=take(g, np.uint8, (g,)).astype(bool),
        placed=take(g, np.uint8, (g,)).astype(bool),
        progress=take(g, "<i4", (g,)),
        best=best,
        best_exists=bool(best_exists),
        assignment_nodes=take(g * k, "<i4", (g, k)),
        assignment_counts=take(g * k, "<i4", (g, k)),
        batch_seq=batch_seq,
    )


def is_stale_batch_message(message: str) -> bool:
    """True when an in-band server error means "this batch's rows no
    longer exist": an explicit stale-batch refusal, or a row request on a
    connection with no batch state yet (the same situation seen through a
    reconnect). Shared by the Python client and the native-client
    bindings so both transports map it to StaleBatchError — the one class
    the scorer's row reads may answer conservatively."""
    return "stale batch" in message or "before any batch" in message


# -- deadline annotation ---------------------------------------------------

_DEADLINE = struct.Struct("<I")


def pack_deadline(deadline_ms: int) -> bytes:
    if not 0 < deadline_ms <= 0xFFFFFFFF:
        raise ValueError(f"deadline_ms out of range: {deadline_ms}")
    return _DEADLINE.pack(deadline_ms)


def unpack_deadline(payload: bytes) -> int:
    return int(_DEADLINE.unpack(payload)[0])


# -- trace annotation + trace-info reply -----------------------------------

# fixed-width ascii: 16-hex trace id + 8-hex parent span id. Binary-fixed
# (not JSON) because the annotation rides the REQUEST hot path; the reply
# (TRACE_INFO) is JSON because it is only ever sent to a tracing client.
_TRACE = struct.Struct("<16s8s")


def pack_trace(trace_id: str, parent_span_id: str = "") -> bytes:
    tid = trace_id.encode("ascii")
    sid = parent_span_id.encode("ascii")
    if len(tid) != 16:
        raise ValueError(f"trace_id must be 16 hex chars, got {trace_id!r}")
    return _TRACE.pack(tid, sid[:8].ljust(8, b"\0"))


def unpack_trace(payload: bytes) -> Tuple[str, str]:
    tid, sid = _TRACE.unpack(payload)
    return (
        tid.decode("ascii", errors="replace"),
        sid.rstrip(b"\0").decode("ascii", errors="replace"),
    )


def pack_trace_info(trace_id: str, spans: list, telemetry: dict) -> bytes:
    import json

    return json.dumps(
        {"trace_id": trace_id, "spans": spans, "telemetry": telemetry},
        default=str,
    ).encode()


def unpack_trace_info(payload: bytes) -> dict:
    import json

    try:
        info = json.loads(payload.decode("utf-8", errors="replace"))
    except ValueError:
        return {}
    if not isinstance(info, dict):
        return {}
    return info


# -- audit-id annotation ----------------------------------------------------

# fixed-width ascii like the TRACE annotation: 16-hex audit record ID
# (utils.audit.new_audit_id) correlating the client's and the sidecar's
# audit records of one batch
_AUDIT = struct.Struct("<16s")


def pack_audit_id(audit_id: str) -> bytes:
    aid = audit_id.encode("ascii")
    if len(aid) != 16:
        raise ValueError(f"audit_id must be 16 hex chars, got {audit_id!r}")
    return _AUDIT.pack(aid)


def unpack_audit_id(payload: bytes) -> str:
    return _AUDIT.unpack(payload)[0].decode("ascii", errors="replace")


# -- policy fingerprint annotation ------------------------------------------

# fixed-width ascii like the AUDIT_ID annotation: the 16-hex policy-config
# fingerprint (policy.engine.PolicyConfig.fingerprint) of the CLIENT's
# active policy engine, annotating the next request on this connection.
# The sidecar executes base (policy-unaware) batches; a client running
# policies compares fingerprints so a mismatched peer is a counted,
# visible condition (bst_policy_fingerprint_mismatch_total) rather than a
# silent plan divergence. No reply; old peers that don't know MsgType 13
# never receive it (clients send it only when a policy engine is live).
_POLICY = struct.Struct("<16s")


def pack_policy_info(fingerprint: str) -> bytes:
    fp = fingerprint.encode("ascii")
    if len(fp) != 16:
        raise ValueError(
            f"policy fingerprint must be 16 hex chars, got {fingerprint!r}"
        )
    return _POLICY.pack(fp)


def unpack_policy_info(payload: bytes) -> str:
    return _POLICY.unpack(payload)[0].decode("ascii", errors="replace")


# -- tenant annotation + busy admission-control reply ------------------------

# Variable-length (tenant labels are namespaces, not fixed-width hex), but
# bounded: a label is already cardinality-capped client-side
# (utils.tenancy.tenant_label), and the byte cap here keeps a hostile
# peer from using the annotation as a memory lever.
TENANT_LABEL_MAX_BYTES = 64


def pack_tenant(label: str) -> bytes:
    raw = label.encode("utf-8")
    if not raw:
        raise ValueError("tenant label must be non-empty")
    if len(raw) > TENANT_LABEL_MAX_BYTES:
        # truncate, never raise: the label is attribution metadata — a
        # long namespace must degrade to a clipped label, not crash the
        # schedule path mid-stream (annotation frames already written).
        # Re-encode through a lossy decode so a codepoint split at the
        # byte cap drops cleanly instead of shipping a partial sequence.
        raw = (
            raw[:TENANT_LABEL_MAX_BYTES]
            .decode("utf-8", errors="ignore")
            .encode("utf-8")
        )
    return raw


def unpack_tenant(payload: bytes) -> str:
    return payload[:TENANT_LABEL_MAX_BYTES].decode("utf-8", errors="replace")


# retry-after hint in ms, then a UTF-8 message for operators/logs
_BUSY = struct.Struct("<I")


def pack_busy(retry_after_ms: int, message: str = "") -> bytes:
    if not 0 <= retry_after_ms <= 0xFFFFFFFF:
        raise ValueError(f"retry_after_ms out of range: {retry_after_ms}")
    return _BUSY.pack(retry_after_ms) + message.encode()


def unpack_busy(payload: bytes) -> Tuple[int, str]:
    (retry_after_ms,) = _BUSY.unpack_from(payload, 0)
    return int(retry_after_ms), payload[_BUSY.size:].decode(errors="replace")


# DRAINING shares BUSY's layout: retry-after hint in ms, then a UTF-8
# failover hint (the standby address list, comma-separated, when the
# operator supplied one — empty otherwise).


def pack_draining(retry_after_ms: int, failover_hint: str = "") -> bytes:
    if not 0 <= retry_after_ms <= 0xFFFFFFFF:
        raise ValueError(f"retry_after_ms out of range: {retry_after_ms}")
    return _BUSY.pack(retry_after_ms) + failover_hint.encode()


def unpack_draining(payload: bytes) -> Tuple[int, str]:
    (retry_after_ms,) = _BUSY.unpack_from(payload, 0)
    return int(retry_after_ms), payload[_BUSY.size:].decode(errors="replace")


# -- device-resident state deltas -------------------------------------------

# kind, base_generation, new_generation. base_generation is the mirror
# generation this delta applies ON TOP OF (ignored for keyframes); the
# server refuses any mismatch with DELTA_RESYNC — a dropped or duplicated
# delta frame must force a keyframe resync, never silently score stale rows.
_DELTA_HEADER = struct.Struct("<BQQ")
DELTA_KEYFRAME = 1
DELTA_ROWS = 2

# counts of a rows-delta body: n, g, r, mask_rows (the padded request
# space, same convention as the full request), churned node rows, churned
# group rows
_DELTA_COUNTS = struct.Struct("<IIIIII")


@dataclass
class DeltaScheduleRequest:
    """Churned-row refresh of a connection's device-resident mirror: the
    [N,R] requested / [G,R] group-demand rows that changed since the
    mirror's generation, plus the full (tiny) O(G) tail — which is
    refresh-fresh by definition. ``alloc`` is never delta'd: alloc churn
    full-repacks host-side (the lane shifts may move), which forces a
    keyframe."""

    node_idx: np.ndarray  # i32 [Mn] churned requested-row indices
    node_rows: np.ndarray  # i32 [Mn, R]
    group_idx: np.ndarray  # i32 [Mg] churned group-demand row indices
    group_rows: np.ndarray  # i32 [Mg, R]
    remaining: np.ndarray  # i32 [G]
    fit_mask: np.ndarray  # bool [mask_rows, N]
    group_valid: np.ndarray  # bool [G]
    order: np.ndarray  # i32 [G]
    min_member: np.ndarray  # i32 [G]
    scheduled: np.ndarray  # i32 [G]
    matched: np.ndarray  # i32 [G]
    ineligible: np.ndarray  # bool [G]
    creation_rank: np.ndarray  # i32 [G]
    n: int = 0
    g: int = 0
    r: int = 0


def pack_delta_keyframe(new_generation: int, req: ScheduleRequest) -> bytes:
    """A full snapshot that (re)installs the server's mirror at
    ``new_generation`` — byte-wise the keyframe body IS a schedule
    request, so the two paths can never drift."""
    return _DELTA_HEADER.pack(
        DELTA_KEYFRAME, 0, new_generation
    ) + pack_schedule_request(req)


def pack_delta_rows(
    base_generation: int, new_generation: int, d: DeltaScheduleRequest
) -> bytes:
    node_idx = _i32(d.node_idx)
    group_idx = _i32(d.group_idx)
    parts = [
        _DELTA_HEADER.pack(DELTA_ROWS, base_generation, new_generation),
        _DELTA_COUNTS.pack(
            d.n, d.g, d.r, np.asarray(d.fit_mask).shape[0],
            node_idx.shape[0], group_idx.shape[0],
        ),
        node_idx.tobytes(),
        _i32(d.node_rows).tobytes(),
        group_idx.tobytes(),
        _i32(d.group_rows).tobytes(),
        _i32(d.remaining).tobytes(),
        _u8(d.fit_mask).tobytes(),
        _u8(d.group_valid).tobytes(),
        _i32(d.order).tobytes(),
        _i32(d.min_member).tobytes(),
        _i32(d.scheduled).tobytes(),
        _i32(d.matched).tobytes(),
        _u8(d.ineligible).tobytes(),
        _i32(d.creation_rank).tobytes(),
    ]
    return b"".join(parts)


def unpack_delta_schedule_request(payload: bytes):
    """Returns ``(kind, base_generation, new_generation, body)`` where
    ``body`` is a ScheduleRequest (keyframe) or DeltaScheduleRequest."""
    kind, base_gen, new_gen = _DELTA_HEADER.unpack_from(payload, 0)
    rest = payload[_DELTA_HEADER.size:]
    if kind == DELTA_KEYFRAME:
        return kind, base_gen, new_gen, unpack_schedule_request(rest)
    if kind != DELTA_ROWS:
        raise ValueError(f"unknown delta kind {kind}")
    n, g, r, mask_rows, m_nodes, m_groups = _DELTA_COUNTS.unpack_from(rest, 0)
    if mask_rows not in (1, g):
        raise ValueError(f"fit_mask rows must be 1 or G={g}, got {mask_rows}")
    off = _DELTA_COUNTS.size

    def take(count, dtype, shape):
        nonlocal off
        arr = np.frombuffer(rest, dtype=dtype, count=count, offset=off)
        off += count * np.dtype(dtype).itemsize
        return arr.reshape(shape)

    d = DeltaScheduleRequest(
        node_idx=take(m_nodes, "<i4", (m_nodes,)),
        node_rows=take(m_nodes * r, "<i4", (m_nodes, r)),
        group_idx=take(m_groups, "<i4", (m_groups,)),
        group_rows=take(m_groups * r, "<i4", (m_groups, r)),
        remaining=take(g, "<i4", (g,)),
        fit_mask=take(mask_rows * n, np.uint8, (mask_rows, n)).astype(bool),
        group_valid=take(g, np.uint8, (g,)).astype(bool),
        order=take(g, "<i4", (g,)),
        min_member=take(g, "<i4", (g,)),
        scheduled=take(g, "<i4", (g,)),
        matched=take(g, "<i4", (g,)),
        ineligible=take(g, np.uint8, (g,)).astype(bool),
        creation_rank=take(g, "<i4", (g,)),
        n=n,
        g=g,
        r=r,
    )
    if off != len(rest):
        raise ValueError(
            f"trailing bytes in delta schedule request: {len(rest) - off}"
        )
    return kind, base_gen, new_gen, d


def pack_delta_resync(reason: str) -> bytes:
    return reason.encode()


def unpack_delta_resync(payload: bytes) -> str:
    return payload.decode(errors="replace")


# -- row request/response --------------------------------------------------

_ROW_REQ = struct.Struct("<BII")  # kind index, group index, batch_seq


def pack_row_request(kind: str, group_index: int, batch_seq: int = 0) -> bytes:
    return _ROW_REQ.pack(ROW_KINDS.index(kind), group_index, batch_seq)


def unpack_row_request(payload: bytes) -> Tuple[str, int, int]:
    kind_idx, group_index, batch_seq = _ROW_REQ.unpack(payload)
    return ROW_KINDS[kind_idx], group_index, batch_seq
