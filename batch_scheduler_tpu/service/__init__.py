from . import protocol
from .client import OracleClient, RemoteScorer
from .server import OracleServer, serve_background

__all__ = [
    "protocol",
    "OracleClient",
    "RemoteScorer",
    "OracleServer",
    "serve_background",
]
