from . import protocol
from .client import OracleClient, RemoteScorer, ResilientOracleClient
from .server import OracleServer, serve_background

__all__ = [
    "protocol",
    "OracleClient",
    "ResilientOracleClient",
    "RemoteScorer",
    "OracleServer",
    "serve_background",
]
