"""ctypes bindings for the native (C++) sidecar client in native/.

The C++ library is the embeddable data-plane client (Go via cgo, C++
directly); these bindings exist so the Python test suite exercises the SAME
native code path end-to-end against the Python server — wire compatibility
is proven, not assumed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from . import protocol as proto

__all__ = ["NATIVE_DIR", "ensure_built", "NativeOracleClient"]

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(NATIVE_DIR, "libbsp_client.so")


def ensure_built() -> Optional[str]:
    """Build the native library if needed; returns its path or None if no
    toolchain is available."""
    if os.path.exists(_LIB_PATH):
        src = os.path.join(NATIVE_DIR, "bsp_client.cpp")
        if os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src):
            return _LIB_PATH
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR, "libbsp_client.so"],
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        return None
    return _LIB_PATH if os.path.exists(_LIB_PATH) else None


def _load():
    lib = ctypes.CDLL(_LIB_PATH)
    lib.bsp_connect.restype = ctypes.c_void_p
    lib.bsp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bsp_close.argtypes = [ctypes.c_void_p]
    lib.bsp_ping.argtypes = [ctypes.c_void_p]
    lib.bsp_last_error.restype = ctypes.c_char_p
    lib.bsp_last_error.argtypes = [ctypes.c_void_p]
    i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    lib.bsp_schedule.argtypes = (
        [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,  # mask_rows
        ]
        + [i32p] * 4
        + [u8p, u8p]
        + [i32p] * 4
        + [u8p, i32p]
        + [u8p, u8p, i32p, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8)]
        + [i32p, i32p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
           ctypes.POINTER(ctypes.c_uint32)]
    )
    lib.bsp_row.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_int32,
        ctypes.c_uint32,
        i32p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    return lib


class NativeOracleClient:
    """Same surface as service.client.OracleClient, through the C++ lib."""

    def __init__(self, host: str, port: int):
        if ensure_built() is None:
            raise RuntimeError("native client library unavailable (no toolchain)")
        self._lib = _load()
        self._handle = self._lib.bsp_connect(host.encode(), port)
        if not self._handle:
            raise ConnectionError(f"bsp_connect to {host}:{port} failed")

    def close(self) -> None:
        if self._handle:
            self._lib.bsp_close(self._handle)
            self._handle = None

    def _error(self) -> str:
        return self._lib.bsp_last_error(self._handle).decode(errors="replace")

    def _raise_op_error(self, op: str) -> None:
        """Classify a failed native call like the Python client does:
        stale-batch answers become StaleBatchError so the scorer's row
        reads stay conservative through the C++ transport too, instead of
        a RuntimeError killing the scheduling cycle. (The native client
        does not send DEADLINE frames; deadline propagation is a
        ResilientOracleClient feature.)"""
        from ..utils.errors import StaleBatchError

        message = self._error()
        if proto.is_stale_batch_message(message):
            raise StaleBatchError(message)
        raise RuntimeError(f"{op} failed: {message}")

    def ping(self) -> bool:
        return self._lib.bsp_ping(self._handle) == 0

    def schedule(self, req: proto.ScheduleRequest) -> proto.ScheduleResponse:
        n, r = req.alloc.shape
        g = req.group_req.shape[0]
        k_cap = 128

        def i32(a):
            return np.ascontiguousarray(a, dtype=np.int32)

        def u8(a):
            return np.ascontiguousarray(a, dtype=np.uint8)

        gang_feasible = np.zeros(g, np.uint8)
        placed = np.zeros(g, np.uint8)
        progress = np.zeros(g, np.int32)
        assignment_nodes = np.zeros((g, k_cap), np.int32)
        assignment_counts = np.zeros((g, k_cap), np.int32)
        best = ctypes.c_int32(0)
        best_exists = ctypes.c_uint8(0)
        k_out = ctypes.c_int32(0)
        batch_seq = ctypes.c_uint32(0)

        mask = u8(req.fit_mask)
        rc = self._lib.bsp_schedule(
            self._handle, n, g, r, mask.shape[0],
            i32(req.alloc), i32(req.requested), i32(req.group_req),
            i32(req.remaining), mask, u8(req.group_valid),
            i32(req.order), i32(req.min_member), i32(req.scheduled),
            i32(req.matched), u8(req.ineligible), i32(req.creation_rank),
            gang_feasible, placed, progress,
            ctypes.byref(best), ctypes.byref(best_exists),
            assignment_nodes.reshape(-1), assignment_counts.reshape(-1),
            ctypes.byref(k_out), k_cap, ctypes.byref(batch_seq),
        )
        if rc != 0:
            self._raise_op_error("bsp_schedule")
        k = int(k_out.value)
        return proto.ScheduleResponse(
            gang_feasible=gang_feasible.astype(bool),
            placed=placed.astype(bool),
            progress=progress,
            best=int(best.value),
            best_exists=bool(best_exists.value),
            assignment_nodes=assignment_nodes.reshape(-1)[: g * k].reshape(g, k),
            assignment_counts=assignment_counts.reshape(-1)[: g * k].reshape(g, k),
            batch_seq=int(batch_seq.value),
        )

    def row(self, kind: str, group_index: int, batch_seq: int = 0) -> np.ndarray:
        out = np.zeros(1 << 16, np.int32)
        n_out = ctypes.c_int32(0)
        rc = self._lib.bsp_row(
            self._handle,
            proto.ROW_KINDS.index(kind),
            group_index,
            batch_seq,
            out,
            out.shape[0],
            ctypes.byref(n_out),
        )
        if rc != 0:
            self._raise_op_error("bsp_row")
        return out[: int(n_out.value)].copy()