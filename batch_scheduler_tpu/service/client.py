"""Oracle sidecar clients.

``OracleClient`` is the raw protocol client (one TCP connection, serialized
round-trips, no recovery). ``ResilientOracleClient`` is the production
transport: same surface, plus automatic reconnect, bounded retries with
exponential backoff + decorrelated jitter (utils.retry.RetryPolicy),
per-request deadline propagation, a circuit breaker that fails fast during
an outage and re-closes through a half-open ping probe, and — with a
multi-address pool — warm-standby failover: promotion on a DRAINING answer
(proactive, never a breaker failure) or on breaker-open (crash), with
delta mirrors re-keyframing on the new primary through the ordinary
DELTA_RESYNC machinery (docs/resilience.md "High availability").
``RemoteScorer`` plugs either into ScheduleOperation with the same
interface as the in-process OracleScorer — the control plane is agnostic to
whether the oracle lives in-process on the local chip or behind the sidecar
(the deployment split of the north star: Go plugin <-> JAX sidecar).
"""

from __future__ import annotations

import socket
import threading
import time
import weakref
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from ..core.oracle_scorer import OracleScorer, conservative_cpu_batch
from ..ops.snapshot import ClusterSnapshot
from ..utils.errors import (
    CircuitOpenError,
    DeltaResyncRequired,
    OracleBusyError,
    OracleDeadlineError,
    OracleDrainingError,
    OracleTransportError,
    StaleBatchError,
)
from ..utils.metrics import DEFAULT_REGISTRY, LONG_OP_BUCKETS, Registry
from ..utils.retry import CircuitBreaker, RetryPolicy
from ..utils import trace as trace_mod
from . import protocol as proto

__all__ = [
    "OracleClient",
    "ResilientOracleClient",
    "RemoteScorer",
    "parse_oracle_addresses",
    "active_failover_report",
]


def parse_oracle_addresses(
    spec: str, default_host: str = "127.0.0.1"
) -> List[Tuple[str, int]]:
    """``host:port[,host:port...]`` -> ``[(host, port), ...]`` — the
    ``--oracle-addr`` list form. Each entry may omit the host
    (``:9090`` / ``9090``), which defaults like the single-address CLI
    parse always has. Raises ValueError on an empty or unparsable spec."""
    addresses = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        addresses.append((host or default_host, int(port)))
    if not addresses:
        raise ValueError(f"no oracle addresses in {spec!r}")
    return addresses


def in_band_error(message: str) -> Exception:
    """Classify an in-band ERROR frame's message: stale-batch answers
    (protocol.is_stale_batch_message — including the post-reconnect
    "before any batch" form) map to StaleBatchError, the one class the
    scorer's row reads answer conservatively; everything else is a plain
    server error. Neither is a transport failure."""
    if proto.is_stale_batch_message(message):
        return StaleBatchError(message)
    return RuntimeError(f"oracle server error: {message}")


class OracleClient:
    # default generous enough to sit through a first TPU jit compile of a
    # new bucket shape (~20-40s) plus the batch itself
    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 120.0,
        connect_timeout: Optional[float] = None,
    ):
        self._timeout = timeout
        # one in-flight round-trip per connection: every frame write/read
        # holds _lock so annotation frames and their response can never
        # interleave with another thread's request on the same stream
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout or timeout
        )  # guarded-by: _lock
        self._sock.settimeout(timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            # analysis: allow(guarded-by) close() is the cancellation path: it must sever the socket while a stuck round-trip still HOLDS _lock
            self._sock.close()
        except OSError:
            pass

    def _round_trip(
        self,
        msg_type: int,
        payload: bytes,
        deadline_ms: Optional[int] = None,
        trace_ctx: Optional[Tuple[str, str]] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[int, bytes]:
        with self._lock:
            if deadline_ms is not None:
                # bound the wait to ~2x the announced budget: the server
                # answers a DEADLINE_ERROR within the deadline itself, so
                # anything past 2x is a transport stall, not a slow batch
                self._sock.settimeout(
                    min(self._timeout, deadline_ms / 1000.0 * 2.0 + 0.25)
                )
            try:
                if deadline_ms is not None:
                    proto.write_frame(
                        self._sock,
                        proto.MsgType.DEADLINE,
                        proto.pack_deadline(deadline_ms),
                    )
                if trace_ctx is not None:
                    proto.write_frame(
                        self._sock,
                        proto.MsgType.TRACE,
                        proto.pack_trace(*trace_ctx),
                    )
                if audit_id is not None:
                    # audit correlation (utils.audit): the sidecar stamps
                    # its own record of this batch with the client's ID
                    proto.write_frame(
                        self._sock,
                        proto.MsgType.AUDIT_ID,
                        proto.pack_audit_id(audit_id),
                    )
                if policy_fp is not None:
                    # policy skew detection (docs/policy.md "Wire"): the
                    # client's policy fingerprint rides ahead so a
                    # policy-unaware sidecar counts the mismatch
                    proto.write_frame(
                        self._sock,
                        proto.MsgType.POLICY_INFO,
                        proto.pack_policy_info(policy_fp),
                    )
                if tenant:
                    # tenant identity (docs/multitenancy.md): the
                    # sidecar's capacity/scan attribution and the
                    # coalescer's DRF fairness key off this label; None
                    # keeps the wire bytes identical to a pre-tenant
                    # client
                    proto.write_frame(
                        self._sock,
                        proto.MsgType.TENANT,
                        proto.pack_tenant(tenant),
                    )
                proto.write_frame(self._sock, msg_type, payload)
                try:
                    resp_type, resp = proto.read_frame(self._sock)
                    # A traced request's real response is preceded by the
                    # server's TRACE_INFO frame: fold its spans into the
                    # local ring (stitching both sides of the wire under
                    # one trace ID) and its device telemetry into the
                    # registry, then keep reading for the actual answer.
                    while resp_type == proto.MsgType.TRACE_INFO:
                        self._absorb_trace_info(resp)
                        resp_type, resp = proto.read_frame(self._sock)
                except ValueError as e:
                    # bad magic / oversized length: the STREAM is broken,
                    # not the request — classify as transport here so a
                    # client-side packing ValueError (a programming error,
                    # raised before any bytes move) stays distinguishable
                    raise OracleTransportError(f"desynced stream: {e}") from e
            finally:
                if deadline_ms is not None:
                    self._sock.settimeout(self._timeout)
        if resp_type == proto.MsgType.DEADLINE_ERROR:
            raise OracleDeadlineError(resp.decode(errors="replace"))
        if resp_type == proto.MsgType.BUSY:
            retry_ms, message = proto.unpack_busy(resp)
            raise OracleBusyError(
                message or "oracle coalescer saturated", retry_ms
            )
        if resp_type == proto.MsgType.DRAINING:
            retry_ms, hint = proto.unpack_draining(resp)
            raise OracleDrainingError(
                "oracle draining"
                + (f" (failover hint: {hint})" if hint else ""),
                retry_ms,
                failover_hint=hint,
            )
        if resp_type == proto.MsgType.ERROR:
            raise in_band_error(resp.decode(errors="replace"))
        return resp_type, resp

    # last TRACE_INFO telemetry absorbed off the wire (oracle device
    # telemetry: compile-cache hit, bucket shape, wave stats, device
    # wall-clock) — kept for callers/tests; metrics fold as it lands
    last_telemetry: Optional[dict] = None

    def _absorb_trace_info(self, payload: bytes) -> None:
        info = proto.unpack_trace_info(payload)
        spans = info.get("spans")
        if isinstance(spans, list):
            trace_mod.record_remote_spans(spans, pid="oracle-server")
        telemetry = info.get("telemetry")
        if isinstance(telemetry, dict):
            self.last_telemetry = telemetry
            device_s = telemetry.get("device_seconds")
            if isinstance(device_s, (int, float)):
                DEFAULT_REGISTRY.histogram(
                    "bst_oracle_device_seconds",
                    "Sidecar-reported device wall-clock per traced batch",
                    buckets=LONG_OP_BUCKETS,
                ).observe(float(device_s))
            if telemetry.get("compiled"):
                DEFAULT_REGISTRY.counter(
                    "bst_oracle_remote_compiles_total",
                    "Traced sidecar batches that built a new executable",
                ).inc()

    def ping(self, deadline_ms: Optional[int] = None) -> bool:
        # a deadline here mostly buys the tightened client-side socket
        # timeout (the server answers pings inline, ignoring the budget):
        # the breaker's half-open probe must stay bounded against a
        # hung-but-accepting sidecar
        resp_type, _ = self._round_trip(
            proto.MsgType.PING, b"", deadline_ms=deadline_ms
        )
        return resp_type == proto.MsgType.PONG

    def schedule(
        self,
        req: proto.ScheduleRequest,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> proto.ScheduleResponse:
        # propagate the live span context over the wire (the TRACE
        # annotation frame); None when tracing is off or no span is open,
        # which keeps the wire bytes identical to a pre-trace client
        trace_ctx = trace_mod.current_context() if trace_mod.enabled() else None
        # last_telemetry is per-request: cleared up front so an untraced
        # (sampled-out) batch can never be attributed the PREVIOUS traced
        # batch's device evidence
        self.last_telemetry = None
        resp_type, resp = self._round_trip(
            proto.MsgType.SCHEDULE_REQ,
            proto.pack_schedule_request(req),
            deadline_ms=deadline_ms,
            trace_ctx=trace_ctx,
            audit_id=audit_id,
            policy_fp=policy_fp,
            tenant=tenant,
        )
        if resp_type != proto.MsgType.SCHEDULE_RESP:
            raise OracleTransportError(
                f"unexpected response type {resp_type} (desynced stream)"
            )
        try:
            return proto.unpack_schedule_response(resp)
        except ValueError as e:  # truncated/garbled payload: stream damage
            raise OracleTransportError(f"undecodable response: {e}") from e

    def delta_schedule(
        self,
        kind: int,
        base_generation: int,
        new_generation: int,
        body,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> proto.ScheduleResponse:
        """One device-resident-state batch (docs/pipelining.md
        "Device-resident state"): ``body`` is a full ScheduleRequest when
        ``kind`` is DELTA_KEYFRAME (installs/refreshes the server's
        per-connection mirror at ``new_generation``) or a
        DeltaScheduleRequest of churned rows on top of
        ``base_generation``. A DELTA_RESYNC answer raises
        DeltaResyncRequired — in-band, never retried: the caller resends
        a keyframe."""
        trace_ctx = trace_mod.current_context() if trace_mod.enabled() else None
        self.last_telemetry = None
        if kind == proto.DELTA_KEYFRAME:
            payload = proto.pack_delta_keyframe(new_generation, body)
        else:
            payload = proto.pack_delta_rows(
                base_generation, new_generation, body
            )
        resp_type, resp = self._round_trip(
            proto.MsgType.DELTA_SCHEDULE_REQ,
            payload,
            deadline_ms=deadline_ms,
            trace_ctx=trace_ctx,
            audit_id=audit_id,
            policy_fp=policy_fp,
            tenant=tenant,
        )
        if resp_type == proto.MsgType.DELTA_RESYNC:
            raise DeltaResyncRequired(proto.unpack_delta_resync(resp))
        if resp_type != proto.MsgType.SCHEDULE_RESP:
            raise OracleTransportError(
                f"unexpected response type {resp_type} (desynced stream)"
            )
        try:
            return proto.unpack_schedule_response(resp)
        except ValueError as e:  # truncated/garbled payload: stream damage
            raise OracleTransportError(f"undecodable response: {e}") from e

    def row(
        self,
        kind: str,
        group_index: int,
        batch_seq: int = 0,
        deadline_ms: Optional[int] = None,
    ) -> np.ndarray:
        resp_type, resp = self._round_trip(
            proto.MsgType.ROW_REQ,
            proto.pack_row_request(kind, group_index, batch_seq),
            deadline_ms=deadline_ms,
        )
        if resp_type != proto.MsgType.ROW_RESP:
            raise OracleTransportError(
                f"unexpected response type {resp_type} (desynced stream)"
            )
        try:
            return np.frombuffer(resp, dtype="<i4")
        except ValueError as e:  # payload not a whole int32 row: desync
            raise OracleTransportError(f"undecodable row: {e}") from e


# what counts as a TRANSPORT failure (retried, advances the breaker):
# socket errors incl. timeouts (OSError covers ConnectionError), EOF, and
# OracleTransportError (which OracleClient raises for frame-level desync:
# bad magic, oversized length, undecodable response). Deliberately NOT
# ValueError: a request-packing ValueError is a client-side programming
# error raised before any bytes move — retrying it against a healthy
# sidecar (and degrading to the CPU fallback) would mask the bug as an
# outage. In-band answers (StaleBatchError, OracleDeadlineError, plain
# RuntimeError) rode a WORKING transport and are excluded by catch order.
_TRANSPORT_ERRORS = (OSError, EOFError, OracleTransportError)

_BREAKER_STATE_VALUES = {"closed": 0, "open": 1, "half-open": 2}

# live multi-address clients, for the /debug/health ``failover`` signal
# (utils.health reads active_failover_report() through a lazy import, the
# same pattern as ops.capacity.active_sampler)
_POOLED_CLIENTS: "weakref.WeakSet" = weakref.WeakSet()


def active_failover_report() -> dict:
    """Pool state of every live multi-address ResilientOracleClient:
    active address, standby freshness (seconds since a standby last
    answered — None until one has), per-backend breaker states, and the
    recent promotion history with reasons. Best-effort and lock-light;
    health snapshots must never block a scheduling cycle."""
    now = time.time()
    mono = time.monotonic()
    clients = []
    for c in list(_POOLED_CLIENTS):
        try:
            with c._pool_lock:
                active = c._active
                promotions = list(c._promotions)
            addrs = [f"{h}:{p}" for h, p in c._addresses]
            last_ok = list(c._backend_last_ok)
            standby_ages = [
                mono - t
                for i, t in enumerate(last_ok)
                if i != active and t > 0.0
            ]
            clients.append({
                "client": c._label,
                "active": active,
                "active_addr": addrs[active],
                "addresses": addrs,
                "standby_freshness_s": (
                    round(min(standby_ages), 3) if standby_ages else None
                ),
                "promotions": [
                    {"ago_s": round(now - ts, 3), "reason": r, "to": to}
                    for ts, r, to in promotions
                ],
                "breakers": {
                    addrs[i]: b.state for i, b in enumerate(c._breakers)
                },
            })
        except Exception:  # noqa: BLE001 — a dying client must not
            continue  # poison the health snapshot
    return {"clients": clients}


class _ClientSlot:
    """One in-flight lane of a windowed ResilientOracleClient: the same
    retry/breaker/deadline policy, its own connection and lock, so a
    dispatch-ahead speculative batch on one lane never contends with row
    reads on the batch the other lane executed. RemoteScorer pins each
    batch's row fetcher to the slot that ran it (the server keeps batch
    state per connection)."""

    __slots__ = ("_parent", "_idx")

    def __init__(self, parent: "ResilientOracleClient", idx: int):
        self._parent = parent
        self._idx = idx

    def ping(self, deadline_ms: Optional[int] = None) -> bool:
        return self._parent.ping(deadline_ms, _slot=self._idx)

    def schedule(
        self,
        req: proto.ScheduleRequest,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> proto.ScheduleResponse:
        return self._parent.schedule(
            req, deadline_ms, audit_id=audit_id, policy_fp=policy_fp,
            tenant=tenant, _slot=self._idx,
        )

    def delta_schedule(
        self,
        kind: int,
        base_generation: int,
        new_generation: int,
        body,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> proto.ScheduleResponse:
        return self._parent.delta_schedule(
            kind, base_generation, new_generation, body, deadline_ms,
            audit_id=audit_id, policy_fp=policy_fp, tenant=tenant,
            _slot=self._idx,
        )

    def row(
        self,
        kind: str,
        group_index: int,
        batch_seq: int = 0,
        deadline_ms: Optional[int] = None,
    ) -> np.ndarray:
        return self._parent.row(
            kind, group_index, batch_seq, deadline_ms, _slot=self._idx
        )

    def would_attempt(self) -> bool:
        return self._parent.would_attempt()

    @property
    def last_telemetry(self) -> Optional[dict]:
        return self._parent.slot_telemetry(self._idx)

    def close(self) -> None:
        self._parent.close_slot(self._idx)


class ResilientOracleClient:
    """OracleClient with reconnect, retry, deadline, and circuit breaker.

    Same call surface as OracleClient (ping/schedule/row/close), so
    RemoteScorer takes either. The connection is lazy: constructed on
    first use and re-established after any transport failure. Per
    request: the breaker gates admission (open => CircuitOpenError
    without touching the socket; half-open => one ping() probe decides),
    then up to ``retry_policy.max_attempts`` attempts run with
    full-jitter backoff, reconnecting between attempts. Semantic answers
    — StaleBatchError, in-band server errors, OracleDeadlineError — are
    never retried and never advance the breaker.

    ``window`` > 1 provisions that many independent connection SLOTS
    (lazily dialed, shared breaker/retry policy, per-slot locks) exposed
    via ``slot(i)`` — the in-flight window of the dispatch-ahead path: a
    speculative batch runs on one slot while the served batch's row reads
    proceed on another, with each batch pinned to the slot (and so the
    server-side connection) that executed it. The default window of 1 is
    exactly the old single-connection behavior.

    ``host`` may be a comma-separated ADDRESS POOL (``"h1:p1,h2:p2"``,
    the ``--oracle-addr`` list form; ``port`` is then ignored): the first
    address is the primary, the rest warm standbys. Each backend gets its
    OWN breaker (an outage of the primary must not poison the standby's
    admission state); every slot always dials the pool's single ACTIVE
    backend and lazily re-dials after a promotion. Promotion happens on a
    DRAINING answer (proactive — the primary said it will not serve
    again; never a breaker failure) or when the active backend's breaker
    opens (crash). Server-side per-connection state (delta mirrors, batch
    rows) dies with the old connections by design: the standby answers
    DELTA_RESYNC / in-band stale, and the existing keyframe + stale-batch
    discipline re-converges (docs/resilience.md "High availability").

    Observability (registry, default the process registry):
    bst_oracle_retries_total, bst_oracle_transport_failures_total,
    bst_oracle_reconnects_total, bst_oracle_deadline_errors_total,
    bst_oracle_failover_total (counters), bst_oracle_breaker_state
    (gauge; 0=closed 1=open 2=half-open; pooled backends are labelled
    ``label@host:port``) and bst_oracle_active_backend (gauge; pool
    index), labelled by ``client`` (``name`` or the address spec).
    """

    def __init__(
        self,
        host: str,
        port: Optional[int] = None,
        timeout: float = 120.0,
        connect_timeout: float = 5.0,
        retry_policy: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        deadline_ms: Optional[int] = None,
        name: Optional[str] = None,
        registry: Optional[Registry] = None,
        window: int = 1,
    ):
        if port is None or "," in host or ":" in host:
            # address-spec form ("h1:p1,h2:p2", ":9090", "9090"): the
            # CLI's --oracle-addr string, port arg ignored
            self._addresses = parse_oracle_addresses(host)
        else:
            self._addresses = [(host, int(port))]
        self._active = 0
        self._pool_lock = threading.Lock()
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        self.retry_policy = retry_policy or RetryPolicy()
        self.deadline_ms = self._check_deadline(deadline_ms)
        self.window = max(1, int(window))
        self._slot_clients: list = [None] * self.window
        self._slot_connected: list = [False] * self.window
        self._slot_addr = [0] * self.window
        self._slot_locks = [threading.RLock() for _ in range(self.window)]
        reg = registry or DEFAULT_REGISTRY
        addr_labels = [f"{h}:{p}" for h, p in self._addresses]
        pooled = len(self._addresses) > 1
        self._label = name or ",".join(addr_labels)
        # single-address clients keep the historical one-gauge-per-client
        # label; pooled backends each get label@host:port so the breaker
        # gauge stays truthful per backend
        self._backend_labels = (
            [f"{self._label}@{a}" for a in addr_labels]
            if pooled
            else [self._label]
        )
        self._retries = reg.counter(
            "bst_oracle_retries_total",
            "Oracle requests retried after a transport failure",
        )
        self._failures = reg.counter(
            "bst_oracle_transport_failures_total",
            "Oracle transport failures (per attempt, pre-retry)",
        )
        self._reconnects = reg.counter(
            "bst_oracle_reconnects_total",
            "Oracle connections re-established after a transport failure",
        )
        self._deadline_errors = reg.counter(
            "bst_oracle_deadline_errors_total",
            "Oracle requests answered with an in-band deadline error",
        )
        self._busy_answers = reg.counter(
            "bst_oracle_busy_total",
            "Oracle requests answered BUSY (coalescer admission queue "
            "saturated) — retried after the server's retry-after hint, "
            "never a breaker failure",
        )
        self._breaker_gauge = reg.gauge(
            "bst_oracle_breaker_state",
            "Oracle circuit breaker state (0=closed 1=open 2=half-open)",
        )
        self._failovers = reg.counter(
            "bst_oracle_failover_total",
            "Pooled-client standby promotions by reason (drain = "
            "proactive on a DRAINING answer; crash = the active "
            "backend's breaker opened)",
        )
        self._active_gauge = reg.gauge(
            "bst_oracle_active_backend",
            "Index into the client's oracle address pool it is currently "
            "serving from (0 = first configured address)",
        )
        first = breaker or CircuitBreaker()
        self._breakers = [first]
        for _ in self._addresses[1:]:
            # standbys clone the caller's breaker CONFIG (threshold,
            # cooldown, clock) but never its state: each backend earns
            # its open/closed verdict from its own transport evidence
            self._breakers.append(
                CircuitBreaker(
                    failure_threshold=first.failure_threshold,
                    reset_timeout=first.reset_timeout,
                    clock=first._clock,
                )
            )
        for i, b in enumerate(self._breakers):
            b.on_transition = (
                lambda st, _i=i: self._record_breaker_state(st, _i)
            )
            self._record_breaker_state(b.state, i)
        self._backend_last_ok = [0.0] * len(self._addresses)
        self._promotions: deque = deque(maxlen=64)  # (wall_ts, reason, to)
        self._active_gauge.set(0, client=self._label)
        if pooled:
            _POOLED_CLIENTS.add(self)

    @property
    def breaker(self) -> CircuitBreaker:
        """The ACTIVE backend's breaker (the only one for a
        single-address client — the historical attribute, unchanged)."""
        return self._breakers[self._active]

    @property
    def active_address(self) -> Tuple[str, int]:
        """(host, port) of the pool backend currently being served from."""
        return self._addresses[self._active]

    @staticmethod
    def _check_deadline(deadline_ms: Optional[int]) -> Optional[int]:
        """Validate a deadline at CONFIG time. Left to pack_deadline, an
        invalid value would raise ValueError inside the request path,
        where it is indistinguishable from a desynced-stream transport
        failure — retried, reconnected, breaker-tripped, and (with the
        local-cpu fallback) silently degrading against a healthy sidecar."""
        if deadline_ms is not None and not 0 < deadline_ms <= 0xFFFFFFFF:
            raise ValueError(
                f"deadline_ms must be in 1..{0xFFFFFFFF}, got {deadline_ms}"
            )
        return deadline_ms

    def _record_breaker_state(self, state: str, idx: int = 0) -> None:
        self._breaker_gauge.set(
            _BREAKER_STATE_VALUES.get(state, -1),
            client=self._backend_labels[idx],
        )

    def would_attempt(self) -> bool:
        """True when the next call would actually touch the transport
        (breaker closed/half-open/cooldown elapsed) — the scorer's cue
        that a degraded batch is worth re-probing."""
        return self.breaker.would_attempt()

    def slot(self, idx: int) -> _ClientSlot:
        """A view pinned to connection slot ``idx`` (< window) — see the
        class docstring's in-flight-window contract."""
        if not 0 <= idx < self.window:
            raise IndexError(f"slot {idx} out of window {self.window}")
        return _ClientSlot(self, idx)

    def slot_telemetry(self, slot: int) -> Optional[dict]:
        c = self._slot_clients[slot]
        return c.last_telemetry if c is not None else None

    @property
    def last_telemetry(self) -> Optional[dict]:
        """The underlying connection's last absorbed TRACE_INFO telemetry
        (None before any traced batch or while disconnected)."""
        return self.slot_telemetry(0)

    def close(self) -> None:
        for idx in range(self.window):
            self.close_slot(idx)

    def close_slot(self, idx: int) -> None:
        with self._slot_locks[idx]:
            self._drop(idx)

    def _ensure(self, slot: int = 0) -> OracleClient:
        active = self._active
        if (
            self._slot_clients[slot] is not None
            and self._slot_addr[slot] != active
        ):
            # a promotion happened since this slot dialed: the old
            # connection points at a draining/dead backend — re-dial
            # lazily (each slot under its own lock, so promotion never
            # needs to touch another slot's connection)
            self._drop(slot)
        if self._slot_clients[slot] is None:
            host, port = self._addresses[active]
            self._slot_clients[slot] = OracleClient(
                host,
                port,
                timeout=self._timeout,
                connect_timeout=self._connect_timeout,
            )
            self._slot_addr[slot] = active
            if self._slot_connected[slot]:
                self._reconnects.inc(client=self._label)
            self._slot_connected[slot] = True
        return self._slot_clients[slot]

    def _drop(self, slot: int = 0) -> None:
        if self._slot_clients[slot] is not None:
            self._slot_clients[slot].close()
            self._slot_clients[slot] = None

    def _note_ok(self) -> None:
        """Active backend answered over a working transport: close/keep
        its breaker closed and stamp its freshness (the /debug/health
        ``failover`` signal's standby-staleness input)."""
        self.breaker.record_success()
        self._backend_last_ok[self._active] = time.monotonic()

    def _promote(self, reason: str, require_healthy: bool = False) -> bool:
        """Advance the pool to the next standby, preferring one whose
        breaker would admit. Connections re-dial lazily per slot
        (``_ensure`` compares ``_slot_addr`` to the active index), so
        promotion never blocks on another slot's in-flight request;
        server-side delta mirrors die with the old connections and the
        standby forces a keyframe via the ordinary DELTA_RESYNC answer.
        ``require_healthy`` (the admission-refused path) declines to
        promote when every standby's breaker is also open — flapping
        round-robin through a fleet-wide outage would only falsify the
        failover counter. Returns False on a single-address pool."""
        if len(self._addresses) < 2:
            return False
        with self._pool_lock:
            old = self._active
            order = [
                (old + k) % len(self._addresses)
                for k in range(1, len(self._addresses))
            ]
            nxt = next(
                (i for i in order if self._breakers[i].would_attempt()),
                None,
            )
            if nxt is None:
                if require_healthy:
                    return False
                nxt = order[0]
            self._active = nxt
            self._promotions.append((time.time(), reason, nxt))
        self._failovers.inc(reason=reason, client=self._label)
        self._active_gauge.set(nxt, client=self._label)
        return True

    def _admit(self, slot: int = 0) -> None:
        decision = self.breaker.admit()
        if decision == "refuse" and self._promote(
            "crash", require_healthy=True
        ):
            # the active backend is in cooldown but a standby would
            # admit: serve from the standby instead of failing fast
            decision = self.breaker.admit()
        if decision == "refuse":
            raise CircuitOpenError(
                f"oracle circuit open ({self._label}); "
                f"retrying after {self.breaker.reset_timeout}s cooldown"
            )
        if decision == "probe":
            # the probe must stay BOUNDED against a hung-but-accepting
            # sidecar: without a deadline it would wait the full base
            # socket timeout (default 120s) inside a scheduling cycle on
            # every cooldown expiry — the exact stall the breaker exists
            # to prevent. Use the configured deadline, else the connect
            # timeout as the probe budget.
            probe_ms = (
                self.deadline_ms
                if self.deadline_ms is not None
                else max(int(self._connect_timeout * 1000), 100)
            )
            try:
                ok = self._ensure(slot).ping(deadline_ms=probe_ms)
            except Exception:  # noqa: BLE001 — any probe failure re-opens
                ok = False
            if not ok:
                self._drop(slot)
                self.breaker.record_failure()
                raise CircuitOpenError(
                    f"oracle half-open probe failed ({self._label})"
                )
            self._note_ok()

    def _call(self, op: str, fn, slot: int = 0):
        with self._slot_locks[slot]:
            self._admit(slot)
            last: Optional[BaseException] = None
            slept_busy_hint = False
            prev_delay: Optional[float] = None
            for attempt in range(self.retry_policy.max_attempts):
                if attempt and not slept_busy_hint:
                    self._retries.inc(op=op, client=self._label)
                    # decorrelated jitter: each delay seeds the next
                    # draw's range, so clients that crashed in sync
                    # drift apart instead of stampeding the standby
                    prev_delay = self.retry_policy.backoff(
                        attempt - 1, prev=prev_delay
                    )
                    time.sleep(prev_delay)
                slept_busy_hint = False
                try:
                    result = fn(self._ensure(slot))
                except (StaleBatchError, OracleDeadlineError) as e:
                    # semantic answers over a live transport: never
                    # retried (stale stays stale; a deadline retry blows
                    # the same budget), never advance the breaker
                    if isinstance(e, OracleDeadlineError):
                        self._deadline_errors.inc(client=self._label)
                    self._note_ok()
                    raise
                except OracleDrainingError as e:
                    # graceful-shutdown answer over a live transport —
                    # never a breaker failure. With a standby configured
                    # this is the PROACTIVE failover signal: promote and
                    # re-issue immediately (no backoff — the primary just
                    # told us it will never serve this request).
                    # Single-address clients wait out the hint like BUSY
                    # and surface the DrainingError when attempts run out.
                    self._note_ok()
                    last = e
                    if self._promote("drain"):
                        slept_busy_hint = True  # promotion IS the wait
                        continue
                    if attempt + 1 >= self.retry_policy.max_attempts:
                        raise
                    time.sleep(min(max(e.retry_after_ms, 1) / 1000.0, 5.0))
                    slept_busy_hint = True
                except OracleBusyError as e:
                    # the sidecar is alive and telling us exactly when to
                    # come back: wait out its hint (capped) and burn one
                    # retry attempt — overload resolves, so unlike a
                    # deadline this IS retried; unlike a transport
                    # failure it never advances the breaker or drops the
                    # connection. Exhausted attempts surface the
                    # BusyError itself (the scorer's fallback decides),
                    # not a transport wrapper.
                    self._note_ok()
                    self._busy_answers.inc(op=op, client=self._label)
                    if attempt + 1 >= self.retry_policy.max_attempts:
                        raise
                    time.sleep(min(max(e.retry_after_ms, 1) / 1000.0, 5.0))
                    # the hint IS the wait: skip the generic transport
                    # backoff (and its retries counter — this was an
                    # answered request, not a transport failure) so the
                    # retry lands when the server said a slot frees up
                    slept_busy_hint = True
                    last = e
                except _TRANSPORT_ERRORS as e:
                    self._failures.inc(op=op, client=self._label)
                    self._drop(slot)
                    self.breaker.record_failure()
                    last = e
                    if not self.breaker.would_attempt():
                        # breaker opened mid-loop. With a standby this is
                        # the CRASH promotion trigger: point the
                        # remaining attempts at it (backoff still
                        # applies — the dial is real work). Without one,
                        # stop burning attempts.
                        if not self._promote("crash"):
                            break
                except RuntimeError:
                    # in-band server error (bad request, row out of
                    # range): the transport answered — surface as-is
                    self._note_ok()
                    raise
                else:
                    self._note_ok()
                    return result
            raise OracleTransportError(
                f"oracle {op} via {self._label} failed after "
                f"{self.retry_policy.max_attempts} attempts: {last}"
            ) from last

    def ping(self, deadline_ms: Optional[int] = None, _slot: int = 0) -> bool:
        d = (
            self.deadline_ms
            if deadline_ms is None
            else self._check_deadline(deadline_ms)
        )
        return self._call("ping", lambda c: c.ping(deadline_ms=d), slot=_slot)

    def schedule(
        self,
        req: proto.ScheduleRequest,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
        _slot: int = 0,
    ) -> proto.ScheduleResponse:
        d = (
            self.deadline_ms
            if deadline_ms is None
            else self._check_deadline(deadline_ms)
        )
        return self._call(
            "schedule",
            lambda c: c.schedule(
                req, deadline_ms=d, audit_id=audit_id, policy_fp=policy_fp,
                tenant=tenant,
            ),
            slot=_slot,
        )

    def delta_schedule(
        self,
        kind: int,
        base_generation: int,
        new_generation: int,
        body,
        deadline_ms: Optional[int] = None,
        audit_id: Optional[str] = None,
        policy_fp: Optional[str] = None,
        tenant: Optional[str] = None,
        _slot: int = 0,
    ) -> proto.ScheduleResponse:
        d = (
            self.deadline_ms
            if deadline_ms is None
            else self._check_deadline(deadline_ms)
        )
        return self._call(
            "delta_schedule",
            lambda c: c.delta_schedule(
                kind, base_generation, new_generation, body, deadline_ms=d,
                audit_id=audit_id, policy_fp=policy_fp, tenant=tenant,
            ),
            slot=_slot,
        )

    def row(
        self,
        kind: str,
        group_index: int,
        batch_seq: int = 0,
        deadline_ms: Optional[int] = None,
        _slot: int = 0,
    ) -> np.ndarray:
        d = (
            self.deadline_ms
            if deadline_ms is None
            else self._check_deadline(deadline_ms)
        )
        return self._call(
            "row",
            lambda c: c.row(kind, group_index, batch_seq, deadline_ms=d),
            slot=_slot,
        )


class _DeltaCursor:
    """Per-connection-lane wire-delta state (docs/pipelining.md
    "Device-resident state"): which generation the server's mirror on THIS
    lane holds, and the union of churned rows packed since — batches
    alternate lanes, so each lane's delta spans every pack since that lane
    last synced. Touched only under the scorer's refresh lock (_note_pack
    and _execute both run inside it)."""

    __slots__ = ("server_gen", "synced", "pending_nodes", "pending_groups",
                 "need_keyframe")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        """Forget the server's state (reconnect, resync, fallback, any
        error whose server-side effect is unknown): the next batch on this
        lane is a keyframe."""
        self.server_gen = 0
        self.synced = False
        self.need_keyframe = True
        self.pending_nodes: set = set()
        self.pending_groups: set = set()

    def note(self, delta) -> None:
        """Fold one pack's SnapshotDelta in. A keyframe-kind record (full
        repack / node-list / group-set change) invalidates positional row
        indices — this lane must resync from a keyframe. Event-fold packs
        (``delta.source == "events"``, ops.snapshot.pack_fold) carry kind
        "delta" with the same unpadded-space row indices as scan deltas —
        positional stability is a precondition of the fold itself — so
        they accumulate here unchanged, and the DELTA_ROWS frame's
        wholesale order/fit columns keep the server's mirror exact even
        when the host resorted the queue between syncs."""
        if delta is None or delta.kind != "delta":
            self.need_keyframe = True
            self.pending_nodes.clear()
            self.pending_groups.clear()
            return
        if self.synced and not self.need_keyframe:
            self.pending_nodes.update(delta.node_rows.tolist())
            self.pending_groups.update(delta.group_rows.tolist())

    def mark_synced(self, generation: int) -> None:
        self.server_gen = generation
        self.synced = True
        self.need_keyframe = False
        self.pending_nodes.clear()
        self.pending_groups.clear()


class RemoteScorer(OracleScorer):
    """OracleScorer whose batch executes on the sidecar service.

    With one connection, background refresh is refused: a background batch
    would hold the connection's lock for the whole sidecar round-trip, so
    any uncached row read in a scheduling cycle would stall behind it —
    the critical-path cost would come back hidden inside
    node_capacity/node_score.

    Pass ``background_client`` (a second connection to the same server) to
    lift that: batches alternate between the two connections, and each
    batch's row fetcher is pinned to the connection that executed it (the
    server keeps batch state per connection), so row reads on the current
    batch never contend with the next batch running on the other
    connection.

    ``fallback`` decides what a batch does when the sidecar transport is
    down (retries exhausted or breaker open) or over deadline:

    - ``"deny"`` (default): the error surfaces into the scheduling cycle
      (the cycle requeues the pod with backoff — visible failure).
    - ``"local-cpu"``: serve a CONSERVATIVE host-side batch instead
      (core.oracle_scorer.conservative_cpu_batch): real per-node member
      capacities and exact independent-feasibility, but no placements and
      no plans — so nothing is admitted speculatively, and PreFilter
      denies only provably-infeasible gangs (docs/resilience.md). The
      scorer marks itself ``degraded``; with a ResilientOracleClient it
      re-probes automatically once the breaker cooldown elapses."""

    FALLBACK_MODES = ("deny", "local-cpu")

    # 16-hex policy-config fingerprint announced on every schedule request
    # when the embedding operation runs a policy engine (the sidecar
    # executes base batches; skew is counted server-side, never silent).
    # Stamped by ScheduleOperation; None keeps the wire pre-policy.
    policy_fingerprint = None

    def __init__(
        self,
        client: OracleClient,
        background_client: OracleClient = None,
        fallback: str = "deny",
        tenant: Optional[str] = None,
    ):
        # device_state=False: this process's device lives behind the
        # sidecar — the server keeps the resident mirror, fed by the wire
        # deltas below, so a local holder would only duplicate the upload
        super().__init__(device_state=False)
        if fallback not in self.FALLBACK_MODES:
            raise ValueError(
                f"unknown fallback {fallback!r} (use one of {self.FALLBACK_MODES})"
            )
        if background_client is not None:
            self._clients = [client, background_client]
        elif getattr(client, "window", 1) > 1:
            # a windowed ResilientOracleClient provides the second lane
            # itself: slot views alternate exactly like an explicit
            # background client, each batch pinned to the slot (server
            # connection) that executed it
            self._clients = [client.slot(0), client.slot(1)]
        else:
            self._clients = [client]
        self._next = 0
        self.fallback = fallback
        # wire tenant identity (docs/multitenancy.md): an explicit label
        # (multi-client sims, fleet deployments with a configured tenant)
        # wins; otherwise each batch announces its snapshot's dominant
        # namespace (OracleScorer.dominant_tenant) — cardinality-capped,
        # so the sidecar's label set stays bounded. None/"" keeps the
        # wire bytes identical to a pre-tenant client.
        self.tenant = tenant
        self.supports_background_refresh = len(self._clients) > 1
        # dispatch-ahead has the same single-connection hazard as
        # background refresh: the speculative wire round-trip would hold
        # the only connection while cycles read rows
        self.supports_dispatch_ahead = len(self._clients) > 1
        self._fallback_batches = DEFAULT_REGISTRY.counter(
            "bst_oracle_fallback_batches_total",
            "Oracle batches served by the conservative local-CPU fallback",
        )
        self._degraded_gauge = DEFAULT_REGISTRY.gauge(
            "bst_oracle_degraded",
            "1 while the remote scorer serves the conservative CPU fallback",
        )
        # Wire deltas (docs/pipelining.md "Device-resident state"): ship
        # only churned rows + generation; the sidecar keeps the
        # device-resident mirror per connection. Gated to resilient
        # transports (``would_attempt`` — resync recovery closes the lane
        # and must be able to re-dial; a plain OracleClient keeps full
        # snapshots) and to BST_DEVICE_STATE. Disproven once against an
        # old peer (in-band "unknown message type"), the process falls
        # back to full snapshots permanently — bit-identical either way.
        from ..ops.device_state import device_state_enabled

        self._cursors = [_DeltaCursor() for _ in self._clients]
        self._wire_delta_ok = device_state_enabled() and all(
            hasattr(c, "delta_schedule") and hasattr(c, "would_attempt")
            for c in self._clients
        )
        # TENANT annotation gate: disproven ONCE against an old peer
        # (in-band "unknown message type 16"), the process stops
        # announcing tenants permanently — same mixed-fleet discipline
        # as the wire-delta fallback, plans unaffected either way.
        # Resilient lanes only (the wire-delta gating): recovering from
        # an old peer's error answer requires dropping the lane (the real
        # response is still in the stream behind it) and re-dialing — a
        # plain OracleClient never reconnects, so on one the recovery
        # would permanently kill the transport. A plain-client
        # deployment just keeps its pre-tenant attribution.
        self._wire_tenant_ok = all(
            hasattr(c, "would_attempt") for c in self._clients
        )
        self._wire_delta_counter = DEFAULT_REGISTRY.counter(
            "bst_oracle_wire_delta_batches_total",
            "Remote batches by wire encoding: churned-row delta, full "
            "keyframe (mirror install/resync), or plain full snapshot "
            "(delta path off or peer without it)",
        )
        self._wire_resyncs = DEFAULT_REGISTRY.counter(
            "bst_oracle_wire_delta_resyncs_total",
            "DELTA_RESYNC answers received (sidecar mirror refused a "
            "delta: generation gap / reconnect) — each forces a keyframe",
        )

    def close(self) -> None:
        for c in self._clients:
            c.close()

    def _probe_due(self) -> bool:
        """While degraded, a batch is worth re-attempting only when the
        next transport call would actually go out (breaker cooldown
        elapsed). A plain OracleClient has no breaker: always re-attempt."""
        client = self._clients[self._next]
        would = getattr(client, "would_attempt", None)
        return True if would is None else would()

    def _set_degraded(self, flag: bool) -> None:
        if flag:
            self._fallback_batches.inc()
        self.degraded = flag
        self._degraded_gauge.set(1 if flag else 0)

    def _note_pack(self, snap) -> None:  # lock-held: _refresh_lock
        """Feed each lane's wire-delta cursor with this pack's churned-row
        record (the local device-state sync the base class does here is
        the sidecar's job on this path)."""
        delta = getattr(snap, "delta", None)
        for cursor in self._cursors:
            cursor.note(delta)

    def _build_delta(self, snap, cursor) -> proto.DeltaScheduleRequest:
        """The churned rows this lane's mirror is missing, read from the
        snapshot's padded arrays (indices are unpadded-space, a prefix of
        padded space — same row values; the server scatters them into its
        padded mirror at the same indices)."""
        node_idx = np.asarray(sorted(cursor.pending_nodes), dtype=np.int32)
        group_idx = np.asarray(sorted(cursor.pending_groups), dtype=np.int32)
        return proto.DeltaScheduleRequest(
            node_idx=node_idx,
            node_rows=np.asarray(snap.requested)[node_idx],
            group_idx=group_idx,
            group_rows=np.asarray(snap.group_req)[group_idx],
            remaining=snap.remaining,
            fit_mask=snap.fit_mask,
            group_valid=snap.group_valid,
            order=snap.order,
            min_member=snap.min_member,
            scheduled=snap.scheduled,
            matched=snap.matched,
            ineligible=snap.ineligible,
            creation_rank=snap.creation_rank,
            n=int(snap.alloc.shape[0]),
            g=int(snap.group_req.shape[0]),
            r=int(snap.alloc.shape[1]),
        )

    def _drop_lane(self, client, cursor) -> None:
        """Close a lane whose stream may carry stale replies (a resync
        after a generation gap) so the next call re-dials clean, and
        forget the server state that died with it."""
        try:
            client.close()
        except Exception:  # noqa: BLE001 — already tearing the lane down
            pass
        cursor.reset()

    def _wire_schedule(self, client, cursor, snap, req, audit_id, policy_fp,
                       tenant=None):
        """One remote batch, delta-encoded when this lane's mirror can
        take it: churned rows + generation (DELTA_ROWS), a full keyframe
        when the mirror needs (re)installing, or a plain full snapshot
        when the delta path is off / the peer predates it. Every encoding
        yields the same executed batch server-side — bit-identity is the
        bench-delta gate's claim, not an optimisation hope."""
        delta = getattr(snap, "delta", None)
        if not self._wire_delta_ok or delta is None:
            self._wire_delta_counter.inc(kind="full")
            return client.schedule(
                req, audit_id=audit_id, policy_fp=policy_fp, tenant=tenant
            )
        gen = delta.generation
        if cursor.synced and not cursor.need_keyframe:
            n, g = int(snap.alloc.shape[0]), int(snap.group_req.shape[0])
            # a delta wider than half the state costs more than a
            # keyframe (rows + indices vs rows): send the keyframe
            if (
                len(cursor.pending_nodes) <= max(n // 2, 1)
                and len(cursor.pending_groups) <= max(g // 2, 1)
            ):
                try:
                    resp = client.delta_schedule(
                        proto.DELTA_ROWS, cursor.server_gen, gen,
                        self._build_delta(snap, cursor),
                        audit_id=audit_id, policy_fp=policy_fp,
                        tenant=tenant,
                    )
                    cursor.mark_synced(gen)
                    self._wire_delta_counter.inc(kind="delta")
                    return resp
                except DeltaResyncRequired:
                    # the mirror refused (generation gap — dropped or
                    # duplicated frame, or a reconnect emptied it). The
                    # stream beyond a gap may carry stale replies: drop
                    # the lane, then resync from a keyframe below.
                    self._wire_resyncs.inc()
                    self._drop_lane(client, cursor)
                except RuntimeError as e:
                    if "unknown message type" not in str(e) or (
                        "message type 16" in str(e)
                    ):
                        # type 16 is the TENANT annotation, written
                        # BEFORE the delta frame: _execute owns that
                        # fallback (stop announcing tenants), not this
                        # knob
                        raise
                    # old peer: no MsgType 14 — full snapshots, forever
                    self._wire_delta_ok = False
                    self._wire_delta_counter.inc(kind="full")
                    return client.schedule(
                        req, audit_id=audit_id, policy_fp=policy_fp,
                        tenant=tenant,
                    )
        try:
            resp = client.delta_schedule(
                proto.DELTA_KEYFRAME, 0, gen, req,
                audit_id=audit_id, policy_fp=policy_fp, tenant=tenant,
            )
            cursor.mark_synced(gen)
            self._wire_delta_counter.inc(kind="keyframe")
            return resp
        except DeltaResyncRequired:
            # a keyframe is unconditionally applicable; an answer here
            # means the stream itself is desynced — re-dial and fall
            # back to the plain full snapshot for this batch
            self._wire_resyncs.inc()
            self._drop_lane(client, cursor)
            self._wire_delta_counter.inc(kind="full")
            return client.schedule(
                req, audit_id=audit_id, policy_fp=policy_fp, tenant=tenant
            )
        except RuntimeError as e:
            if "unknown message type" not in str(e) or (
                "message type 16" in str(e)
            ):
                raise  # type 16 = TENANT annotation: _execute's fallback
            self._wire_delta_ok = False
            self._wire_delta_counter.inc(kind="full")
            return client.schedule(
                req, audit_id=audit_id, policy_fp=policy_fp, tenant=tenant
            )

    def _execute(self, snap: ClusterSnapshot):
        # fit_mask may be the [1,N] broadcast fast path; the wire carries
        # it as ONE row (protocol mask_rows header — at 5k nodes the
        # expanded [G,N] form was 96% of the request bytes).
        req = proto.ScheduleRequest(
            alloc=snap.alloc,
            requested=snap.requested,
            group_req=snap.group_req,
            remaining=snap.remaining,
            fit_mask=snap.fit_mask,
            group_valid=snap.group_valid,
            order=snap.order,
            min_member=snap.min_member,
            scheduled=snap.scheduled,
            matched=snap.matched,
            ineligible=snap.ineligible,
            creation_rank=snap.creation_rank,
        )
        # _execute calls are serialized by the scorer's _refresh_lock;
        # alternating here means a background batch runs on the connection
        # the CURRENT batch's rows are not being read from
        slot = self._next
        client = self._clients[slot]
        cursor = self._cursors[slot]
        self._next = (self._next + 1) % len(self._clients)
        # audit correlation: when this scorer records audit evidence, the
        # batch's ID is minted HERE (before the round-trip) and sent as the
        # AUDIT_ID annotation so the sidecar's own record of this batch
        # carries the same ID; _publish consumes the marker for the
        # client-side record (same ride-along contract as _degraded)
        audit_id = None
        if self.audit_log is not None:
            from ..utils import audit as audit_mod

            audit_id = audit_mod.new_audit_id()
        # policy skew annotation (docs/policy.md "Wire"): the sidecar runs
        # base (policy-unaware) batches, so a client with an active policy
        # engine announces its config fingerprint and the server counts
        # the mismatch — never a silent divergence. None when no policy is
        # live, which keeps the wire bytes identical to a pre-policy client.
        policy_fp = getattr(self, "policy_fingerprint", None)
        # tenant identity at the client edge (docs/multitenancy.md): an
        # explicit configured label wins; else the snapshot's dominant
        # namespace, through the cardinality-capped registry — the same
        # label the local scan counter uses (OracleScorer._execute)
        tenant = None
        if self._wire_tenant_ok:
            tenant = self.tenant or self.dominant_tenant(snap) or None
        try:
            with trace_mod.span("oracle.wire_round_trip", cat="oracle"):
                try:
                    resp = self._wire_schedule(
                        client, cursor, snap, req, audit_id, policy_fp,
                        tenant=tenant,
                    )
                except RuntimeError as e:
                    if not (
                        tenant and "unknown message type 16" in str(e)
                    ):
                        raise
                    # old peer: no TENANT frame. The stream still holds
                    # the un-consumed real response behind the in-band
                    # error, so drop the lane and resend plain — and
                    # never announce again (DEADLINE ship-together rule,
                    # degraded gracefully).
                    self._wire_tenant_ok = False
                    self._drop_lane(client, cursor)
                    resp = self._wire_schedule(
                        client, cursor, snap, req, audit_id, policy_fp
                    )
        except _TRANSPORT_ERRORS + (
            OracleDeadlineError, OracleBusyError, OracleDrainingError,
        ) as e:
            # whether the server applied anything is unknown (a deadline
            # may abandon a half-applied delta): forget this lane's
            # mirror state so the next batch on it keyframes. A BUSY
            # answer is the exception — admission was refused before any
            # mirror mutation, so the cursor stays valid. A DRAINING
            # answer surfacing here means a single-address client rode
            # out the whole retry budget against a draining sidecar: the
            # connection dies with the server, so the cursor resets too.
            if not isinstance(e, OracleBusyError):
                cursor.reset()
            # raw OSError/EOFError included, not just the resilient
            # client's wrapped OracleTransportError: a plain OracleClient
            # is a supported transport here, and its bare socket errors
            # must reach the same fallback
            if self.fallback != "local-cpu":
                raise
            # conservative degradation: safe progress over exact answers.
            # CircuitOpenError lands here too, so during an outage this
            # path costs one host-side numpy pass, no connect timeout.
            # The degraded FLAG flips only when this batch is PUBLISHED
            # (_publish consumes the marker): a dispatch-ahead speculative
            # batch degrading mid-flight must not relax PreFilter
            # semantics for the healthy batch still being served.
            host, fetcher = conservative_cpu_batch(snap)
            host["_degraded"] = True
            return host, fetcher
        host = {
            "_degraded": False,
            "gang_feasible": resp.gang_feasible,
            "placed": resp.placed,
            "assignment_nodes": resp.assignment_nodes,
            "assignment_counts": resp.assignment_counts,
            "best": resp.best,
            "best_exists": resp.best_exists,
            "progress": resp.progress,
        }
        # traced batches carry the sidecar's device telemetry back in the
        # TRACE_INFO frame; surface it like the in-process path does so
        # the flight recorder's batch records are transport-agnostic
        telemetry = getattr(client, "last_telemetry", None)
        if telemetry:
            host["telemetry"] = telemetry
        if audit_id is not None:
            host["_audit_id"] = audit_id
        batch_seq = resp.batch_seq

        def row_fetcher(kind: str, g: int) -> np.ndarray:
            # the captured batch_seq pins this fetcher to ITS batch ON ITS
            # connection: if a newer batch has run there, the server answers
            # an in-band stale-batch error instead of another batch's row
            return client.row(kind, g, batch_seq)

        return host, row_fetcher
