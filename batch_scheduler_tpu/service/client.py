"""Oracle sidecar clients.

``OracleClient`` is the raw protocol client (one TCP connection, serialized
round-trips). ``RemoteScorer`` plugs it into ScheduleOperation with the same
interface as the in-process OracleScorer — the control plane is agnostic to
whether the oracle lives in-process on the local chip or behind the sidecar
(the deployment split of the north star: Go plugin <-> JAX sidecar).
"""

from __future__ import annotations

import socket
import threading
from typing import Tuple

import numpy as np

from ..core.oracle_scorer import OracleScorer
from ..ops.snapshot import ClusterSnapshot
from . import protocol as proto

__all__ = ["OracleClient", "RemoteScorer"]


class OracleClient:
    # default generous enough to sit through a first TPU jit compile of a
    # new bucket shape (~20-40s) plus the batch itself
    def __init__(self, host: str, port: int, timeout: float = 120.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _round_trip(self, msg_type: int, payload: bytes) -> Tuple[int, bytes]:
        with self._lock:
            proto.write_frame(self._sock, msg_type, payload)
            resp_type, resp = proto.read_frame(self._sock)
        if resp_type == proto.MsgType.ERROR:
            message = resp.decode(errors="replace")
            if "stale batch" in message:
                from ..utils.errors import StaleBatchError

                raise StaleBatchError(message)
            raise RuntimeError(f"oracle server error: {message}")
        return resp_type, resp

    def ping(self) -> bool:
        resp_type, _ = self._round_trip(proto.MsgType.PING, b"")
        return resp_type == proto.MsgType.PONG

    def schedule(self, req: proto.ScheduleRequest) -> proto.ScheduleResponse:
        resp_type, resp = self._round_trip(
            proto.MsgType.SCHEDULE_REQ, proto.pack_schedule_request(req)
        )
        if resp_type != proto.MsgType.SCHEDULE_RESP:
            raise RuntimeError(f"unexpected response type {resp_type}")
        return proto.unpack_schedule_response(resp)

    def row(self, kind: str, group_index: int, batch_seq: int = 0) -> np.ndarray:
        resp_type, resp = self._round_trip(
            proto.MsgType.ROW_REQ,
            proto.pack_row_request(kind, group_index, batch_seq),
        )
        if resp_type != proto.MsgType.ROW_RESP:
            raise RuntimeError(f"unexpected response type {resp_type}")
        return np.frombuffer(resp, dtype="<i4")


class RemoteScorer(OracleScorer):
    """OracleScorer whose batch executes on the sidecar service.

    With one connection, background refresh is refused: a background batch
    would hold the connection's lock for the whole sidecar round-trip, so
    any uncached row read in a scheduling cycle would stall behind it —
    the critical-path cost would come back hidden inside
    node_capacity/node_score.

    Pass ``background_client`` (a second connection to the same server) to
    lift that: batches alternate between the two connections, and each
    batch's row fetcher is pinned to the connection that executed it (the
    server keeps batch state per connection), so row reads on the current
    batch never contend with the next batch running on the other
    connection."""

    def __init__(
        self, client: OracleClient, background_client: OracleClient = None
    ):
        super().__init__()
        self._clients = [client] if background_client is None else [
            client, background_client,
        ]
        self._next = 0
        self.supports_background_refresh = background_client is not None

    def close(self) -> None:
        for c in self._clients:
            c.close()

    def _execute(self, snap: ClusterSnapshot):
        # fit_mask may be the [1,N] broadcast fast path; the wire carries
        # it as ONE row (protocol mask_rows header — at 5k nodes the
        # expanded [G,N] form was 96% of the request bytes).
        req = proto.ScheduleRequest(
            alloc=snap.alloc,
            requested=snap.requested,
            group_req=snap.group_req,
            remaining=snap.remaining,
            fit_mask=snap.fit_mask,
            group_valid=snap.group_valid,
            order=snap.order,
            min_member=snap.min_member,
            scheduled=snap.scheduled,
            matched=snap.matched,
            ineligible=snap.ineligible,
            creation_rank=snap.creation_rank,
        )
        # _execute calls are serialized by the scorer's _refresh_lock;
        # alternating here means a background batch runs on the connection
        # the CURRENT batch's rows are not being read from
        client = self._clients[self._next]
        self._next = (self._next + 1) % len(self._clients)
        resp = client.schedule(req)
        host = {
            "gang_feasible": resp.gang_feasible,
            "placed": resp.placed,
            "assignment_nodes": resp.assignment_nodes,
            "assignment_counts": resp.assignment_counts,
            "best": resp.best,
            "best_exists": resp.best_exists,
            "progress": resp.progress,
        }
        batch_seq = resp.batch_seq

        def row_fetcher(kind: str, g: int) -> np.ndarray:
            # the captured batch_seq pins this fetcher to ITS batch ON ITS
            # connection: if a newer batch has run there, the server answers
            # an in-band stale-batch error instead of another batch's row
            return client.row(kind, g, batch_seq)

        return host, row_fetcher
