"""The oracle sidecar service: a TCP server wrapping the jitted batch.

This is the deployment shape of the north star: the (Go) control plane keeps
its informers and gang choreography, and ships packed resource arrays to
this sidecar, which owns the TPU and answers with O(G) verdicts + compact
assignments. Stateless across batches (all durable state stays in the CRD
status, SURVEY.md §5 checkpoint/resume) — per-connection, the last batch's
(G,N) tensors are kept on device so row fetches don't resend the batch.
"""

from __future__ import annotations

import socketserver
import threading
from typing import Optional

import jax
import numpy as np

from ..ops.bucketing import pad_oracle_batch
from ..ops.oracle import execute_batch_host
from . import protocol as proto

__all__ = ["OracleServer", "serve_background"]


def _pad_request(req: proto.ScheduleRequest):
    """Bucket-pad an unpadded request via the SAME canonical padding as the
    in-process snapshot packer (ops.bucketing.pad_oracle_batch) so the wire
    path can never drift from the local path.

    The wire carries ``mask_rows`` rows (1 = broadcast fast path, G =
    per-group masks); a client that shipped a uniform [G,N] mask anyway is
    re-collapsed to the broadcast [1,N] row here so its batches still
    reach the same fast paths as in-process batches (smaller device
    transfer + the fused pallas assignment kernel)."""
    n = req.alloc.shape[0]
    g = req.group_req.shape[0]
    mask = req.fit_mask
    if mask.shape[0] > 1 and bool((mask == mask[0:1]).all()):
        mask = mask[0:1]
    batch_args, progress_args = pad_oracle_batch(
        alloc=req.alloc,
        requested=req.requested,
        group_req=req.group_req,
        remaining=req.remaining,
        fit_mask=mask,
        group_valid=req.group_valid,
        order=req.order,
        min_member=req.min_member,
        scheduled=req.scheduled,
        matched=req.matched,
        ineligible=req.ineligible,
        creation_rank=req.creation_rank,
    )
    return batch_args, progress_args, (n, g)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        last_batch: Optional[dict] = None
        last_counts = (0, 0)
        batch_seq = 0
        while True:
            try:
                msg_type, payload = proto.read_frame(self.request)
            except (ConnectionError, OSError):
                return
            except ValueError:
                return  # not speaking our protocol: drop the connection
            try:
                if msg_type == proto.MsgType.PING:
                    proto.write_frame(self.request, proto.MsgType.PONG, b"")
                elif msg_type == proto.MsgType.SCHEDULE_REQ:
                    req = proto.unpack_schedule_request(payload)
                    args, progress_args, (n, g) = _pad_request(req)
                    mesh = self.server.scan_mesh
                    if mesh is not None:
                        from ..parallel.mesh import shard_snapshot_args

                        args = shard_snapshot_args(mesh, args)
                    host, last_batch = execute_batch_host(
                        args, progress_args, scan_mesh=mesh
                    )
                    last_counts = (n, g)
                    batch_seq += 1
                    resp = proto.ScheduleResponse(
                        gang_feasible=np.asarray(host["gang_feasible"])[:g],
                        placed=np.asarray(host["placed"])[:g],
                        progress=np.asarray(host["progress"])[:g],
                        best=int(host["best"]),
                        best_exists=bool(host["best_exists"]),
                        assignment_nodes=np.asarray(host["assignment_nodes"])[:g],
                        assignment_counts=np.asarray(host["assignment_counts"])[:g],
                        batch_seq=batch_seq,
                    )
                    proto.write_frame(
                        self.request,
                        proto.MsgType.SCHEDULE_RESP,
                        proto.pack_schedule_response(resp),
                    )
                elif msg_type == proto.MsgType.ROW_REQ:
                    kind, gidx, req_seq = proto.unpack_row_request(payload)
                    if last_batch is None:
                        raise ValueError("row request before any batch")
                    if req_seq != batch_seq:
                        raise ValueError(
                            f"stale batch: row for seq {req_seq}, current {batch_seq}"
                        )
                    n, g = last_counts
                    if not 0 <= gidx < g:
                        raise ValueError(f"row index {gidx} out of range {g}")
                    row = np.asarray(
                        jax.device_get(last_batch[kind][gidx])
                    ).astype("<i4")[:n]
                    proto.write_frame(
                        self.request, proto.MsgType.ROW_RESP, row.tobytes()
                    )
                else:
                    raise ValueError(f"unknown message type {msg_type}")
            except Exception as e:  # protocol errors answer in-band
                try:
                    proto.write_frame(
                        self.request, proto.MsgType.ERROR, str(e).encode()
                    )
                except OSError:
                    return


class OracleServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        # Multi-chip deployments (v5e-4 DP config of BASELINE, or a full
        # slice after init_distributed) shard batches over the global mesh
        # with the replicated-scan layout; one chip stays single-device.
        import jax

        from ..parallel.distributed import global_mesh

        self.scan_mesh = global_mesh() if len(jax.devices()) > 1 else None

    @property
    def address(self):
        return self.server_address


def serve_background(host: str = "127.0.0.1", port: int = 0) -> OracleServer:
    """Start an OracleServer on a daemon thread; returns it (``.address``
    has the bound port, ``.shutdown()`` stops it)."""
    server = OracleServer(host, port)
    t = threading.Thread(
        target=server.serve_forever, name="oracle-server", daemon=True
    )
    t.start()
    return server
