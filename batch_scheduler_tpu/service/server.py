"""The oracle sidecar service: a TCP server wrapping the jitted batch.

This is the deployment shape of the north star: the (Go) control plane keeps
its informers and gang choreography, and ships packed resource arrays to
this sidecar, which owns the TPU and answers with O(G) verdicts + compact
assignments. Stateless across batches (all durable state stays in the CRD
status, SURVEY.md §5 checkpoint/resume) — per-connection, the last batch's
(G,N) tensors are kept on device so row fetches don't resend the batch.

Deadline enforcement (docs/resilience.md): a DEADLINE annotation frame
bounds the next request; request bodies run on a per-connection daemon
worker so the handler can answer a DEADLINE_ERROR frame the moment the
budget elapses instead of letting a slow jit compile blow the caller's
scheduling-cycle budget.

Device work is issued by a single-owner executor queue thread
(``DeviceExecutor``, docs/pipelining.md): connections unpack/pad
concurrently and enqueue packed batches; the executor overlaps the next
batch's dispatch with the current batch's device compute (in-flight
window 2) while keeping every device's launch order total — the
mesh-collective safety the PR-1 ``execute_lock`` bought, without the
stop-and-wait.
"""

from __future__ import annotations

import os
import queue
import socketserver
import threading
import time
import weakref
from collections import deque
from typing import Optional

import jax
import numpy as np

from ..ops.bucketing import pad_oracle_batch
from ..ops.oracle import collect_batch, dispatch_batch
from ..utils.metrics import DEFAULT_REGISTRY, LONG_OP_BUCKETS
from ..utils import trace as trace_mod
from . import protocol as proto
from .coalescer import (
    CoalesceJob,
    CoalesceSaturated,
    OracleCoalescer,
    coalesce_enabled,
)

__all__ = [
    "DeviceExecutor",
    "OracleServer",
    "serve_background",
    "active_servers",
]

# graceful drain (docs/resilience.md "High availability"): the
# work-bearing message types the drain gate refuses. Annotations and PING
# keep flowing — a draining sidecar is alive and says so; only execution
# is refused.
_DRAIN_GATED = (
    proto.MsgType.SCHEDULE_REQ,
    proto.MsgType.DELTA_SCHEDULE_REQ,
    proto.MsgType.ROW_REQ,
)
# retry-after hint carried in every DRAINING answer: long enough that a
# single-address client's hint-sleeps don't hammer the dying process,
# short enough that it observes the exit within its retry budget
_DRAIN_RETRY_AFTER_MS = 200

# live servers in this process, for the /debug/drain endpoint
# (utils.metrics reaches them through a lazy import — no import cycle)
_LIVE_SERVERS: "weakref.WeakSet" = weakref.WeakSet()


def active_servers() -> list:
    """Every live OracleServer in this process (weakly held)."""
    return list(_LIVE_SERVERS)


def _drain_timeout_s() -> float:
    """BST_DRAIN_TIMEOUT_S: bound on how long ``drain()`` waits for the
    in-flight request window to empty before flushing and reporting
    (seconds, default 30; parse-guarded like every knob)."""
    raw = os.environ.get("BST_DRAIN_TIMEOUT_S")
    if raw is None:
        return 30.0
    try:
        return max(0.0, float(raw))
    except ValueError:
        return 30.0


# ---------------------------------------------------------------------------
# sidecar-side capacity observatory (ops.capacity)
# ---------------------------------------------------------------------------
#
# One process-wide sampler shared by every connection: a TRACED schedule
# batch (single-device only — mesh-placed args would reshard under the
# analytics jit) gets a budget-gated capacity sample whose compact form
# rides back to the client inside the TRACE_INFO telemetry dict, so a
# traced client sees the SIDECAR's utilization/fragmentation beside its
# own. The sidecar sees packed arrays, never names, so tenant attribution
# was historically all-"other"; a connection that announced its tenant
# (the TENANT wire annotation, docs/multitenancy.md) now attributes its
# batches' capacity shares to that label — the shares also feed the
# coalescer's DRF admission weights (_capacity_tenant_shares).
# Gated to traced requests: an untraced serving path must never pay the
# analytics kernel's first compile inside a deadline'd request.

_server_capacity_lock = threading.Lock()
_server_capacity = None  # guarded-by: _server_capacity_lock


def _capacity_tenant_shares() -> dict:
    """{tenant: dominant share} from the sidecar sampler's last summary —
    the capacity observatory's live feed into the coalescer's DRF
    admission order (empty before the first sample / with capacity off)."""
    with _server_capacity_lock:
        sampler = _server_capacity
    if sampler is None:
        return {}
    last = sampler.last()
    if not last:
        return {}
    return {
        t["tenant"]: float(t["dominant_share"])
        for t in last.get("tenants", [])
    }


def _maybe_server_capacity(batch_args, progress_args, host, tenant=None,
                           g=None) -> None:
    global _server_capacity
    from ..ops.capacity import CapacitySampler, capacity_enabled

    if not capacity_enabled():
        return
    with _server_capacity_lock:
        if _server_capacity is None:
            _server_capacity = CapacitySampler(label="server")
        sampler = _server_capacity
    try:
        kwargs = {}
        if tenant and g:
            # synthetic namespace-prefixed names: the kernel's per-batch
            # tenant mapping (utils.tenancy.batch_tenants) derives from
            # gang names the wire never carries — the announced label
            # stands in for all of them, so the whole batch attributes
            # to the connection's tenant instead of "other"
            kwargs["group_names"] = [f"{tenant}/wire-{i}" for i in range(g)]
        summary = sampler.note_batch(
            batch_args, host,
            scheduled=progress_args[1], matched=progress_args[2],
            **kwargs,
        )
    except Exception:  # noqa: BLE001 — telemetry only
        return
    tel = host.get("telemetry")
    if summary is None or not isinstance(tel, dict):
        return
    tel["capacity"] = {
        "fragmentation_index": summary["fragmentation_index"],
        "largest_placeable_gang": summary["largest_placeable_gang"],
        "utilization": {
            str(lane["lane"]): lane["utilization"]
            for lane in summary["lanes"] if lane["alloc"] > 0
        },
        "stranded_nodes": summary["stranded"]["nodes"],
        "pending_unplaceable_gangs": (
            summary["pending"]["unplaceable_gangs"]
        ),
    }


def _pad_request(req: proto.ScheduleRequest):
    """Bucket-pad an unpadded request via the SAME canonical padding as the
    in-process snapshot packer (ops.bucketing.pad_oracle_batch) so the wire
    path can never drift from the local path.

    The wire carries ``mask_rows`` rows (1 = broadcast fast path, G =
    per-group masks); a client that shipped a uniform [G,N] mask anyway is
    re-collapsed to the broadcast [1,N] row here so its batches still
    reach the same fast paths as in-process batches (smaller device
    transfer + the fused pallas assignment kernel)."""
    n = req.alloc.shape[0]
    g = req.group_req.shape[0]
    mask = req.fit_mask
    if mask.shape[0] > 1 and bool((mask == mask[0:1]).all()):
        mask = mask[0:1]
    batch_args, progress_args = pad_oracle_batch(
        alloc=req.alloc,
        requested=req.requested,
        group_req=req.group_req,
        remaining=req.remaining,
        fit_mask=mask,
        group_valid=req.group_valid,
        order=req.order,
        min_member=req.min_member,
        scheduled=req.scheduled,
        matched=req.matched,
        ineligible=req.ineligible,
        creation_rank=req.creation_rank,
    )
    return batch_args, progress_args, (n, g)


def _pad_delta_request(d: proto.DeltaScheduleRequest):
    """Pad a rows-delta's O(G) tail via THE canonical pad_oracle_batch, so
    the wire delta path can never drift from the full-request padding.

    The real lane buffers are device-resident in the connection's mirror,
    so the [N,R]/[G,R] positions get ZERO-WIDTH placeholders — they carry
    the n/g extents the bucket sizes derive from without re-materialising
    (or lane-scanning) full-size zero arrays per delta. The lane-domain
    check pad_oracle_batch would have run over full snapshots is applied
    to the CHURNED ROWS instead — the only lane values this frame carries
    — so an out-of-domain lane raises the same OverflowError (-> in-band
    ERROR) the full-snapshot wire path raises, never a silently wrong
    ``_exact_floordiv``. Returns the padded ``(remaining, fit_mask,
    group_valid, order)`` tail + progress args."""
    from ..ops.lanes import LANE_MAX

    for name, arr in (
        ("node_rows", d.node_rows), ("group_rows", d.group_rows)
    ):
        a = np.asarray(arr)
        if a.size and (np.abs(a.astype(np.int64)) > int(LANE_MAX)).any():
            raise OverflowError(
                f"delta {name} lanes exceed LANE_MAX (2**30): max abs "
                f"{int(np.abs(a.astype(np.int64)).max())}"
            )
    zeros_n = np.zeros((d.n, 0), np.int32)
    zeros_g = np.zeros((d.g, 0), np.int32)
    batch_args, progress_args = pad_oracle_batch(
        alloc=zeros_n,
        requested=zeros_n,
        group_req=zeros_g,
        remaining=d.remaining,
        fit_mask=d.fit_mask,
        group_valid=d.group_valid,
        order=d.order,
        min_member=d.min_member,
        scheduled=d.scheduled,
        matched=d.matched,
        ineligible=d.ineligible,
        creation_rank=d.creation_rank,
    )
    return batch_args[3:], progress_args


class _ResyncNeeded:
    """Sentinel outcome of a delta request the mirror could not apply —
    answered with a DELTA_RESYNC frame so the client resends a keyframe."""

    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason


_DEADLINE_HIT = object()

_EXEC_STOP = object()


class _ExecJob:
    """One unit of device work queued on the DeviceExecutor. ``wait``
    blocks until the executor completes it; an abandoned waiter (deadline
    hit on the connection worker) leaves the job to finish normally — its
    result is simply never delivered, so the device pipeline stays
    consistent no matter which side gave up."""

    __slots__ = ("kind", "args", "progress_args", "fn", "enqueued",
                 "queue_wait", "run_seconds", "donate", "tenant", "_done",
                 "_result", "_error")

    def __init__(self, kind, args=None, progress_args=None, fn=None,
                 donate=None, tenant=None):
        self.kind = kind
        self.args = args
        self.progress_args = progress_args
        self.fn = fn
        # None = executor default (donate single-device host-numpy
        # batches); False is forced for batches dispatched FROM a
        # device-resident mirror, whose buffers donation would consume
        self.donate = donate
        # tenant label (the TENANT wire annotation / coalescer span) for
        # the collect-side scan-counter attribution — the sidecar sees
        # packed arrays, never names, so the label is the only tenant
        # identity this process ever has
        self.tenant = tenant
        self.enqueued = time.perf_counter()
        self.queue_wait = 0.0
        self.run_seconds = 0.0
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def finish(self, result=None, error: Optional[BaseException] = None):
        self._result, self._error = result, error
        self._done.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("device executor job still running")
        if self._error is not None:
            raise self._error
        return self._result


class DeviceExecutor:
    """Single-owner device-executor queue thread: THE one thread that
    issues device work (fused batches, row gathers), replacing the old
    server-wide ``execute_lock``.

    The lock existed because two threads executing batches concurrently on
    a sharded mesh interleave their collectives' rendezvous and stall for
    seconds — but it also made the server stop-and-wait: unpack/H2D of
    batch N+1 couldn't start until batch N's device work AND D2H finished.
    A single issuing thread gives the same total launch order on every
    device (no interleaving is possible) while pipelining: a batch job is
    DISPATCHED (async, ``ops.oracle.dispatch_batch``) and the executor
    immediately picks up the next job, so the next batch's dispatch —
    and every connection's unpack/pad, which now runs outside the device
    path entirely — overlaps the current batch's device compute.
    Collection happens in dispatch order with an in-flight window of
    ``window`` (default 2: one computing, one being fed).

    DEADLINE semantics are preserved one level up: the per-connection
    worker abandons its wait when the client's budget elapses, and the
    executor still collects the abandoned batch — the device pipeline and
    every QUEUED batch stay intact (the chaos test's invariant).
    """

    def __init__(self, scan_mesh=None, window: int = 2):
        self.scan_mesh = scan_mesh
        self.window = max(1, int(window))
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False
        self._depth = DEFAULT_REGISTRY.gauge(
            "bst_oracle_executor_queue_depth",
            "Batches/rows waiting in the sidecar device-executor queue",
        )
        self._thread = threading.Thread(
            target=self._loop, name="oracle-device-executor", daemon=True
        )
        self._thread.start()

    # -- submission ---------------------------------------------------------

    def _submit(self, job: _ExecJob) -> _ExecJob:
        # refuse after stop: a job enqueued behind the stop sentinel would
        # never be processed and its waiter would block forever (the loop
        # also fails any job that raced past this check — see _loop)
        if self._stopped:
            raise RuntimeError("device executor stopped")
        self._q.put(job)
        self._depth.set(float(self._q.qsize()))
        return job

    def submit_batch(self, batch_args, progress_args, donate=None,
                     tenant=None) -> _ExecJob:
        return self._submit(
            _ExecJob(
                "batch", args=batch_args, progress_args=progress_args,
                donate=donate, tenant=tenant,
            )
        )

    def run_batch(self, batch_args, progress_args, donate=None, tenant=None):
        """Blocking convenience: returns (host, batch, queue_wait_s,
        run_s). The caller's thread (a per-connection worker) may be
        abandoned on deadline — see class docstring. ``donate=False``
        forces non-donating dispatch (device-resident mirror batches);
        ``tenant`` attributes the batch's scan counter
        (bst_scan_batches_total) to the announced wire tenant."""
        job = self.submit_batch(
            batch_args, progress_args, donate=donate, tenant=tenant
        )
        host, batch = job.wait()
        return host, batch, job.queue_wait, job.run_seconds

    def run(self, fn):
        """Execute an arbitrary device closure (row gather) in queue
        order — same total-order guarantee as batches."""
        return self._submit(_ExecJob("call", fn=fn)).wait()

    def stop(self, timeout: float = 60.0) -> bool:
        self._stopped = True
        self._q.put(_EXEC_STOP)
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # -- the executor thread ------------------------------------------------

    def _collect_oldest(self, inflight: deque) -> None:
        from ..utils import tenancy

        job, pending = inflight.popleft()
        # arm the executor thread's dominant-tenant context for the
        # collect-side metric fold (ops.oracle._fold_batch_metrics): the
        # wire tenant the connection announced (TENANT annotation), or
        # the coalescer span's tenant — cleared in the finally so the
        # next job never inherits it
        if job.tenant:
            tenancy.set_batch_tenant(job.tenant)
        try:
            result = collect_batch(pending)
        except BaseException as e:  # noqa: BLE001 — delivered to the waiter
            job.run_seconds = time.perf_counter() - job.enqueued - job.queue_wait
            job.finish(error=e)
            return
        finally:
            if job.tenant:
                tenancy.set_batch_tenant(None)
        job.run_seconds = time.perf_counter() - job.enqueued - job.queue_wait
        job.finish(result=result)

    def _loop(self) -> None:
        inflight: deque = deque()
        while True:
            if inflight:
                # drain the queue opportunistically; with nothing queued,
                # collecting the oldest in-flight batch IS the next job
                try:
                    job = self._q.get_nowait()
                except queue.Empty:
                    self._collect_oldest(inflight)
                    continue
            else:
                job = self._q.get()
            self._depth.set(float(self._q.qsize()))
            if job is _EXEC_STOP:
                while inflight:
                    self._collect_oldest(inflight)
                # fail anything that raced past the _stopped check into
                # the queue: blocked waiters get an error, never a hang
                while True:
                    try:
                        straggler = self._q.get_nowait()
                    except queue.Empty:
                        return
                    if straggler is not _EXEC_STOP:
                        straggler.finish(
                            error=RuntimeError("device executor stopped")
                        )
            if job.kind == "batch":
                while len(inflight) >= self.window:
                    self._collect_oldest(inflight)
                job.queue_wait = time.perf_counter() - job.enqueued
                try:
                    # single-device batches arrive as host numpy (fresh H2D
                    # per dispatch) — safe to donate; sharded args are
                    # pre-placed device arrays, which the donation
                    # contract forbids re-dispatching (docs/pipelining.md).
                    # Jobs dispatched from a device-resident mirror pin
                    # donate=False themselves — donation would consume
                    # the mirror the next delta scatters into.
                    donate = (
                        self.scan_mesh is None
                        if job.donate is None
                        else job.donate
                    )
                    pending = dispatch_batch(
                        job.args, job.progress_args, scan_mesh=self.scan_mesh,
                        donate=donate,
                    )
                except BaseException as e:  # noqa: BLE001 — compile/lowering
                    job.finish(error=e)
                    continue
                inflight.append((job, pending))
            else:
                # row gathers ride the same total order; their data
                # dependency is an ALREADY-DISPATCHED batch, so they
                # complete without waiting out the in-flight window
                job.queue_wait = time.perf_counter() - job.enqueued
                t0 = time.perf_counter()
                try:
                    result = job.fn()
                except BaseException as e:  # noqa: BLE001
                    job.finish(error=e)
                    continue
                job.run_seconds = time.perf_counter() - t0
                job.finish(result=result)


class _ConnWorker:
    """Per-connection daemon worker running request bodies, so the handler
    thread can enforce a client-announced deadline: it waits a bounded
    time and answers a DEADLINE_ERROR frame while the stalled computation
    (e.g. an unwarmed jit compile) keeps running here — its result is
    dropped at delivery, never applied to connection state. Jobs
    serialize per connection, so a request queued behind a stalled one
    spends its own budget waiting, which is the correct signal for a
    wedged device. Daemon thread: a hung job must never block server
    shutdown or interpreter exit."""

    def __init__(self):
        import queue

        self._q = queue.SimpleQueue()
        threading.Thread(
            target=self._loop, name="oracle-conn-worker", daemon=True
        ).start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, slot, done = item
            try:
                slot[:] = [(True, fn())]
            except BaseException as e:  # noqa: BLE001 — re-raised at run()
                slot[:] = [(False, e)]
            done.set()

    def run(self, fn, budget_ms: Optional[int]):
        """Execute ``fn`` on the worker; block up to ``budget_ms`` (None =
        forever). Returns the result, re-raises fn's exception, or returns
        ``_DEADLINE_HIT`` when the budget elapsed first (fn keeps running;
        its outcome is discarded)."""
        slot: list = []
        done = threading.Event()
        self._q.put((fn, slot, done))
        timeout = None if budget_ms is None else max(int(budget_ms), 1) / 1000.0
        if not done.wait(timeout):
            return _DEADLINE_HIT
        ok, value = slot[0]
        if not ok:
            raise value
        return value

    def close(self) -> None:
        self._q.put(None)


class _Handler(socketserver.BaseRequestHandler):
    def _run(self, fn, budget_ms: Optional[int]):
        """Run one request body: inline while the connection has never
        armed a deadline (the common case — the native client never does —
        pays no worker thread and no queue hop), else on the lazily
        created per-connection worker so the budget is enforceable. Once a
        worker exists, ALL subsequent requests route through it, keeping
        them serialized behind any abandoned still-running job instead of
        racing it."""
        if budget_ms is None and self._worker is None:
            return fn()
        if self._worker is None:
            self._worker = _ConnWorker()
        return self._worker.run(fn, budget_ms)

    @staticmethod
    def _note_policy_skew(peer_fp: str) -> None:
        """A client announced its policy-config fingerprint (the
        POLICY_INFO annotation). The sidecar executes BASE batches — it
        has no policy engine unless one was configured process-wide — so
        any fingerprint it does not share means the client's policy scan
        and this server's base scan would produce different plans. Counted,
        never fatal: the client still gets its batch, and the counter (plus
        the client-side audit fingerprints) is the skew evidence."""
        own = None
        try:
            from ..policy.engine import active_fingerprint

            fp = active_fingerprint()
            own = fp["fingerprint"] if fp else None
        except Exception:  # noqa: BLE001 — detection must never drop a batch
            pass
        if peer_fp != (own or ""):
            DEFAULT_REGISTRY.counter(
                "bst_policy_fingerprint_mismatch_total",
                "Schedule requests whose client announced a policy-config "
                "fingerprint this server does not share (the client-side "
                "policy scan and this sidecar's base scan would diverge)",
            ).inc()

    @staticmethod
    def _mk_span(name: str, ts_epoch: float, dur_s: float, trace_ctx, **args):
        """One Chrome-trace span dict for the TRACE_INFO reply, stamped
        with the CLIENT's trace/parent IDs so both sides of the wire
        stitch into a single timeline (utils.trace.record_remote_spans)."""
        trace_id, parent_id = trace_ctx
        a = {"trace_id": trace_id, **args}
        if parent_id:
            a["parent_id"] = parent_id
        return {
            "name": name,
            "cat": "oracle",
            "ts": ts_epoch * 1e6,
            "dur": dur_s * 1e6,
            "args": a,
        }

    def handle(self) -> None:
        deadline_ms: Optional[int] = None  # armed for the NEXT request
        trace_ctx: Optional[tuple] = None  # armed for the NEXT request
        audit_ctx: Optional[str] = None  # armed for the NEXT request
        policy_ctx: Optional[str] = None  # armed for the NEXT request
        self._worker: Optional[_ConnWorker] = None
        # the connection's announced tenant (TENANT annotation): armed for
        # the next request like every annotation, then kept STICKY — a
        # scheduler's tenant identity doesn't change per batch, and the
        # coalescer/capacity attribution wants it on every later request
        self._tenant: Optional[str] = None
        # per-connection batch state (handler instances are per-connection;
        # requests serialize through _run, so these need no lock)
        self._last_batch: Optional[dict] = None
        self._last_counts = (0, 0)
        self._batch_seq = 0
        # device-resident mirror of the client's packed state
        # (ops.device_state), fed by DELTA_SCHEDULE_REQ frames; dies with
        # the connection — a reconnecting client must keyframe, which the
        # DELTA_RESYNC answer forces
        self._mirror = None
        self._mirror_counts = None
        self._batch_seconds = DEFAULT_REGISTRY.histogram(
            "bst_oracle_server_batch_seconds",
            "Sidecar-side wall-clock per schedule batch (unpack + pad + "
            "device), compile stalls included",
            buckets=LONG_OP_BUCKETS,
        )
        self._batches_total = DEFAULT_REGISTRY.counter(
            "bst_oracle_server_batches_total",
            "Schedule batches executed by the sidecar, by traced",
        )
        try:
            while True:
                try:
                    msg_type, payload = proto.read_frame(self.request)
                except (ConnectionError, OSError):
                    return
                except ValueError:
                    return  # not speaking our protocol: drop the connection
                admitted = False
                try:
                    if msg_type == proto.MsgType.DEADLINE:
                        deadline_ms = proto.unpack_deadline(payload)
                        continue  # annotation only; no reply
                    if msg_type == proto.MsgType.TRACE:
                        trace_ctx = proto.unpack_trace(payload)
                        continue  # annotation only; no reply
                    if msg_type == proto.MsgType.AUDIT_ID:
                        audit_ctx = proto.unpack_audit_id(payload)
                        continue  # annotation only; no reply
                    if msg_type == proto.MsgType.POLICY_INFO:
                        policy_ctx = proto.unpack_policy_info(payload)
                        continue  # annotation only; no reply
                    if msg_type == proto.MsgType.TENANT:
                        self._tenant = proto.unpack_tenant(payload)
                        continue  # annotation only; no reply
                    budget_ms, deadline_ms = deadline_ms, None
                    req_trace, trace_ctx = trace_ctx, None
                    req_audit, audit_ctx = audit_ctx, None
                    req_policy, policy_ctx = policy_ctx, None
                    if req_policy is not None:
                        self._note_policy_skew(req_policy)
                    if msg_type in _DRAIN_GATED:
                        # graceful-drain admission gate (docs/resilience.md
                        # "High availability"): once drain() flips the
                        # flag, work-bearing requests get a DRAINING
                        # answer + failover hint instead of execution —
                        # while requests admitted BEFORE the flip finish
                        # inside the in-flight window drain() waits out.
                        # PING stays answered below: liveness is truthful
                        # to the end, and a half-open probe that succeeds
                        # only to see DRAINING next promotes proactively.
                        if not self.server._admit_request():
                            proto.write_frame(
                                self.request, proto.MsgType.DRAINING,
                                proto.pack_draining(
                                    _DRAIN_RETRY_AFTER_MS,
                                    self.server.failover_hint,
                                ),
                            )
                            continue
                        admitted = True
                    if msg_type == proto.MsgType.PING:
                        # answered inline, never through the worker:
                        # liveness must stay observable even while a
                        # stalled batch occupies the worker (the client's
                        # half-open breaker probe depends on it)
                        proto.write_frame(self.request, proto.MsgType.PONG, b"")
                    elif msg_type == proto.MsgType.SCHEDULE_REQ:

                        def run_schedule(payload=payload):
                            # phase timings double as the TRACE_INFO span
                            # source and the server metric observations —
                            # epoch stamps so client+server spans share a
                            # clock domain in the stitched timeline
                            ts0 = time.time()
                            t0 = time.perf_counter()
                            req = proto.unpack_schedule_request(payload)
                            args, progress_args, (n, g) = _pad_request(req)
                            mesh = self.server.scan_mesh
                            warmer = self.server.warmer
                            coal = self.server.coalescer
                            if coal is not None and mesh is None:
                                # multi-tenant coalescing (service.
                                # coalescer): the padded batch joins the
                                # DRF merge queue instead of going to the
                                # executor directly; the demuxed result
                                # is bit-identical to this direct path
                                t1 = time.perf_counter()
                                job = CoalesceJob(
                                    tenant=self._tenant or "",
                                    n=n, g=g,
                                    r=int(req.alloc.shape[1]),
                                    padded_args=args,
                                    progress_args=progress_args,
                                    raw_fn=lambda req=req: (
                                        req.alloc, req.requested,
                                        req.group_req, req.remaining,
                                        req.fit_mask, req.group_valid,
                                        req.order, req.min_member,
                                        req.scheduled, req.matched,
                                        req.ineligible, req.creation_rank,
                                    ),
                                    want_audit=(
                                        self.server.audit_log is not None
                                    ),
                                )
                                res = coal.schedule(job)
                                if warmer is not None:
                                    try:
                                        # the span lowering dispatches
                                        # these padded args donating
                                        # (executor default), so warm
                                        # the same variant the fallback/
                                        # span path serves with
                                        warmer.note_batch(
                                            args, progress_args,
                                            res.host.get("telemetry")
                                            or {},
                                            donate=True,
                                        )
                                    except Exception:  # noqa: BLE001
                                        pass
                                if req_trace is not None:
                                    _maybe_server_capacity(
                                        args, progress_args, res.host,
                                        tenant=self._tenant, g=g,
                                    )
                                timings = {
                                    "ts0": ts0,
                                    "unpack_pad": t1 - t0,
                                    "lock_wait": res.queue_wait,
                                    "device": res.run_seconds,
                                }
                                return (
                                    res.host, res.rows, (n, g), timings,
                                    res.audit_args,
                                )
                            # host-side padded args, captured BEFORE mesh
                            # placement: the audit record must replay on
                            # any backend, so it keeps plain numpy
                            audit_args = (
                                (args, progress_args)
                                if self.server.audit_log is not None
                                else None
                            )
                            if mesh is not None:
                                from ..ops.oracle import scan_sharded_active
                                from ..parallel.mesh import shard_snapshot_args

                                # layout must match the rung dispatch will
                                # pick: the sharded scan wants the node
                                # axis split over EVERY device end-to-end,
                                # or GSPMD reshards the [N,R] lanes at the
                                # shard_map boundary
                                args = shard_snapshot_args(
                                    mesh, args,
                                    flat_nodes=scan_sharded_active(),
                                )
                            t1 = time.perf_counter()
                            # All device work goes through the single-owner
                            # executor queue (DeviceExecutor): one issuing
                            # thread keeps mesh collectives un-interleaved
                            # (the guarantee the old execute_lock bought)
                            # while the executor overlaps this batch's
                            # device compute with the NEXT batch's dispatch
                            # — and this unpack/pad above already ran
                            # outside the device path, concurrent across
                            # connections.
                            host, batch, queue_wait, run_s = (
                                self.server.executor.run_batch(
                                    args, progress_args,
                                    tenant=self._tenant,
                                )
                            )
                            if warmer is not None:
                                try:
                                    # donate mirrors the executor's
                                    # dispatch, so the warmer warms the
                                    # SAME jit variant serving traffic hits
                                    warmer.note_batch(
                                        args, progress_args,
                                        host.get("telemetry") or {},
                                        donate=mesh is None,
                                    )
                                except Exception:  # noqa: BLE001 — warm-only
                                    pass
                            if req_trace is not None and mesh is None:
                                # sidecar capacity sample for the traced
                                # client (budget-gated; rides TRACE_INFO),
                                # attributed to the announced wire tenant
                                _maybe_server_capacity(
                                    args, progress_args, host,
                                    tenant=self._tenant, g=g,
                                )
                            timings = {
                                "ts0": ts0,
                                "unpack_pad": t1 - t0,
                                # span name kept for trace-schema stability:
                                # with the executor this is QUEUE wait
                                "lock_wait": queue_wait,
                                "device": run_s,
                            }
                            return host, batch, (n, g), timings, audit_args

                        # a full request supersedes any delta mirror: the
                        # client's cursor keyframes after a fallback, and a
                        # stale mirror would only pin device memory
                        self._mirror = None
                        self._mirror_counts = None
                        try:
                            outcome = self._run(run_schedule, budget_ms)
                        except CoalesceSaturated as e:
                            # admission control: bounded coalescer queue
                            # full — an in-band BUSY with the retry-after
                            # hint, never a dropped or hanging request
                            proto.write_frame(
                                self.request, proto.MsgType.BUSY,
                                proto.pack_busy(e.retry_after_ms, str(e)),
                            )
                            continue
                        if outcome is _DEADLINE_HIT:
                            proto.write_frame(
                                self.request,
                                proto.MsgType.DEADLINE_ERROR,
                                f"schedule exceeded deadline of {budget_ms}ms".encode(),
                            )
                            continue
                        self._finish_schedule(outcome, req_trace, req_audit)
                    elif msg_type == proto.MsgType.DELTA_SCHEDULE_REQ:

                        def run_delta(payload=payload):
                            return self._run_delta_body(
                                payload, traced=req_trace is not None
                            )

                        try:
                            outcome = self._run(run_delta, budget_ms)
                        except CoalesceSaturated as e:
                            # _run_delta_body checks admission BEFORE
                            # touching the mirror, so the common refusal
                            # leaves the client's cursor valid for a
                            # plain retry. The rare race (queue filled
                            # between the check and the submit, mirror
                            # already advanced) still converges: the
                            # retried delta's base mismatches, the
                            # server answers DELTA_RESYNC, and the
                            # client keyframes — correct, one extra
                            # round-trip.
                            proto.write_frame(
                                self.request, proto.MsgType.BUSY,
                                proto.pack_busy(e.retry_after_ms, str(e)),
                            )
                            continue
                        if outcome is _DEADLINE_HIT:
                            # the abandoned job may still advance the
                            # mirror generation; the client resets its
                            # cursor on any error and keyframes next, so
                            # no stale-row window opens
                            proto.write_frame(
                                self.request,
                                proto.MsgType.DEADLINE_ERROR,
                                f"schedule exceeded deadline of {budget_ms}ms".encode(),
                            )
                            continue
                        if isinstance(outcome, _ResyncNeeded):
                            DEFAULT_REGISTRY.counter(
                                "bst_device_delta_resyncs_total",
                                "Wire deltas the sidecar mirror refused "
                                "(generation gap / no state / shape "
                                "mismatch) — the client resends a keyframe",
                            ).inc()
                            proto.write_frame(
                                self.request,
                                proto.MsgType.DELTA_RESYNC,
                                proto.pack_delta_resync(outcome.reason),
                            )
                            continue
                        self._finish_schedule(outcome, req_trace, req_audit)
                    elif msg_type == proto.MsgType.ROW_REQ:
                        kind, gidx, req_seq = proto.unpack_row_request(payload)
                        if self._last_batch is None:
                            raise ValueError("row request before any batch")
                        if req_seq != self._batch_seq:
                            raise ValueError(
                                f"stale batch: row for seq {req_seq}, current {self._batch_seq}"
                            )
                        n, g = self._last_counts
                        if not 0 <= gidx < g:
                            raise ValueError(f"row index {gidx} out of range {g}")
                        batch = self._last_batch

                        def run_row(batch=batch, kind=kind, gidx=gidx, n=n):
                            # issued by the executor thread, in the same
                            # total order as batch dispatches: on a
                            # sharded mesh, device_get of a sharded (G,N)
                            # tensor launches its own cross-device gather,
                            # and one interleaving with a concurrent
                            # batch's collectives deadlocks the rendezvous
                            # (seen as a 2-minute stall in the dual-
                            # connection background-refresh test)
                            if hasattr(batch, "gather"):
                                # coalesced batch: the row view owns the
                                # span slicing AND the executor hop
                                return batch.gather(kind, gidx)

                            def gather():
                                return np.asarray(
                                    jax.device_get(batch[kind][gidx])
                                ).astype("<i4")[:n]

                            return self.server.executor.run(gather)

                        outcome = self._run(run_row, budget_ms)
                        if outcome is _DEADLINE_HIT:
                            proto.write_frame(
                                self.request,
                                proto.MsgType.DEADLINE_ERROR,
                                f"row fetch exceeded deadline of {budget_ms}ms".encode(),
                            )
                            continue
                        proto.write_frame(
                            self.request, proto.MsgType.ROW_RESP, outcome.tobytes()
                        )
                    else:
                        raise ValueError(f"unknown message type {msg_type}")
                except Exception as e:  # protocol errors answer in-band
                    try:
                        proto.write_frame(
                            self.request, proto.MsgType.ERROR, str(e).encode()
                        )
                    except OSError:
                        return
                finally:
                    # every admitted request retires exactly once (the
                    # annotation/BUSY/DEADLINE `continue`s above still
                    # pass through here) — drain() waits on this count
                    if admitted:
                        self.server._request_done()
        finally:
            if self._worker is not None:
                self._worker.close()


    def _finish_schedule(self, outcome, req_trace, req_audit) -> None:
        """Shared tail of the full and delta schedule paths: install
        the batch as connection state, record the sidecar-side audit
        evidence, emit metrics/spans/TRACE_INFO, and answer the
        SCHEDULE_RESP in the client's node space."""
        host, batch, (n, g), timings, audit_args = outcome
        self._last_batch = batch
        self._last_counts = (n, g)
        self._batch_seq += 1
        if audit_args is not None:
            # sidecar-side audit record, stamped with the
            # CLIENT's audit ID (the AUDIT_ID annotation)
            # so both sides' records of this batch join
            # one evidence chain; enqueue only — the
            # daemon writer owns serialization and disk
            try:
                from ..utils import audit as audit_mod

                self.server.audit_log.record_batch(
                    batch_args=audit_args[0],
                    progress_args=audit_args[1],
                    result=host,
                    plan_digest=audit_mod.plan_digest(host),
                    audit_id=req_audit,
                    trace_id=(
                        req_trace[0] if req_trace else None
                    ),
                    telemetry=host.get("telemetry") or {},
                    extra={
                        "side": "server",
                        "batch_seq": self._batch_seq,
                        "n": n,
                        "g": g,
                    },
                )
            except Exception:  # noqa: BLE001 — evidence only
                pass
        total_s = (
            timings["unpack_pad"]
            + timings["lock_wait"]
            + timings["device"]
        )
        self._batch_seconds.observe(total_s)
        self._batches_total.inc(
            traced="yes" if req_trace else "no"
        )
        if req_trace is not None:
            telemetry = dict(host.get("telemetry") or {})
            telemetry.update(
                device_seconds=round(timings["device"], 6),
                lock_wait_seconds=round(
                    timings["lock_wait"], 6
                ),
                unpack_pad_seconds=round(
                    timings["unpack_pad"], 6
                ),
                batch_seq=self._batch_seq,
                n=n,
                g=g,
                # pipelining evidence (docs/pipelining.md):
                # in-flight depth at collect time and the
                # warmer's absorption counters ride back to
                # the client with the device telemetry
                inflight_batches=int(
                    DEFAULT_REGISTRY.gauge(
                        "bst_oracle_inflight_batches"
                    ).value()
                ),
            )
            if telemetry.get("waves_per_batch"):
                # per-wave merge cost: on the sharded scan
                # rung this is the tree-reduce cadence the
                # collective budget is written against
                # (docs/scan_parallelism.md)
                telemetry["per_wave_device_seconds"] = round(
                    timings["device"]
                    / telemetry["waves_per_batch"],
                    6,
                )
            if req_audit is not None:
                telemetry["audit_id"] = req_audit
            if self.server.warmer is not None:
                telemetry.update(
                    self.server.warmer.stats()
                )
            # sidecar HBM + compile-ledger evidence rides
            # back with the device telemetry: the client
            # (whose own process has no accelerator) sees
            # the server's memory watermarks and cold-
            # compile count per traced batch
            # (docs/observability.md "Device profiling")
            try:
                from ..utils import profiler as prof_mod

                mem = prof_mod.sample_device_memory()
                if mem is not None:
                    telemetry["device_memory"] = mem
                ledger_n = (
                    prof_mod.COMPILE_LEDGER.entry_count()
                )
                if ledger_n:
                    telemetry["compile_ledger_entries"] = (
                        ledger_n
                    )
            except Exception:  # noqa: BLE001 — telemetry
                pass
            ts0 = timings["ts0"]
            spans = [
                self._mk_span(
                    "oracle.schedule", ts0, total_s,
                    req_trace, n=n, g=g,
                ),
                self._mk_span(
                    "oracle.unpack_pad", ts0,
                    timings["unpack_pad"], req_trace,
                ),
                self._mk_span(
                    "oracle.lock_wait",
                    ts0 + timings["unpack_pad"],
                    timings["lock_wait"], req_trace,
                ),
                self._mk_span(
                    "oracle.device_batch",
                    ts0 + timings["unpack_pad"]
                    + timings["lock_wait"],
                    timings["device"], req_trace,
                    compiled=telemetry.get("compiled"),
                ),
            ]
            if trace_mod.enabled():
                # server-side local ring (serve --trace):
                # the same spans land in this process's
                # /debug/trace too
                trace_mod.record_remote_spans(
                    spans, pid="oracle-server"
                )
            proto.write_frame(
                self.request,
                proto.MsgType.TRACE_INFO,
                proto.pack_trace_info(
                    req_trace[0], spans, telemetry
                ),
            )
        # Map assignment node indexes back into the
        # CLIENT's node space before packing: the batch ran
        # in the server's bucket-padded (and, on a mesh,
        # shard-placed) node space, whose first n indexes
        # are the client's nodes and whose tail is padding.
        # Real takes can only land on the first n (pad
        # nodes are masked, zero-capacity), but top_k
        # backfills zero-count rows with arbitrary pad
        # indexes — zero those out so a client stamping a
        # whole-gang plan never sees an out-of-space index
        # (the PR-1 multi-device empty-plan bug; see
        # docs/scan_parallelism.md).
        a_nodes = np.asarray(host["assignment_nodes"])[:g]
        a_counts = np.asarray(host["assignment_counts"])[:g]
        in_space = a_nodes < n
        a_nodes = np.where(in_space, a_nodes, 0)
        a_counts = np.where(in_space, a_counts, 0)
        resp = proto.ScheduleResponse(
            gang_feasible=np.asarray(host["gang_feasible"])[:g],
            placed=np.asarray(host["placed"])[:g],
            progress=np.asarray(host["progress"])[:g],
            best=int(host["best"]),
            best_exists=bool(host["best_exists"]),
            assignment_nodes=a_nodes,
            assignment_counts=a_counts,
            batch_seq=self._batch_seq,
        )
        proto.write_frame(
            self.request,
            proto.MsgType.SCHEDULE_RESP,
            proto.pack_schedule_response(resp),
        )

    def _run_delta_body(self, payload: bytes, traced: bool = False):
        """One DELTA_SCHEDULE_REQ: bring the connection's device-resident
        mirror (ops.device_state.DeviceStateHolder) up to the client's
        generation — scatter-applying churned rows, or installing a full
        keyframe — then dispatch the batch FROM the resident buffers
        (donate=False: donation would consume the mirror). Returns the
        same outcome tuple as the full path so ``_finish_schedule`` is
        shared, or a ``_ResyncNeeded`` when the mirror cannot apply the
        delta (generation gap / no state / shape mismatch) — the client
        must resend a keyframe, never have stale rows scored silently."""
        ts0 = time.time()
        t0 = time.perf_counter()
        kind, base_gen, new_gen, body = proto.unpack_delta_schedule_request(
            payload
        )
        mesh = self.server.scan_mesh
        executor = self.server.executor
        coal = self.server.coalescer if mesh is None else None
        if coal is not None:
            # refuse BEFORE the mirror apply below, so a BUSY answer
            # leaves the client's generation cursor valid (see the
            # BUSY handler's race note)
            coal.check_admission()
        if self._mirror is None:
            from ..ops.device_state import DeviceStateHolder

            self._mirror = DeviceStateHolder(mesh=mesh, label="server")
        holder = self._mirror
        want_audit = self.server.audit_log is not None
        audit_args = None
        if kind == proto.DELTA_KEYFRAME:
            args, progress_args, (n, g) = _pad_request(body)
            if want_audit:
                audit_args = (args, progress_args)
            # placement is device work: it rides the executor queue so it
            # can never interleave with a mesh batch's collectives
            device_args = executor.run(
                lambda: holder.keyframe(args, new_gen, "wire-keyframe")
            )
            self._mirror_counts = (n, g, int(body.alloc.shape[1]))
        else:
            n, g = body.n, body.g
            if self._mirror_counts != (n, g, body.r):
                return _ResyncNeeded(
                    f"shape mismatch: mirror {self._mirror_counts}, "
                    f"delta ({n}, {g}, {body.r})"
                )
            small_args, progress_args = _pad_delta_request(body)

            def apply():
                return holder.apply_rows(
                    base_gen,
                    new_gen,
                    (body.node_idx, body.node_rows),
                    (body.group_idx, body.group_rows),
                    small_args,
                )

            device_args = executor.run(apply)
            if device_args is None:
                return _ResyncNeeded(
                    f"generation gap: mirror at "
                    f"{holder.current_generation()}, delta base {base_gen}"
                )
            if want_audit:
                # the audit record must replay on any backend: read the
                # delta-applied lane buffers back to host numpy (evidence
                # cost, paid only when the sidecar runs --audit-dir)
                audit_args = (
                    tuple(np.asarray(a) for a in device_args), progress_args
                )
        t1 = time.perf_counter()
        if coal is not None:
            # the mirror is synced; the batch itself joins the DRF merge
            # queue like a full request. donate=False is load-bearing —
            # a donated span dispatch would consume the mirror.
            n_real, g_real, r_real = n, g, self._mirror_counts[2]

            def raw_fn(device_args=device_args,
                       progress_args=progress_args, n=n_real, g=g_real):
                al, rq, gr, rem, fm, gv, od = (
                    np.asarray(a) for a in device_args
                )
                mm, sc, mt, inel, cr = (
                    np.asarray(a) for a in progress_args
                )
                mask = fm[:1, :n] if fm.shape[0] == 1 else fm[:g, :n]
                return (
                    al[:n], rq[:n], gr[:g], rem[:g], mask, gv[:g],
                    od[:g], mm[:g], sc[:g], mt[:g], inel[:g], cr[:g],
                )

            job = CoalesceJob(
                tenant=self._tenant or "", n=n_real, g=g_real, r=r_real,
                padded_args=device_args, progress_args=progress_args,
                raw_fn=raw_fn, donate=False, want_audit=want_audit,
            )
            res = coal.schedule(job)
            host, batch = res.host, res.rows
            queue_wait, run_s = res.queue_wait, res.run_seconds
            if want_audit and audit_args is None:
                audit_args = res.audit_args
        else:
            host, batch, queue_wait, run_s = executor.run_batch(
                device_args, progress_args, donate=False,
                tenant=self._tenant,
            )
        telemetry = host.get("telemetry")
        if isinstance(telemetry, dict):
            telemetry["device_state"] = {
                "generation": holder.current_generation(),
                "applied": "keyframe" if kind == proto.DELTA_KEYFRAME
                else "delta",
                "rows": int(
                    len(body.node_idx) + len(body.group_idx)
                ) if kind == proto.DELTA_ROWS else 0,
            }
        if traced and mesh is None:
            # capacity over the MIRROR's resident buffers — the sidecar's
            # own view of the cluster it is scoring (rides TRACE_INFO),
            # attributed to the announced wire tenant
            _maybe_server_capacity(
                device_args, progress_args, host,
                tenant=self._tenant, g=g,
            )
        timings = {
            "ts0": ts0,
            "unpack_pad": t1 - t0,
            "lock_wait": queue_wait,
            "device": run_s,
        }
        return host, batch, (n, g), timings, audit_args


class OracleServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        compile_warmer: bool = False,
        audit_log=None,
        coalesce: Optional[bool] = None,
    ):
        super().__init__((host, port), _Handler)
        # sidecar-side batch audit ring (utils.audit): every executed
        # batch's padded inputs + plan digest, correlated with the
        # client's records via the AUDIT_ID annotation
        self.audit_log = audit_log
        # Multi-chip deployments (v5e-4 DP config of BASELINE, or a full
        # slice after init_distributed) shard batches over the global mesh
        # with the replicated-scan layout; one chip stays single-device.
        import jax

        from ..parallel.distributed import global_mesh

        self.scan_mesh = global_mesh() if len(jax.devices()) > 1 else None
        # the single-owner device pipeline (replaces the PR-1 server-wide
        # execute_lock; see DeviceExecutor)
        self.executor = DeviceExecutor(scan_mesh=self.scan_mesh)
        # multi-tenant cross-client coalescer (service.coalescer,
        # docs/multitenancy.md): DRF-fair merge queue in front of the
        # executor. Single-device only — a mesh batch's shard placement
        # happens per connection BEFORE the executor, and a merged
        # mega-batch would reshard under it; the mesh deployment keeps
        # the direct path (its executor already serializes launches).
        want_coalesce = (
            coalesce_enabled() if coalesce is None else bool(coalesce)
        )
        self.coalescer = None
        if want_coalesce and self.scan_mesh is None:
            self.coalescer = OracleCoalescer(
                self.executor, weights_fn=_capacity_tenant_shares
            )
        elif want_coalesce:
            import sys

            print(
                "coalescer skipped: mesh server (shard placement happens "
                "per connection; merged batches would reshard)",
                file=sys.stderr,
            )
        self.warmer = None
        if compile_warmer:
            from ..ops.bucketing import maybe_compile_warmer

            self.warmer = maybe_compile_warmer(self.scan_mesh)
        # graceful drain (docs/resilience.md "High availability"):
        # _admit_request/_request_done bracket every work-bearing request
        # so drain() can wait out the admitted in-flight window before
        # flushing; failover_hint rides in every DRAINING answer so even
        # clients configured with a single address learn where the
        # standby lives (BST_FAILOVER_HINT, or drain(failover_hint=...))
        self._draining = False
        self._inflight_requests = 0
        self._inflight_lock = threading.Lock()
        self._drain_done = threading.Event()
        self._drain_report: dict = {}
        try:
            self.failover_hint = os.environ.get("BST_FAILOVER_HINT", "") or ""
        except Exception:  # noqa: BLE001 — hint is advisory
            self.failover_hint = ""
        self._draining_gauge = DEFAULT_REGISTRY.gauge(
            "bst_server_draining",
            "1 while the sidecar refuses new work with DRAINING answers "
            "(SIGTERM / /debug/drain received), else 0",
        )
        self._draining_gauge.set(0, addr=f"{host}:{self.server_address[1]}")
        self._gauge_addr = f"{host}:{self.server_address[1]}"
        _LIVE_SERVERS.add(self)

    @property
    def address(self):
        return self.server_address

    def draining(self) -> bool:
        with self._inflight_lock:
            return self._draining

    def _admit_request(self) -> bool:
        """Admission bracket for one work-bearing request (the handler's
        drain gate). False once drain() flipped the flag — the handler
        answers DRAINING instead of executing."""
        with self._inflight_lock:
            if self._draining:
                return False
            self._inflight_requests += 1
            return True

    def _request_done(self) -> None:
        with self._inflight_lock:
            self._inflight_requests -= 1

    def drain(
        self,
        timeout: Optional[float] = None,
        failover_hint: Optional[str] = None,
    ) -> dict:
        """Graceful drain: stop admitting, finish the in-flight window,
        flush everything durable, report. Subsequent work requests get
        DRAINING + the failover hint; PING and annotations still flow.

        Flush order is the producer-before-join shutdown discipline:
        warmer stop (its precompiles spawn telemetry threads), coalescer
        stop (an executor producer), executor drain, telemetry-thread
        join, and the audit flush LAST — every producer retired before
        its consumer, so nothing lands after its ledger closed.

        Idempotent: concurrent callers wait on the first drain and get
        its report. ``timeout`` bounds the in-flight wait only
        (default BST_DRAIN_TIMEOUT_S, 30s); flush steps keep their own
        bounded budgets. Does NOT close the listener — the caller (the
        SIGTERM path in cmd.main, or /debug/drain followed by an
        operator stop) decides when the refusing-but-alive phase ends.
        """
        if timeout is None:
            timeout = _drain_timeout_s()
        if failover_hint is not None:
            self.failover_hint = failover_hint
        with self._inflight_lock:
            first = not self._draining
            self._draining = True
        self._draining_gauge.set(1, addr=self._gauge_addr)
        if not first:
            self._drain_done.wait(max(1.0, float(timeout)) + 60.0)
            return dict(self._drain_report)
        t0 = time.monotonic()
        deadline = t0 + max(0.0, float(timeout))
        while True:
            with self._inflight_lock:
                inflight = self._inflight_requests
            if inflight <= 0 or time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        if self.warmer is not None:
            self.warmer.stop(timeout=10.0)
        if self.coalescer is not None:
            self.coalescer.stop(timeout=10.0)
        self.executor.stop(timeout=10.0)
        from ..ops.oracle import drain_telemetry_threads

        telemetry_ok = bool(drain_telemetry_threads(timeout=30.0))
        audit_ok = True
        if self.audit_log is not None:
            try:
                self.audit_log.flush(timeout=30.0)
            except Exception:  # noqa: BLE001 — report, don't abort exit
                audit_ok = False
        self._drain_report = {
            "drained": inflight <= 0,
            "inflight_at_flush": inflight,
            "wait_s": round(time.monotonic() - t0, 3),
            "telemetry_joined": telemetry_ok,
            "audit_flushed": audit_ok,
            "failover_hint": self.failover_hint,
        }
        self._drain_done.set()
        return dict(self._drain_report)

    def warmth_snapshot(self) -> list:
        """The compile warmer's observed bucket-shape prototypes —
        the primary side of warmth replication (standby HA)."""
        if self.warmer is None:
            return []
        return self.warmer.warmth_snapshot()

    def replicate_warmth(self, protos) -> int:
        """Feed another sidecar's observed shapes into this server's
        warmer so promotion pays no cold compile; returns how many
        prototypes were enqueued (0 with no warmer)."""
        if self.warmer is None or not protos:
            return 0
        return self.warmer.replicate(protos)

    def server_close(self) -> None:
        try:
            # warmer first (its precompiles spawn bucket-cost telemetry
            # threads), then the executor, then the telemetry-thread
            # join — the same producer-before-join shutdown ordering as
            # OracleScorer.drain_background (exit-abort fix)
            if self.warmer is not None:
                self.warmer.stop(timeout=10.0)
            # coalescer before executor: it is an executor PRODUCER, and
            # a group dispatched after executor.stop would hang its
            # waiters (the producer-before-join shutdown ordering)
            if self.coalescer is not None:
                self.coalescer.stop(timeout=10.0)
            self.executor.stop(timeout=10.0)
            if self.audit_log is not None:
                self.audit_log.stop(timeout=10.0)
            from ..ops.oracle import drain_telemetry_threads

            # escalating patience, like plugin factory shutdown: a
            # telemetry thread may be inside a 20-40s accelerator
            # compile, and a timed-out join means teardown would still
            # race the XLA call
            for timeout in (60.0, 120.0):
                if drain_telemetry_threads(timeout=timeout):
                    break
            else:
                import sys

                print(
                    "server_close: telemetry compile thread still live "
                    "after drain; teardown may race an XLA call",
                    file=sys.stderr,
                )
        finally:
            super().server_close()


def serve_background(
    host: str = "127.0.0.1", port: int = 0, compile_warmer: bool = False,
    audit_log=None, coalesce: Optional[bool] = None,
) -> OracleServer:
    """Start an OracleServer on a daemon thread; returns it (``.address``
    has the bound port, ``.shutdown()`` stops it)."""
    server = OracleServer(
        host, port, compile_warmer=compile_warmer, audit_log=audit_log,
        coalesce=coalesce,
    )
    t = threading.Thread(
        target=server.serve_forever, name="oracle-server", daemon=True
    )
    t.start()
    return server
