from .pg_cache import PGStatusCache, PodGroupMatchStatus, PodNodePair

__all__ = ["PGStatusCache", "PodGroupMatchStatus", "PodNodePair"]
