"""In-memory PodGroup scheduling-state cache.

Equivalent of the reference's ``pkg/scheduler/cache``
(reference pkg/scheduler/cache/cache.go:30-116): a thread-safe map from
PodGroup full name to its live match status, where per-group TTL caches hold
the permitted-but-unbound pod→node pairs. TTL expiry of the pod-name→UID
cache is the gang timeout signal (see controller wiring).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..api.types import Pod, PodGroup
from ..utils.ttl_cache import TTLCache

__all__ = ["PodNodePair", "PodGroupMatchStatus", "PGStatusCache"]

# go-cache defaults used by the reference when building per-group caches
# (reference pkg/scheduler/controller/controller.go:317-318).
DEFAULT_MATCH_TTL = 60.0
DEFAULT_JANITOR_INTERVAL = 2.0


@dataclass
class PodNodePair:
    """A permitted pod and its chosen node
    (reference pkg/scheduler/cache/cache.go:70-73)."""

    pod_name: str  # "namespace/name"
    node: str


class PodGroupMatchStatus:
    """Live gang bookkeeping for one PodGroup
    (reference pkg/scheduler/cache/cache.go:52-67)."""

    def __init__(
        self,
        pod_group: PodGroup,
        match_ttl: float = DEFAULT_MATCH_TTL,
        janitor_interval: float = DEFAULT_JANITOR_INTERVAL,
        clock=None,
    ):
        kwargs = {} if clock is None else {"clock": clock}
        self.pod_group = pod_group
        # permitted pod UID -> PodNodePair, TTL = gang wait time
        self.matched_pod_nodes = TTLCache(match_ttl, janitor_interval, **kwargs)
        # "namespace/podName" -> pod UID, TTL = gang wait time; its expiry
        # callback is the gang abort trigger.
        self.pod_name_uids = TTLCache(match_ttl, janitor_interval, **kwargs)
        self.failed: Dict[str, str] = {}
        self.succeed: Dict[str, str] = {}
        self.count_lock = threading.RLock()
        # A representative member pod; fixes the group's per-member resource
        # shape when spec.min_resources is unset (reference core.go:486-493).
        self.pod: Optional[Pod] = None
        # True once the gang has been released to bind at least once.
        self.scheduled = False
        # Binds THIS scheduler committed (PostBind-side counter). The
        # status.scheduled field has two monotone lower-bound sources —
        # this counter and the controller's live member count — and
        # PostBind takes max(status.scheduled, binds_committed) instead of
        # blind addition, so the two writers commute: a controller count
        # that already includes a bind this counter later accounts cannot
        # double it (and vice versa for binds whose API responses were
        # lost, which only the controller ever sees).
        self.binds_committed = 0
        # Gang-granular admission plan (no reference equivalent — it admits
        # gangs pod by pod against a TTL cache, core.go:268-309): the oracle
        # batch that places this gang stamps its node->member-count plan
        # here, and member pods ride pre_filter/permit/select off the plan
        # without re-running the batch per pod. ``plan_base_matched`` is the
        # matched-per-node counter at stamp time: slots consumed on a node =
        # current matched there minus the base, so evicted/rejected permits
        # automatically re-open their slots.
        self.placement_plan: Optional[Dict[str, int]] = None
        self.plan_base_matched: Dict[str, int] = {}
        self.plan_batch_seq: int = -1

    def close(self) -> None:
        self.matched_pod_nodes.close()
        self.pod_name_uids.close()


class PGStatusCache:
    """Thread-safe full-name -> PodGroupMatchStatus map
    (reference pkg/scheduler/cache/cache.go:45-116)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._map: Dict[str, PodGroupMatchStatus] = {}  # guarded-by: _lock
        # monotone set/delete counter: the scorer's event-fold compares it
        # across refreshes to prove the GROUP SET could not have changed
        # without an event (a silently added/removed entry would otherwise
        # let a targeted fold serve a wrong-group-set snapshot)
        self._mutations = 0  # guarded-by: _lock
        # registration-time list; delete() iterates it OUTSIDE the lock on
        # purpose (callbacks may re-enter this cache)
        self._on_delete: list = []

    def mutations(self) -> int:
        """Monotone count of set/delete calls (membership churn proxy)."""
        with self._lock:
            return self._mutations

    def on_delete(self, fn: Callable[[str], None]) -> None:
        """Register a callback fired (outside the lock) with the full name
        of every entry removed — lets per-group derived caches (e.g. the
        queue sort key's creation-timestamp cache) die with the group, so
        a name reused by a recreated group never serves stale values."""
        self._on_delete.append(fn)

    def get(self, full_name: str) -> Optional[PodGroupMatchStatus]:
        with self._lock:
            return self._map.get(full_name)

    def set(self, full_name: str, status: PodGroupMatchStatus) -> None:
        with self._lock:
            self._map[full_name] = status
            self._mutations += 1

    def delete(self, full_name: str) -> None:
        with self._lock:
            status = self._map.pop(full_name, None)
            self._mutations += 1
        if status is not None:
            status.close()
        for fn in self._on_delete:
            fn(full_name)

    def snapshot(self) -> Dict[str, PodGroupMatchStatus]:
        """Consistent point-in-time view for batch scoring."""
        with self._lock:
            return dict(self._map)

    def for_each(self, fn: Callable[[str, PodGroupMatchStatus], None]) -> None:
        for name, status in self.snapshot().items():
            fn(name, status)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)
