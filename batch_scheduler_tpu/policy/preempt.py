"""Vectorized preemption: the masked second scan that answers "which
victim set frees enough capacity for this denied gang, minimizing
preempted pods" as one jitted device pass (docs/policy.md "Preemption
pass").

The host-side ``Scheduler._try_preempt`` loop walks nodes × pods in
Python — fine for one online pod, hopeless for gang-scale preemption
where the denied unit needs capacity across many nodes at once. Here the
victim search is two ``lax.scan`` passes over packed victim rows:

1. **Greedy pass** — victims ordered (priority asc, pods asc — evict the
   cheapest, lowest tier first; the host computes the order, the device
   consumes it) are taken whole-gang (gang semantics: evicting ANY member
   breaks the victim's quorum, so the correct eviction unit is the gang)
   until the preemptor's pooled need-clipped capacity covers its need.
2. **Reprieve pass** — in reverse order (most expensive first), any taken
   victim whose removal still leaves the preemptor covered is given back.

The surviving set is inclusion-minimal BY CONSTRUCTION: after the
reprieve, removing any single victim drops pooled capacity below the
need (asserted property-style in tests/test_policy.py). The plan is a
DRY RUN — the control plane re-verifies it host-side against live
cluster state and applies it through the existing preempt hooks
(framework.scheduler) before any eviction happens.

Tier rule enforced on device: a victim is eligible only when its priority
class is STRICTLY below the preemptor's (never equal-or-higher — the
first policy invariant).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["plan_victims", "PreemptionPlanner", "VictimPlan"]

_BIG = 2**30

# Victim-gang bucket sizes (power-of-two jit signatures, min 8) and a hard
# cap: a preemption pass considering more than 512 victim gangs is a sign
# the cluster is misconfigured, not a planning problem.
_V_MIN, _V_MAX = 8, 512


def _v_bucket(v: int) -> int:
    b = _V_MIN
    while b < v and b < _V_MAX:
        b <<= 1
    return b


def _capacity(left, req):
    """Members of demand row ``req`` fitting each leftover row of
    ``left`` [..., R]. Plain int32 division — the planner is off the
    batch hot path and its answer is re-verified host-side, so it does
    not share the oracle's _exact_floordiv bit-discipline."""
    safe = jnp.maximum(req, 1)
    lpos = jnp.clip(left, 0, _BIG)
    per_lane = jnp.where(req > 0, lpos // safe, _BIG)
    return jnp.min(per_lane, axis=-1).astype(jnp.int32)


@jax.jit
def plan_victims(left, fit_row, req, need, prio, valloc, vreq, vprio,
                 vvalid, vorder):
    """One preemption plan on packed buffers.

    - ``left[N, R]``    live leftover lanes (post current accounting)
    - ``fit_row[N]``    0/1 nodes the preemptor may use at all
    - ``req[R]``        preemptor per-member demand row
    - ``need``          members still requiring seats (scalar)
    - ``prio``          preemptor priority class (scalar)
    - ``valloc[V, N]``  victim members per node
    - ``vreq[V, R]``    victim per-member demand rows
    - ``vprio[V]``      victim priority classes
    - ``vvalid[V]``     0/1 real victim rows (padding = 0)
    - ``vorder[V]``     host-computed greedy order (priority asc, pods asc)

    Returns ``(taken[V] bool, feasible bool, pooled_after int32)`` where
    ``taken`` marks the inclusion-minimal victim set and ``feasible``
    says the set covers the need (False = even evicting every eligible
    victim cannot seat the gang — no plan).
    """
    eligible = (vvalid > 0) & (vprio < prio)  # never equal-or-higher tier

    def pooled(left_c):
        cap = _capacity(left_c, req[None, :]) * fit_row
        return jnp.sum(jnp.minimum(cap, need))

    pooled0 = pooled(left)

    def greedy(carry, v):
        left_c, have = carry
        freed = valloc[v][:, None] * vreq[v][None, :]  # [N, R]
        cand = left_c + freed
        cand_pool = pooled(cand)
        take = eligible[v] & (have < need)
        left_c = jnp.where(take, cand, left_c)
        have = jnp.where(take, cand_pool, have)
        return (left_c, have), take

    (left_all, have_all), taken_ord = jax.lax.scan(
        greedy, (left, pooled0), vorder
    )
    feasible = have_all >= need

    def reprieve(carry, v):
        left_c, tk = carry
        freed = valloc[v][:, None] * vreq[v][None, :]
        without = left_c - freed
        still = pooled(without) >= need
        drop = tk[v] & still & feasible
        left_c = jnp.where(drop, without, left_c)
        tk = tk.at[v].set(tk[v] & ~drop)
        return (left_c, tk), None

    taken = jnp.zeros((valloc.shape[0],), bool).at[vorder].set(taken_ord)
    taken = taken & feasible  # an infeasible pass evicts nothing
    # reverse greedy order: give back the most expensive victims first
    (left_fin, taken), _ = jax.lax.scan(
        reprieve, (left_all, taken), vorder[::-1]
    )
    return taken, feasible, pooled(left_fin)


@dataclass
class VictimPlan:
    """One dry-run preemption plan, ready for the control plane's
    verify-then-commit transaction (framework.scheduler)."""

    preemptor: str  # gang full_name (or pod name for non-gang preemptors)
    need: int
    gangs: List[str] = field(default_factory=list)
    # victim gang full_name -> its member pods (the eviction unit)
    pods_by_gang: Dict[str, list] = field(default_factory=dict)
    feasible: bool = False
    pooled_after: int = 0
    plan_seconds: float = 0.0

    @property
    def evicted_pods(self) -> int:
        return sum(len(p) for p in self.pods_by_gang.values())

    def victims(self) -> list:
        out = []
        for pods in self.pods_by_gang.values():
            out.extend(pods)
        return out


class PreemptionPlanner:
    """Host packer + verifier around ``plan_victims``.

    Victim rows are built from live cluster state (pods grouped by gang
    per node); the device answers the minimal set; ``verify`` re-checks
    the freed capacity against the same live state with the control
    plane's own resource math (core.resources) — the dry-run half of the
    dry-run/commit transaction.
    """

    def __init__(self, config):
        self.config = config

    # -- victim harvest -----------------------------------------------------

    def _harvest(self, cluster, status_cache, preemptor_gang: str,
                 preemptor_prio: int):
        """Group every gang pod bound/assumed on the cluster into victim
        candidates: (full_name -> {node -> [pods]}), honoring the tier
        and phase eligibility rules host-side (the device re-checks the
        tier rule; belt and braces)."""
        from ..utils.labels import pod_group_name

        victims: Dict[str, Dict[str, list]] = {}
        prio: Dict[str, int] = {}
        for node in cluster.list_nodes():
            for pod in cluster.pods_on(node.metadata.name):
                gname, is_gang = pod_group_name(pod)
                if not is_gang:
                    continue  # online pods are never policy-tier victims
                full = f"{pod.metadata.namespace}/{gname}"
                if full == preemptor_gang:
                    continue  # no self-preemption
                victims.setdefault(full, {}).setdefault(
                    node.metadata.name, []
                ).append(pod)
                # gang tier = its highest member priority: one equal-or-
                # higher member protects the whole gang (the caller's
                # vprio_map filter drops it)
                prio[full] = max(prio.get(full, -1), pod.spec.priority)
        if self.config.protect_running and status_cache is not None:
            from ..api.types import PodGroupPhase

            for full in list(victims):
                pgs = status_cache.get(full)
                if pgs is not None and pgs.pod_group.status.phase in (
                    PodGroupPhase.SCHEDULED,
                    PodGroupPhase.RUNNING,
                ):
                    del victims[full]
        return victims, prio

    def plan(self, pod, cluster, status_cache, full_name: str,
             need: int) -> Optional[VictimPlan]:
        """Dry-run one preemption plan for ``pod``'s denied gang. Returns
        None when nothing is evictable or even full eviction cannot seat
        the gang."""
        t0 = time.perf_counter()
        preemptor_prio = int(pod.spec.priority)
        victims, vprio_map = self._harvest(
            cluster, status_cache, full_name, preemptor_prio
        )
        victims = {
            f: nodes
            for f, nodes in victims.items()
            if vprio_map.get(f, 0) < preemptor_prio
        }
        if not victims or need <= 0:
            return None

        nodes = cluster.list_nodes()
        node_idx = {n.metadata.name: i for i, n in enumerate(nodes)}
        names = sorted(
            {
                k
                for n in nodes
                for k in n.status.allocatable
            }
            | set(pod.resource_require())
            | {
                k
                for per_node in victims.values()
                for pods in per_node.values()
                for k in pods[0].resource_require()
            }
            | {"pods"}
        )
        lane = {k: i for i, k in enumerate(names)}
        n_count, r_count = len(nodes), len(names)

        def row(d: Dict[str, int]) -> np.ndarray:
            out = np.zeros(r_count, np.int32)
            for k, v in d.items():
                out[lane[k]] = min(int(v), _BIG)
            return out

        left = np.zeros((n_count, r_count), np.int64)
        from ..core import resources as rmath

        fit_row = np.zeros(n_count, np.int32)
        for i, node in enumerate(nodes):
            left_d = rmath.single_node_left(
                node, cluster.node_requested(node.metadata.name), None
            )
            left[i] = row(left_d)
            fit_row[i] = int(
                not node.spec.unschedulable and rmath.check_fit(pod, node)
            )
        left = np.clip(left, -_BIG, _BIG).astype(np.int32)

        req_d = dict(pod.resource_require())
        req_d["pods"] = req_d.get("pods", 0) + 1
        req = row(req_d)

        vnames = sorted(victims)
        v = len(vnames)
        vb = _v_bucket(v)
        valloc = np.zeros((vb, n_count), np.int32)
        vreq = np.zeros((vb, r_count), np.int32)
        vprio = np.zeros(vb, np.int32)
        vvalid = np.zeros(vb, np.int32)
        vpods = np.zeros(vb, np.int32)
        for i, full in enumerate(vnames):
            per_node = victims[full]
            any_pod = next(iter(per_node.values()))[0]
            vr = dict(any_pod.resource_require())
            vr["pods"] = vr.get("pods", 0) + 1
            vreq[i] = row(vr)
            vprio[i] = vprio_map.get(full, 0)
            vvalid[i] = 1
            for node_name, pods in per_node.items():
                ni = node_idx.get(node_name)
                if ni is not None:
                    valloc[i, ni] = len(pods)
                    vpods[i] += len(pods)
        # greedy order: lowest tier first, then fewest pods (minimize
        # preempted pods), then name order (deterministic); padding last
        order = sorted(
            range(vb),
            key=lambda i: (
                -vvalid[i],
                int(vprio[i]),
                int(vpods[i]),
                vnames[i] if i < v else "~",
            ),
        )
        taken, feasible, pooled_after = plan_victims(
            jnp.asarray(left),
            jnp.asarray(fit_row),
            jnp.asarray(req),
            jnp.int32(min(need, _BIG)),
            jnp.int32(preemptor_prio),
            jnp.asarray(valloc),
            jnp.asarray(vreq),
            jnp.asarray(vprio),
            jnp.asarray(vvalid),
            jnp.asarray(np.array(order, np.int32)),
        )
        taken = np.asarray(taken)
        if not bool(feasible):
            return None
        plan = VictimPlan(
            preemptor=full_name,
            need=int(need),
            feasible=True,
            pooled_after=int(pooled_after),
            plan_seconds=time.perf_counter() - t0,
        )
        for i in range(v):
            if taken[i]:
                full = vnames[i]
                plan.gangs.append(full)
                plan.pods_by_gang[full] = [
                    p for pods in victims[full].values() for p in pods
                ]
        return plan if plan.gangs else None

    # -- dry-run verification ----------------------------------------------

    def verify(self, plan: VictimPlan, pod, cluster) -> bool:
        """Re-verify the plan host-side against LIVE cluster state with the
        control plane's own resource math: after removing every victim
        pod's charge, the preemptor's pooled member capacity must cover
        its need. The commit half runs only on a True verdict."""
        from ..core import resources as rmath

        victim_by_node: Dict[str, list] = {}
        for pods in plan.pods_by_gang.values():
            for vp in pods:
                node = vp.spec.node_name
                if node is None:
                    # assumed-but-unbound victims release via their Permit
                    # reject; their charge is found through cluster state
                    continue
                victim_by_node.setdefault(node, []).append(vp)
        require = dict(pod.resource_require())
        require["pods"] = require.get("pods", 0) + 1
        seats = 0
        for node in cluster.list_nodes():
            if node.spec.unschedulable or not rmath.check_fit(pod, node):
                continue
            left = dict(
                rmath.single_node_left(
                    node, cluster.node_requested(node.metadata.name), None
                )
            )
            for vp in victim_by_node.get(node.metadata.name, ()):
                vreq = dict(vp.resource_require())
                vreq["pods"] = vreq.get("pods", 0) + 1
                left = rmath.add_resources(left, vreq)
            # count members fitting this node under the freed leftover
            while seats < plan.need and rmath.resource_satisfied(
                left, require
            ):
                left = rmath.add_resources(
                    left, {k: -v for k, v in require.items()}
                )
                seats += 1
            if seats >= plan.need:
                return True
        return seats >= plan.need
