"""Policy term registry: each policy is a pure jit'd scoring term over the
packed policy columns, composed per gang into the assignment scan's
selection composite.

The reference plugin exposes ``Score`` and ``PreemptAddPod`` /
``PreemptRemovePod`` extension points that its shipped implementation
stubs out (reference core.go:263-265, batchscheduler.go:116-144). Here
those become real, *vectorized* policies: every term is a pure function of
per-gang scalars and per-node columns — no host string work inside a batch
— so the whole policy surface rides the same one-device-round-trip
discipline as the oracle itself (docs/policy.md "Term algebra").

Packed columns (built host-side once per snapshot, ops.snapshot):

- ``prio[G]``       priority class per gang (the same field queue order
                    sorts on — one source of truth for tiers)
- ``aff[G]``        soft-affinity label hash (0 = no preference)
- ``anti[G]``       anti-affinity label hash (0 = none; HARD exclusion)
- ``gang_dom[G,D]`` members of the gang already placed per spread-domain
                    bucket (all-zero when the gang did not opt into spread)
- ``node_hash[N,H]``label hashes of each node's first H labels (0-padded)
- ``node_dom[N]``   spread-domain bucket of each node

A term maps those to either a per-node int32 PENALTY (soft: added to the
tightness bucket so penalized nodes are consumed later, never excluded) or
a per-node 0/1 KEEP mask (hard: multiplied into the gang's capacity row).
With every term disabled the composite is identically zero / all-ones, so
policy-off batches are bit-identical to the base scan by construction —
the invariant ``make bench-policy`` enforces.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = [
    "DOMAIN_BUCKETS",
    "HASH_LANES",
    "TERM_REGISTRY",
    "SCORING_TERMS",
    "register_term",
    "label_hash",
    "parse_label_ref",
    "node_policy_row",
    "compose_terms",
    "compose_keep_dense",
]

# Spread-domain hash buckets. Domains (zones/racks) number in the tens on
# real clusters; 16 buckets keep the per-gang column one cache line while
# a hash collision only makes two domains share a spread count —
# conservative (more spreading), never unsafe.
DOMAIN_BUCKETS = 16

# Label-hash lanes per node: the first H node labels (sorted by key) ride
# the packed column. Affinity/anti-affinity against a label beyond the
# H-th simply never matches — documented in docs/policy.md, and the
# packer counts such truncations (bst_policy_label_truncations_total).
HASH_LANES = 4


def label_hash(key: str, value: str) -> int:
    """Stable positive int32 hash of one ``key=value`` label pair; never 0
    (0 is the empty-lane sentinel in the packed columns)."""
    h = zlib.crc32(f"{key}={value}".encode()) & 0x7FFFFFFF
    return h or 1


def parse_label_ref(raw: str) -> Tuple[str, str]:
    """Parse a policy label value naming a node label: "key:value" (or the
    "key=value" spelling). Returns ("", "") for an unparseable value — a
    typo'd policy label degrades to "no constraint", never to an error in
    the packing hot path (the BST_SCAN_WAVE parse-guard idiom)."""
    for sep in (":", "="):
        if sep in raw:
            k, _, v = raw.partition(sep)
            if k and v:
                return k, v
    return "", ""


def node_policy_row(labels: Dict[str, str], spread_key: str):
    """One node's packed policy columns: (hash_lanes[H], domain_bucket,
    truncated_label_count). Pure host-side numpy — called by the snapshot
    packer once per churned node, not per batch."""
    row = np.zeros(HASH_LANES, np.int32)
    keys = sorted(labels)
    for i, k in enumerate(keys[:HASH_LANES]):
        row[i] = label_hash(k, labels[k])
    dom = 0
    sv = labels.get(spread_key)
    if sv is not None:
        dom = label_hash(spread_key, sv) % DOMAIN_BUCKETS
    return row, dom, max(0, len(keys) - HASH_LANES)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

# name -> (kind, fn). Kinds:
#   "penalty"  fn(ctx) -> pen[N] int32 >= 0 added into the selection key
#   "mask"     fn(ctx) -> keep[N] int32 0/1 multiplied into capacity
#   "gate"     no device fn; toggles a control-plane behavior (preemption)
# ctx is a dict of per-gang scalars + node columns + weights — see
# compose_terms for the exact keys. Terms must be pure jnp (trace-safe).
TERM_REGISTRY: Dict[str, Tuple[str, Callable]] = {}


def register_term(name: str, kind: str = "penalty"):
    """Register one policy term. Decorator form:

        @register_term("affinity")
        def _affinity(ctx): ...
    """

    def deco(fn):
        TERM_REGISTRY[name] = (kind, fn)
        return fn

    return deco


@register_term("affinity", "penalty")
def _affinity_term(ctx):
    """Soft node-affinity: a gang with ``aff`` set pays ``w_aff`` on every
    node whose label lanes do not contain the hash — matching nodes are
    consumed first, non-matching remain available (no starvation)."""
    aff = ctx["aff"]  # scalar
    match = jnp.any(ctx["node_hash"] == aff, axis=-1)  # [N]
    want = aff > 0
    return jnp.where(want & ~match, ctx["w_aff"], 0).astype(jnp.int32)


@register_term("anti-affinity", "mask")
def _anti_affinity_term(ctx):
    """Hard anti-affinity: nodes carrying the gang's ``anti`` label are
    excluded from its capacity row exactly like a failed node selector."""
    anti = ctx["anti"]
    hit = jnp.any(ctx["node_hash"] == anti, axis=-1)  # [N]
    return jnp.where((anti > 0) & hit, 0, 1).astype(jnp.int32)


@register_term("spread", "penalty")
def _spread_term(ctx):
    """Spread penalty: a node whose spread domain already holds k of this
    gang's members pays ``w_spread * min(k, spread_cap)`` — emptier
    domains are consumed first, saturating so one crowded domain cannot
    push nodes past the loosest tightness bucket forever."""
    occupancy = jnp.take(ctx["gang_dom"], ctx["node_dom"], mode="clip")  # [N]
    return (
        jnp.minimum(occupancy, ctx["spread_cap"]) * ctx["w_spread"]
    ).astype(jnp.int32)


# Preemption is a control-plane gate, not a device scoring term: enabling
# it arms the vectorized victim planner (policy.preempt) on the deny path.
register_term("preempt", "gate")(lambda ctx: None)

# Terms with a device-side scoring contribution, in composite order.
SCORING_TERMS = ("affinity", "anti-affinity", "spread")


def compose_terms(terms: tuple, weights: tuple):
    """Compose the enabled scoring terms into one per-gang function
    ``fn(aff, anti, dom_row, node_hash, node_dom) -> (pen[N], keep[N])``.

    ``terms`` is the static tuple of enabled term names and ``weights``
    the static ``(w_aff, w_spread, spread_cap)`` triple — both hashable,
    so the jitted scan treats each policy config as its own signature.
    Unknown names are ignored (a version-skewed config must degrade, not
    crash a batch); "gate" terms contribute nothing here.
    """
    w_aff, w_spread, spread_cap = (tuple(weights) + (0, 0, 0))[:3]

    def fn(aff, anti, dom_row, node_hash, node_dom):
        ctx = {
            "aff": aff,
            "anti": anti,
            "gang_dom": dom_row,
            "node_hash": node_hash,
            "node_dom": node_dom,
            "w_aff": jnp.int32(w_aff),
            "w_spread": jnp.int32(w_spread),
            "spread_cap": jnp.int32(spread_cap),
        }
        n = node_dom.shape[0]
        pen = jnp.zeros((n,), jnp.int32)
        keep = jnp.ones((n,), jnp.int32)
        for name in terms:
            entry = TERM_REGISTRY.get(name)
            if entry is None:
                continue
            kind, term = entry
            if kind == "penalty":
                pen = pen + term(ctx)
            elif kind == "mask":
                keep = keep * term(ctx)
        return pen, keep

    return fn


def compose_keep_dense(terms: tuple, anti, node_hash):
    """The [G, N] hard-mask product of every enabled mask term — applied to
    the batch-head capacity matrix so feasibility/scores stay consistent
    with what the policy scan will refuse to take. Today the only mask
    term is anti-affinity; unknown names are ignored like compose_terms."""
    if "anti-affinity" not in terms:
        g = anti.shape[0]
        return jnp.ones((g, 1), jnp.int32)
    hit = jnp.any(
        node_hash[None, :, :] == anti[:, None, None], axis=-1
    )  # [G, N]
    return jnp.where((anti[:, None] > 0) & hit, 0, 1).astype(jnp.int32)
