"""Vectorized policy engine: priority-tiered preemption, affinity and
spread as composable jit'd scoring terms over the oracle's packed buffers
(docs/policy.md).

- policy.terms   — the term registry + packed-column conventions
- policy.engine  — PolicyConfig / PolicyEngine (env knobs, fingerprint,
                   /debug/policy view, per-term flight-recorder blame)
- policy.preempt — the vectorized victim planner + dry-run verifier
"""

from .engine import (
    PolicyConfig,
    PolicyEngine,
    active_engine,
    active_fingerprint,
    policy_debug_view,
)
from .preempt import PreemptionPlanner, VictimPlan, plan_victims
from .terms import (
    DOMAIN_BUCKETS,
    HASH_LANES,
    SCORING_TERMS,
    TERM_REGISTRY,
    compose_terms,
    label_hash,
    node_policy_row,
    parse_label_ref,
    register_term,
)

__all__ = [
    "PolicyConfig",
    "PolicyEngine",
    "PreemptionPlanner",
    "VictimPlan",
    "plan_victims",
    "active_engine",
    "active_fingerprint",
    "policy_debug_view",
    "DOMAIN_BUCKETS",
    "HASH_LANES",
    "SCORING_TERMS",
    "TERM_REGISTRY",
    "compose_terms",
    "label_hash",
    "node_policy_row",
    "parse_label_ref",
    "register_term",
]
