"""PolicyEngine: configuration, fingerprinting and the host-side face of
the vectorized policy subsystem (docs/policy.md).

The engine owns WHICH terms are enabled and with what weights; the scoring
math itself lives in policy.terms (jit'd, composed into the assignment
scan) and the preemption pass in policy.preempt. Everything here is
host-side bookkeeping: env parsing (parse-guarded — a typo'd knob degrades
to "policies off", never a crashed batch), the config fingerprint that
rides audit records and the wire annotation, per-term explain() for the
flight recorder, and the /debug/policy view.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from .terms import (
    DOMAIN_BUCKETS,
    HASH_LANES,
    SCORING_TERMS,
    TERM_REGISTRY,
    label_hash,
)

__all__ = [
    "PolicyConfig",
    "PolicyEngine",
    "set_active_engine",
    "active_engine",
    "active_fingerprint",
    "policy_debug_view",
]

_POLICY_ENV = "BST_POLICY"
_env_warned = [False]


@dataclass(frozen=True)
class PolicyConfig:
    """One policy configuration: the enabled term set + weights.

    ``terms`` is the sorted tuple of enabled term names (from
    policy.terms.TERM_REGISTRY). Empty = the policy engine is OFF and
    every batch runs the exact pre-policy code path (bit-identity by
    construction, enforced by ``make bench-policy``).
    """

    terms: Tuple[str, ...] = ()
    # Soft-affinity penalty added to the tightness bucket of non-matching
    # nodes: 32 pushes them behind every realistically-tight matching
    # bucket while staying well inside the [0, _BINS-1] composite domain.
    affinity_weight: int = 32
    # Spread penalty per already-occupied domain member, saturating at
    # spread_cap occupants.
    spread_weight: int = 8
    spread_cap: int = 3
    # Node label whose value defines the spread domain.
    spread_node_key: str = "zone"
    # Preemption eligibility: when False (spot semantics, the default) a
    # strictly-lower-tier gang may be evicted even after its gang released
    # (Scheduled/Running); True restores the reference's phase protection.
    protect_running: bool = False

    @property
    def enabled(self) -> bool:
        return bool(self.terms)

    @property
    def preemption(self) -> bool:
        return "preempt" in self.terms

    @property
    def scoring_terms(self) -> Tuple[str, ...]:
        return tuple(t for t in self.terms if t in SCORING_TERMS)

    @property
    def weights(self) -> Tuple[int, int, int]:
        return (
            int(self.affinity_weight),
            int(self.spread_weight),
            int(self.spread_cap),
        )

    def fingerprint(self) -> dict:
        """The policy slice of the execution config fingerprint
        (utils.audit.config_fingerprint): the dict itself plus a 16-hex
        sha over it, so divergence blame can name WHICH knob differed."""
        cfg = {
            "terms": list(self.terms),
            "affinity_weight": self.affinity_weight,
            "spread_weight": self.spread_weight,
            "spread_cap": self.spread_cap,
            "spread_node_key": self.spread_node_key,
            "protect_running": self.protect_running,
        }
        digest = hashlib.sha256(
            json.dumps(cfg, sort_keys=True).encode()
        ).hexdigest()
        cfg["fingerprint"] = digest[:16]
        return cfg

    @classmethod
    def from_env(cls) -> "PolicyConfig":
        """Parse BST_POLICY ("affinity,spread,preempt", "all", or
        0/off/empty) + the BST_POLICY_* weight knobs. Parse-guarded like
        BST_SCAN_WAVE: anything unparseable degrades to policies-off with
        one stderr warning, never a crashed batch."""
        raw = os.environ.get(_POLICY_ENV, "").strip()
        if not raw or raw.lower() in ("0", "off", "false", "no"):
            return cls()
        if raw.lower() == "all":
            names = sorted(TERM_REGISTRY)
        else:
            names = sorted(
                {t.strip() for t in raw.split(",") if t.strip()}
            )
        unknown = [t for t in names if t not in TERM_REGISTRY]
        if unknown and not _env_warned[0]:
            _env_warned[0] = True
            import sys

            print(
                f"ignoring unknown {_POLICY_ENV} terms {unknown!r} "
                f"(known: {sorted(TERM_REGISTRY)})",
                file=sys.stderr,
            )
        names = tuple(t for t in names if t in TERM_REGISTRY)

        def _int(name: str, default: int) -> int:
            v = os.environ.get(name, "").strip()
            if not v:
                return default
            try:
                return max(0, int(v))
            except ValueError:
                return default

        protect = os.environ.get(
            "BST_POLICY_PROTECT_RUNNING", ""
        ).strip().lower() in ("1", "true", "yes", "on")
        return cls(
            terms=names,
            affinity_weight=_int("BST_POLICY_AFFINITY_WEIGHT", 32),
            spread_weight=_int("BST_POLICY_SPREAD_WEIGHT", 8),
            spread_cap=_int("BST_POLICY_SPREAD_CAP", 3),
            spread_node_key=os.environ.get(
                "BST_POLICY_SPREAD_KEY", "zone"
            ).strip()
            or "zone",
            protect_running=protect,
        )


class PolicyEngine:
    """Host-side policy runtime: config + counters + explain(). One per
    ScheduleOperation; the most recently constructed enabled engine is
    also registered as the process's /debug/policy view."""

    def __init__(self, config: Optional[PolicyConfig] = None):
        self.config = config if config is not None else PolicyConfig.from_env()
        self._lock = threading.Lock()
        self.batches_scored = 0  # guarded-by: _lock
        self.preempt_plans = 0  # guarded-by: _lock
        # denied-gang preemption attempts that yielded NO plan (no
        # eligible victims, nothing to free, or infeasible even with full
        # eviction — the planner returns one None for all three)
        self.preempt_no_plan = 0  # guarded-by: _lock
        if self.config.enabled:
            set_active_engine(self)

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    @property
    def preemption(self) -> bool:
        return self.config.preemption

    def note_batch(self) -> None:
        with self._lock:
            self.batches_scored += 1

    def note_plan(self, planned: bool) -> None:
        with self._lock:
            if planned:
                self.preempt_plans += 1
            else:
                self.preempt_no_plan += 1

    # -- flight-recorder blame ---------------------------------------------

    def explain(self, policy_cols, g: int, node_indices) -> Dict[str, int]:
        """Per-term score contributions for one gang at its chosen nodes —
        the flight recorder's policy blame payload. Pure numpy on the
        already-packed columns; O(len(node_indices)) per placed gang."""
        if policy_cols is None or not node_indices:
            return {}
        prio, aff, anti, gang_dom, node_hash, node_dom = (
            np.asarray(a) for a in policy_cols
        )
        idx = [int(n) for n in node_indices if 0 <= int(n) < node_dom.shape[0]]
        if not idx or g >= aff.shape[0]:
            return {}
        out: Dict[str, int] = {"priority_class": int(prio[g])}
        w_aff, w_spread, cap = self.config.weights
        if "affinity" in self.config.terms and aff[g] > 0:
            miss = sum(
                1 for n in idx if aff[g] not in node_hash[n]
            )
            out["affinity_penalty"] = int(miss * w_aff)
        if "spread" in self.config.terms:
            pen = sum(
                min(int(gang_dom[g, int(node_dom[n])]), cap) * w_spread
                for n in idx
            )
            out["spread_penalty"] = int(pen)
        if "anti-affinity" in self.config.terms and anti[g] > 0:
            out["anti_affinity_active"] = 1
        return out

    def debug_view(self) -> dict:
        """The /debug/policy payload (utils.metrics)."""
        with self._lock:
            counters = {
                "batches_scored": self.batches_scored,
                "preempt_plans": self.preempt_plans,
                "preempt_no_plan": self.preempt_no_plan,
            }
        return {
            "config": self.config.fingerprint(),
            "registry": {
                name: kind for name, (kind, _) in sorted(TERM_REGISTRY.items())
            },
            "columns": {
                "domain_buckets": DOMAIN_BUCKETS,
                "hash_lanes": HASH_LANES,
            },
            "counters": counters,
        }


# ---------------------------------------------------------------------------
# process-wide view (the /debug/policy endpoint + config fingerprinting)
# ---------------------------------------------------------------------------

_active: list = [None]


def set_active_engine(engine: Optional[PolicyEngine]) -> None:
    _active[0] = engine


def active_engine() -> Optional[PolicyEngine]:
    return _active[0]


def active_fingerprint() -> Optional[dict]:
    """The active engine's config fingerprint, or None when no enabled
    engine exists — folded into utils.audit.config_fingerprint so policy
    drift shows up in replay divergence blame."""
    eng = _active[0]
    if eng is None or not eng.enabled:
        return None
    return eng.config.fingerprint()


def policy_debug_view() -> dict:
    eng = _active[0]
    if eng is None:
        return {"enabled": False}
    view = eng.debug_view()
    view["enabled"] = eng.enabled
    return view
