"""API group registration constants.

Mirror of the reference's scheme registration
(reference pkg/apis/podgroup/register.go:21, pkg/apis/podgroup/v1/register.go:28-55).
"""

GROUP_NAME = "batch.scheduler.tpu"
VERSION = "v1"
GROUP_VERSION = f"{GROUP_NAME}/{VERSION}"

KIND_POD_GROUP = "PodGroup"
PLURAL_POD_GROUPS = "podgroups"
SHORT_NAMES = ("pg", "pgs")

CRD_NAME = f"{PLURAL_POD_GROUPS}.{GROUP_NAME}"
