"""Kubernetes-style resource quantity parsing.

Parses the quantity grammar used in pod/node resource lists (``100m``,
``1.5``, ``64Mi``, ``2G``, ``1e3``) into exact canonical integers:

- ``cpu`` is canonicalised to **millicores** (``"1" -> 1000``, ``"250m" -> 250``),
- everything else to its base unit rounded **up** for requests/limits and
  **down** for capacities, so that integer comparisons stay conservative.

The reference relies on k8s ``resource.MustParse`` + ``nodeinfo.Resource``
int64 fields (reference pkg/scheduler/core/core_test.go:34-66,
core.go:656-668); this module is the equivalent exact-arithmetic layer,
implemented with ``fractions.Fraction`` so binary and decimal suffixes are
lossless.
"""

from __future__ import annotations

import re
from fractions import Fraction

__all__ = [
    "parse_quantity",
    "canonicalize",
    "parse_resource_list",
    "format_quantity",
]

_BINARY_SUFFIXES = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "k": 1000,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:[eE](?P<exp>[+-]?\d+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$"
)


def parse_quantity(value: "str | int | float") -> Fraction:
    """Parse a k8s quantity string into an exact Fraction of the base unit."""
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity: {value!r}")
    if isinstance(value, int):
        return Fraction(value)
    if isinstance(value, float):
        return Fraction(value).limit_denominator(10**9)
    m = _QUANTITY_RE.match(value.strip())
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num = Fraction(m.group("num"))
    if m.group("exp"):
        num *= Fraction(10) ** int(m.group("exp"))
    suffix = m.group("suffix")
    if suffix:
        num *= Fraction(_BINARY_SUFFIXES.get(suffix) or _DECIMAL_SUFFIXES[suffix])
    if m.group("sign") == "-":
        num = -num
    return num


def canonicalize(resource: str, value: "str | int | float", *, floor: bool = False) -> int:
    """Canonicalise a quantity to the integer unit used on-device.

    cpu -> millicores; everything else -> base units. Requests round up
    (default) and capacities round down (``floor=True``) so that
    ``capacity >= request`` comparisons never pass due to rounding.
    """
    q = parse_quantity(value)
    if resource == "cpu":
        q *= 1000
    n = q.numerator // q.denominator
    if not floor and n * q.denominator != q.numerator:
        n += 1
    return int(n)


def parse_resource_list(
    raw: "dict[str, str | int | float] | None", *, floor: bool = False
) -> "dict[str, int]":
    """Canonicalise a whole resource list (e.g. a container's requests)."""
    if not raw:
        return {}
    return {name: canonicalize(name, v, floor=floor) for name, v in raw.items()}


def format_quantity(resource: str, canonical: int) -> str:
    """Human-readable rendering of a canonical integer quantity."""
    if resource == "cpu":
        if canonical % 1000 == 0:
            return str(canonical // 1000)
        return f"{canonical}m"
    for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        base = _BINARY_SUFFIXES[suffix]
        if canonical and canonical % base == 0:
            return f"{canonical // base}{suffix}"
    return str(canonical)
