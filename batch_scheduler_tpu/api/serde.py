"""Dict <-> object serde for API objects.

The API server stores plain dicts (so JSON merge patches apply naturally,
matching the reference's apiserver interactions) and rehydrates typed
objects at the clientset boundary. ``to_dict`` lives in api.types; these are
the inverse constructors.
"""

from __future__ import annotations

from typing import Optional

from .types import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodGroupSpec,
    PodGroupStatus,
    PodPhase,
    PodSpec,
    PodStatus,
    Taint,
    Toleration,
)

__all__ = [
    "pod_group_from_dict",
    "pod_from_dict",
    "node_from_dict",
    "object_from_dict",
    "KIND_CONSTRUCTORS",
]


def _meta(d: Optional[dict]) -> ObjectMeta:
    d = d or {}
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid", ""),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        owner_references=list(d.get("owner_references") or []),
        creation_timestamp=d.get("creation_timestamp", 0.0),
        resource_version=d.get("resource_version", 0),
    )


def pod_group_from_dict(d: dict) -> PodGroup:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return PodGroup(
        metadata=_meta(d.get("metadata")),
        spec=PodGroupSpec(
            min_member=spec.get("min_member", 0),
            priority_class_name=spec.get("priority_class_name", ""),
            # copied like every other nested container: typed objects must
            # never alias the source dict (it may be an informer store entry)
            min_resources=(
                dict(spec["min_resources"])
                if spec.get("min_resources") is not None
                else None
            ),
            max_schedule_time=spec.get("max_schedule_time"),
        ),
        status=PodGroupStatus(
            phase=PodGroupPhase(status.get("phase", "")),
            occupied_by=status.get("occupied_by", ""),
            scheduled=status.get("scheduled", 0),
            running=status.get("running", 0),
            succeeded=status.get("succeeded", 0),
            failed=status.get("failed", 0),
            schedule_start_time=status.get("schedule_start_time", 0.0),
        ),
    )


def _container(d: dict) -> Container:
    return Container(
        name=d.get("name", "main"),
        requests=dict(d.get("requests") or {}),
        limits=dict(d.get("limits") or {}),
    )


def _toleration(d: dict) -> Toleration:
    return Toleration(
        key=d.get("key", ""),
        operator=d.get("operator", "Equal"),
        value=d.get("value", ""),
        effect=d.get("effect", ""),
    )


def pod_from_dict(d: dict) -> Pod:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return Pod(
        metadata=_meta(d.get("metadata")),
        spec=PodSpec(
            containers=[_container(c) for c in spec.get("containers") or []],
            node_selector=dict(spec.get("node_selector") or {}),
            tolerations=[_toleration(t) for t in spec.get("tolerations") or []],
            # "priority": null is legal external JSON; normalize here so
            # every typed consumer (compare, preemption sorts) sees an int
            priority=spec.get("priority") or 0,
            node_name=spec.get("node_name") or "",
        ),
        # explicit null is as legal as a missing field here (same contract
        # as priority above; the raw-path consumers normalize identically)
        status=PodStatus(phase=PodPhase(status.get("phase") or "Pending")),
    )


def _taint(d: dict) -> Taint:
    return Taint(
        key=d.get("key", ""),
        value=d.get("value", ""),
        effect=d.get("effect", "NoSchedule"),
    )


def node_from_dict(d: dict) -> Node:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return Node(
        metadata=_meta(d.get("metadata")),
        spec=NodeSpec(
            taints=[_taint(t) for t in spec.get("taints") or []],
            unschedulable=spec.get("unschedulable", False),
        ),
        status=NodeStatus(
            allocatable=dict(status.get("allocatable") or {}),
            capacity=dict(status.get("capacity") or {}),
        ),
    )


KIND_CONSTRUCTORS = {
    "PodGroup": pod_group_from_dict,
    "Pod": pod_from_dict,
    "Node": node_from_dict,
}


def object_from_dict(kind: str, d: dict):
    return KIND_CONSTRUCTORS[kind](d)
