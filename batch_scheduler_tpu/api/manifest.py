"""Kubernetes-manifest loader: YAML documents -> API model objects.

The reference consumes manifests through the Kubernetes API server (CRD
``deploy/crd.yaml`` + workloads like ``examples/example1.yaml`` — a PodGroup
plus a Parallel StatefulSet whose template carries the group label, reference
examples/example1.yaml:1-34). This framework has no API server in front of
it, so this module does the equivalent translation directly: camelCase
Kubernetes YAML -> the internal snake_case/canonical-integer model in
:mod:`batch_scheduler_tpu.api.types`, expanding workload controllers
(StatefulSet / Deployment / ReplicaSet / Job) into their member pods the way
kube-controller-manager would.

Quantity strings ("1", "500m", "4Gi") are canonicalised to exact integers via
:func:`batch_scheduler_tpu.api.quantity.parse_resource_list`.
"""

from __future__ import annotations

import io
import re
from typing import List, Optional, Union

import yaml

from .quantity import parse_resource_list
from .types import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    Taint,
    Toleration,
)

__all__ = [
    "load_manifests",
    "load_manifest_file",
    "parse_pod_group",
    "parse_pod",
    "parse_node",
    "expand_workload",
    "WORKLOAD_KINDS",
]

WORKLOAD_KINDS = ("StatefulSet", "Deployment", "ReplicaSet", "Job")


def _meta(d: Optional[dict]) -> ObjectMeta:
    d = d or {}
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid", ""),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
    )


def parse_pod_group(doc: dict) -> PodGroup:
    """PodGroup manifest -> model (reference pkg/apis/podgroup/v1/types.go:79-101).

    ``spec.minResources`` is a per-member resource floor with Kubernetes
    quantity strings; ``spec.maxScheduleTime`` accepts seconds (int/float) or
    a Go-style duration string handled by the caller's config layer.
    """
    spec = doc.get("spec") or {}
    min_resources = spec.get("minResources")
    return PodGroup(
        metadata=_meta(doc.get("metadata")),
        spec=PodGroupSpec(
            min_member=int(spec.get("minMember", 0)),
            priority_class_name=spec.get("priorityClassName", ""),
            min_resources=(
                parse_resource_list(min_resources) if min_resources else None
            ),
            max_schedule_time=_duration_seconds(spec.get("maxScheduleTime")),
        ),
    )


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h)")
_DURATION_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def _duration_seconds(v) -> Optional[float]:
    """Accept seconds (number) or a Go-style duration ("30s", "5m", "1m30s",
    "500ms", "1h2m3s")."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    matches = list(_DURATION_RE.finditer(s))
    if matches and "".join(m.group(0) for m in matches) == s:
        return sum(float(m.group(1)) * _DURATION_UNITS[m.group(2)] for m in matches)
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"invalid maxScheduleTime duration: {s!r}") from None


def _container_from_manifest(d: dict) -> Container:
    res = d.get("resources") or {}
    return Container(
        name=d.get("name", "main"),
        requests=parse_resource_list(res.get("requests")),
        limits=parse_resource_list(res.get("limits")),
    )


def _pod_spec_from_manifest(spec: Optional[dict]) -> PodSpec:
    spec = spec or {}
    return PodSpec(
        containers=[_container_from_manifest(c) for c in spec.get("containers") or []],
        node_selector=dict(spec.get("nodeSelector") or {}),
        tolerations=[
            Toleration(
                key=t.get("key", ""),
                operator=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
            for t in spec.get("tolerations") or []
        ],
        priority=int(spec.get("priority", 0)),
        node_name=spec.get("nodeName", ""),
    )


def parse_pod(doc: dict) -> Pod:
    return Pod(metadata=_meta(doc.get("metadata")), spec=_pod_spec_from_manifest(doc.get("spec")))


def parse_node(doc: dict) -> Node:
    status = doc.get("status") or {}
    spec = doc.get("spec") or {}
    allocatable = parse_resource_list(status.get("allocatable"))
    capacity = parse_resource_list(status.get("capacity"))
    return Node(
        metadata=_meta(doc.get("metadata")),
        spec=NodeSpec(
            taints=[
                Taint(
                    key=t.get("key", ""),
                    value=t.get("value", ""),
                    effect=t.get("effect", "NoSchedule"),
                )
                for t in spec.get("taints") or []
            ],
            unschedulable=bool(spec.get("unschedulable", False)),
        ),
        status=NodeStatus(
            allocatable=allocatable or dict(capacity),
            capacity=capacity or dict(allocatable),
        ),
    )


def expand_workload(doc: dict) -> List[Pod]:
    """Expand a workload controller manifest into its member pods.

    Mirrors what the pod controllers do for the reference's gang demo: a
    Parallel StatefulSet with ``replicas: 9`` whose pod template carries the
    group label becomes 9 pods named ``<name>-<ordinal>`` (reference
    examples/example1.yaml:8-34). Jobs use ``spec.parallelism``.
    """
    meta = doc.get("metadata") or {}
    spec = doc.get("spec") or {}
    name = meta.get("name", "workload")
    namespace = meta.get("namespace", "default")
    replicas = int(spec.get("replicas", spec.get("parallelism", 1)))
    template = spec.get("template") or {}
    tmeta = template.get("metadata") or {}
    labels = dict(tmeta.get("labels") or {})
    annotations = dict(tmeta.get("annotations") or {})

    pods: List[Pod] = []
    for ordinal in range(replicas):
        pod = Pod(
            metadata=ObjectMeta(
                name=f"{name}-{ordinal}",
                namespace=namespace,
                labels=dict(labels),
                annotations=dict(annotations),
            ),
            spec=_pod_spec_from_manifest(template.get("spec")),
        )
        pods.append(pod)
    return pods


def load_manifests(source: Union[str, io.TextIOBase]) -> List[object]:
    """Parse a (possibly multi-document) YAML manifest string/stream into
    model objects: PodGroup / Pod / Node directly, workload kinds expanded
    into their member Pods. Unknown kinds (Service, CRD, ...) are skipped —
    they configure layers this framework does not model."""
    text = source.read() if hasattr(source, "read") else source
    out: List[object] = []
    for doc in yaml.safe_load_all(text):
        if not doc or not isinstance(doc, dict):
            continue
        kind = doc.get("kind", "")
        if kind == "PodGroup":
            out.append(parse_pod_group(doc))
        elif kind == "Pod":
            out.append(parse_pod(doc))
        elif kind == "Node":
            out.append(parse_node(doc))
        elif kind in WORKLOAD_KINDS:
            out.extend(expand_workload(doc))
        # else: skip (CRD manifests, Services, ... are deploy-time config)
    return out


def load_manifest_file(path: str) -> List[object]:
    with open(path, "r", encoding="utf-8") as fh:
        return load_manifests(fh)
