"""Placement-fit primitives shared by the serial path and the oracle
snapshot builder: node-selector matching and taint toleration
(reference pkg/scheduler/core/core.go:741-759 via k8s predicates
PodMatchNodeSelector + PodToleratesNodeTaints).

Kept in one place so the serial and batched paths can never diverge on
which nodes a gang may use.
"""

from __future__ import annotations

from typing import Dict, Iterable

from .types import Taint, Toleration

__all__ = ["BLOCKING_TAINT_EFFECTS", "selector_matches", "tolerates_all"]

# PreferNoSchedule never blocks placement (k8s semantics).
BLOCKING_TAINT_EFFECTS = ("NoSchedule", "NoExecute")


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def tolerates_all(
    tolerations: Iterable[Toleration], taints: Iterable[Taint]
) -> bool:
    tols = list(tolerations)
    for taint in taints:
        if taint.effect not in BLOCKING_TAINT_EFFECTS:
            continue
        if not any(t.tolerates(taint) for t in tols):
            return False
    return True
