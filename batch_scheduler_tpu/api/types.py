"""Core API object model: PodGroup CRD, Pods, Nodes.

This is the data-model equivalent of the reference's CRD types
(reference pkg/apis/podgroup/v1/types.go:25-143) plus the minimal slices of
the core/v1 Pod and Node objects the scheduler consumes
(reference pkg/scheduler/core/core.go:436-475,634-669,741-772).

Everything is a plain dataclass with exact-integer canonical resource lists
(see ``api.quantity``), deep-copyable and JSON-serialisable — the properties
the reference gets from k8s deepcopy-gen and apimachinery. Durable state
lives in object ``status`` fields stored in the (simulated or real) API
server; in-memory caches can always be rebuilt from watches, which is what
makes the scheduling oracle stateless per batch.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import itertools
from dataclasses import dataclass, field
from typing import Optional

from .quantity import parse_resource_list

__all__ = [
    "PodGroupPhase",
    "PodPhase",
    "ObjectMeta",
    "Toleration",
    "Taint",
    "Container",
    "PodSpec",
    "PodStatus",
    "Pod",
    "NodeSpec",
    "NodeStatus",
    "Node",
    "PodGroupSpec",
    "PodGroupStatus",
    "PodGroup",
    "new_uid",
]

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    """Generate a unique, deterministic-per-process object UID."""
    return f"{prefix}-{next(_uid_counter):08d}"


class PodGroupPhase(str, enum.Enum):
    """PodGroup lifecycle (reference pkg/apis/podgroup/v1/types.go:28-56)."""

    PENDING = "Pending"
    PRE_SCHEDULING = "PreScheduling"
    SCHEDULING = "Scheduling"
    SCHEDULED = "Scheduled"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    FINISHED = "Finished"
    FAILED = "Failed"
    # The empty phase of a freshly created object, normalised to PENDING by
    # the controller (reference pkg/scheduler/controller/controller.go:199-200).
    EMPTY = ""


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    # Owner UIDs, used for PodGroup occupancy fencing
    # (reference pkg/scheduler/core/core.go:477-512).
    owner_references: list = field(default_factory=list)
    creation_timestamp: float = 0.0
    resource_version: int = 0

    def full_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: str = ""  # "" tolerates all effects for the key

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Container:
    name: str = "main"
    # Canonical integer resource lists (cpu in milli, bytes elsewhere).
    requests: dict = field(default_factory=dict)
    limits: dict = field(default_factory=dict)

    @classmethod
    def from_raw(cls, name: str = "main", requests: dict = None, limits: dict = None):
        return cls(
            name=name,
            requests=parse_resource_list(requests),
            limits=parse_resource_list(limits),
        )


@dataclass
class PodSpec:
    containers: list = field(default_factory=list)
    node_selector: dict = field(default_factory=dict)
    tolerations: list = field(default_factory=list)
    priority: int = 0
    node_name: str = ""


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def resource_require(self) -> dict:
        """Per-pod required resources: sum of container limits, falling back
        to requests when no limits are set — the exact accounting rule of the
        reference (pkg/scheduler/core/core.go:761-772)."""
        total: dict = {}
        for c in self.spec.containers:
            chosen = c.limits if c.limits else c.requests
            for k, v in chosen.items():
                total[k] = total.get(k, 0) + v
        return total

    def deepcopy(self) -> "Pod":
        return copy.deepcopy(self)


@dataclass
class NodeSpec:
    taints: list = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeStatus:
    # Canonical integer lists; "pods" is the allowed pod count.
    allocatable: dict = field(default_factory=dict)
    capacity: dict = field(default_factory=dict)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def deepcopy(self) -> "Node":
        return copy.deepcopy(self)


@dataclass
class PodGroupSpec:
    """Reference pkg/apis/podgroup/v1/types.go:79-101."""

    min_member: int = 0
    priority_class_name: str = ""
    # Per-member resource floor (canonical integers); initialised from the
    # first observed member pod when unset (reference core.go:489-493).
    min_resources: Optional[dict] = None
    # Seconds; per-group override of the scheduler-wide max schedule time.
    max_schedule_time: Optional[float] = None


@dataclass
class PodGroupStatus:
    """Reference pkg/apis/podgroup/v1/types.go:104-130."""

    phase: PodGroupPhase = PodGroupPhase.EMPTY
    occupied_by: str = ""
    scheduled: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    schedule_start_time: float = 0.0


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    def full_name(self) -> str:
        return self.metadata.full_name()

    def deepcopy(self) -> "PodGroup":
        return copy.deepcopy(self)


def _meta_dict(m: ObjectMeta) -> dict:
    return {
        "name": m.name,
        "namespace": m.namespace,
        "uid": m.uid,
        "labels": dict(m.labels),
        "annotations": dict(m.annotations),
        "owner_references": list(m.owner_references),
        "creation_timestamp": m.creation_timestamp,
        "resource_version": m.resource_version,
    }


def _pod_dict(p: "Pod") -> dict:
    return {
        "metadata": _meta_dict(p.metadata),
        "spec": {
            "containers": [
                {
                    "name": c.name,
                    "requests": dict(c.requests),
                    "limits": dict(c.limits),
                }
                for c in p.spec.containers
            ],
            "node_selector": dict(p.spec.node_selector),
            "tolerations": [
                {
                    "key": t.key,
                    "operator": t.operator,
                    "value": t.value,
                    "effect": t.effect,
                }
                for t in p.spec.tolerations
            ],
            "priority": p.spec.priority,
            "node_name": p.spec.node_name,
        },
        "status": {"phase": p.status.phase.value},
    }


def _node_dict(n: "Node") -> dict:
    return {
        "metadata": _meta_dict(n.metadata),
        "spec": {
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in n.spec.taints
            ],
            "unschedulable": n.spec.unschedulable,
        },
        "status": {
            "allocatable": dict(n.status.allocatable),
            "capacity": dict(n.status.capacity),
        },
    }


def _pg_status_dict(s: PodGroupStatus) -> dict:
    return {
        "phase": s.phase.value,
        "occupied_by": s.occupied_by,
        "scheduled": s.scheduled,
        "running": s.running,
        "succeeded": s.succeeded,
        "failed": s.failed,
        "schedule_start_time": s.schedule_start_time,
    }


def _pg_dict(g: "PodGroup") -> dict:
    return {
        "metadata": _meta_dict(g.metadata),
        "spec": {
            "min_member": g.spec.min_member,
            "priority_class_name": g.spec.priority_class_name,
            "min_resources": (
                dict(g.spec.min_resources)
                if g.spec.min_resources is not None
                else None
            ),
            "max_schedule_time": g.spec.max_schedule_time,
        },
        "status": _pg_status_dict(g.status),
    }


_TO_DICT_FAST = {}  # populated below Pod/Node/PodGroup definitions


def to_dict(obj) -> dict:
    """Serialise an API object to plain JSON-able data (for patches/storage).

    The API kinds (and PodGroupStatus, the controller's patch unit) have
    explicit encoders — ``dataclasses.asdict`` walks the reduce protocol per
    field and was the control plane's single largest CPU line at 10k-pod
    scale. Output is field-for-field identical (asserted in
    tests/test_patch.py); unknown dataclasses still fall back to asdict.
    """
    fast = _TO_DICT_FAST.get(type(obj))
    if fast is not None:
        return fast(obj)

    def encode(v):
        if isinstance(v, enum.Enum):
            return v.value
        return v

    def factory(items):
        return {k: encode(v) for k, v in items}

    return dataclasses.asdict(obj, dict_factory=factory)


_TO_DICT_FAST.update(
    {
        Pod: _pod_dict,
        Node: _node_dict,
        PodGroup: _pg_dict,
        PodGroupStatus: _pg_status_dict,
    }
)
