from .chaos import ChaosProxy
from .harness import SimCluster
from .kubelet import SimKubelet
from .scenarios import (
    SyntheticSpec,
    make_member_pods,
    make_sim_group,
    make_sim_node,
    race_scenario,
    synthetic_cluster,
)

__all__ = [
    "ChaosProxy",
    "SimCluster",
    "SimKubelet",
    "SyntheticSpec",
    "make_member_pods",
    "make_sim_group",
    "make_sim_node",
    "race_scenario",
    "synthetic_cluster",
]
