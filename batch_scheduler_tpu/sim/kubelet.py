"""SimKubelet: KWOK-style pod lifecycle simulation.

The reference validates multi-node gang behaviour only manually against a
real cluster (SURVEY.md §4); here a simulated kubelet drives bound pods
through Pending -> Running (-> Succeeded/Failed) so the controller's phase
machine and the gang timeout/abort paths run end-to-end in-process, at any
cluster size — the KWOK harness the build plan calls for.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, Optional

from ..api.types import PodPhase
from ..client.apiserver import APIServer, NotFoundError, WatchEvent
from ..client.clientset import Clientset
from ..utils.drain import drain_queue

__all__ = ["SimKubelet"]


class SimKubelet:
    def __init__(
        self,
        api: APIServer,
        start_delay: float = 0.05,
        run_duration: Optional[float] = None,
        fail_pod: Optional[Callable[[str], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        """``start_delay``: bind -> Running latency. ``run_duration``: if
        set, Running -> Succeeded after this long. ``fail_pod``: fault
        injection — pods whose "namespace/name" it accepts go to Failed
        instead of Running."""
        self.api = api
        self.clientset = Clientset(api)
        self.start_delay = start_delay
        self.run_duration = run_duration
        self.fail_pod = fail_pod
        self._clock = clock
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pending: list = []  # heap of (due, seq, ns, name, next_phase)
        self._seq = 0
        self._batch_failures = 0  # consecutive _apply_due failures
        self._threads = []
        self._events = None

    def start(self) -> None:
        self._events = self.api.watch("Pod", replay=True)
        self._threads = [
            threading.Thread(target=self._watch_loop, name="kubelet-watch", daemon=True),
            threading.Thread(target=self._tick_loop, name="kubelet-tick", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        if self._events is not None:
            self.api.stop_watch("Pod", self._events)

    def _schedule_transition(self, ns: str, name: str, phase: PodPhase, delay: float) -> None:
        with self._lock:
            self._seq += 1
            heapq.heappush(
                self._pending, (self._clock() + delay, self._seq, ns, name, phase)
            )

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            batch = drain_queue(self._events, timeout=0.1)
            if batch is None:
                continue
            for event in batch:
                self._handle_event(event)

    def _handle_event(self, event) -> None:
        if event.type == WatchEvent.DELETED:
            return
        obj = event.obj
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        if not spec.get("node_name"):
            return
        if status.get("phase", "Pending") != "Pending":
            return
        meta = obj.get("metadata") or {}
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        key = f"{ns}/{name}"
        next_phase = (
            PodPhase.FAILED
            if self.fail_pod is not None and self.fail_pod(key)
            else PodPhase.RUNNING
        )
        self._schedule_transition(ns, name, next_phase, self.start_delay)

    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(0.02)
            now = self._clock()
            due = []
            with self._lock:
                while self._pending and self._pending[0][0] <= now:
                    due.append(heapq.heappop(self._pending))
            if not due:
                continue
            try:
                self._apply_due(due)
                self._batch_failures = 0
            except Exception:
                # the tick thread must survive a transport outage (HTTP
                # API): push the batch back and retry next tick — but
                # BOUNDED, then per-item with failures dropped, so one
                # poisoned pod cannot starve every co-due transition
                self._batch_failures += 1
                if self._batch_failures <= 25:  # ~5s outage budget
                    with self._lock:
                        for item in due:
                            heapq.heappush(self._pending, item)
                    self._stop.wait(0.2)
                else:
                    for item in due:
                        try:
                            self._apply_due([item])
                        except Exception:
                            pass  # poisoned item: dropped
                    self._batch_failures = 0

    def _apply_due(self, due) -> None:
        applied = set()
        patch_many = getattr(self.api, "patch_many", None)
        if patch_many is not None:
            # batched phase transitions: one lock pass per (tick, ns)
            # instead of a patch round trip per pod — at 10k pods the
            # per-pod form was measurable GIL load beside the scheduler
            by_ns: Dict[str, list] = {}
            for _, _, ns, name, phase in due:
                by_ns.setdefault(ns, []).append(
                    (name, {"status": {"phase": phase.value}})
                )
            for ns, patches in by_ns.items():
                for name in patch_many("Pod", ns, patches):
                    applied.add((ns, name))
        else:
            for _, _, ns, name, phase in due:
                try:
                    self.clientset.pods(ns).patch(
                        name, {"status": {"phase": phase.value}}
                    )
                except NotFoundError:
                    continue
                applied.add((ns, name))
        if self.run_duration is not None:
            # deleted pods (patch skipped) must not get phantom SUCCEEDED
            # transitions queued against their name
            for _, _, ns, name, phase in due:
                if phase == PodPhase.RUNNING and (ns, name) in applied:
                    self._schedule_transition(
                        ns, name, PodPhase.SUCCEEDED, self.run_duration
                    )
