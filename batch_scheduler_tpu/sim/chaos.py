"""ChaosProxy: an in-process fault-injecting TCP proxy for the oracle wire.

Sits between an oracle client and a real OracleServer and injects the
transport-failure classes a production sidecar link actually exhibits, on a
probability schedule (or deterministically via ``limit``):

- ``reset``     : hard connection reset mid-exchange (SO_LINGER 0 => RST,
                  the kill -9 / LB-drain failure mode)
- ``hang``      : black-hole — the response frame is swallowed and the
                  connection goes silent (hung sidecar / dropped route);
                  bounded by ``hang_s`` so test runs always terminate
- ``delay``     : the response frame arrives ``delay_s`` late (congested
                  or tunneled link)
- ``truncate``  : the frame header promises more payload than is sent
                  before the connection closes (peer died mid-write)
- ``garbage``   : bytes that are not a protocol frame at all (desynced or
                  hostile peer)

Faults are injected at FRAME granularity on the server->client direction
(the request made it out; the response is what suffers — exercising the
client's read/recovery path, which is where the resilient client lives).
The client->server direction relays raw bytes untouched by default; with
``c2s_frames=True`` it relays at frame granularity too and supports two
request-direction faults aimed at the device-resident-state delta stream
(docs/pipelining.md "Device-resident state"):

- ``drop_c2s`` : one request frame silently vanishes (lossy middlebox) —
                 the connection stays up, the client's read times out
- ``dup_c2s``  : one request frame is delivered twice (retransmit bug) —
                 the server sees the same delta again and must refuse it
                 on the generation check, never apply it twice

Beyond per-frame faults, the proxy exposes ENDPOINT-level primitives for
the HA crash drills (docs/resilience.md "High availability"): ``kill_
endpoint()`` RSTs every live connection and refuses new ones (instance
loss), ``partition_endpoint()`` black-holes both directions while
connections stay up (network partition), ``hang_endpoint()`` delivers
requests but swallows every response (accepting-but-dead), and
``restore_endpoint()`` brings the endpoint back. The failover gate
(benchmarks/failover_gate.py) drives a whole sidecar through these to
prove the pooled client's standby promotion.

Used by tests/test_chaos_oracle.py to prove ResilientOracleClient survives
every class, and by the chaos-enabled fuzz e2e (tests/test_fuzz_e2e.py).
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Dict, Optional, Union

from ..service import protocol as proto

__all__ = ["ChaosProxy", "FAULT_KINDS", "C2S_FAULT_KINDS"]

# response-direction faults (the original classes; tests parametrize over
# exactly these — each implies a client-visible failure mode)
FAULT_KINDS = ("reset", "hang", "delay", "truncate", "garbage")
# request-direction faults (frame-granular c2s relay only); a draw on one
# pump only considers its own kinds, so arming a c2s fault never perturbs
# responses and vice versa
C2S_FAULT_KINDS = ("drop_c2s", "dup_c2s")
_ALL_KINDS = FAULT_KINDS + C2S_FAULT_KINDS


class ChaosProxy:
    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        host: str = "127.0.0.1",
        seed: int = 0,
        c2s_frames: bool = False,
    ):
        self._upstream = (upstream_host, upstream_port)
        # frame-granular client->server relay (needed for the drop_c2s /
        # dup_c2s faults); off by default — raw relay is cheaper and the
        # original five faults only touch the response direction
        self._c2s_frames = c2s_frames
        self._listener = socket.create_server((host, 0))
        self._listener.settimeout(0.2)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._rng = random.Random(seed)  # guarded-by: _lock
        # kind -> probability per response frame; drawn in FAULT_KINDS order
        self._faults: Dict[str, float] = {}  # guarded-by: _lock
        self._limit: Optional[int] = None  # guarded-by: _lock
        self.delay_s = 0.05
        self.hang_s = 30.0
        self.injected: Dict[str, int] = {k: 0 for k in _ALL_KINDS}  # guarded-by: _lock
        self._socks: list = [self._listener]  # guarded-by: _lock
        # endpoint-wide failure mode: None | "killed" | "partitioned" |
        # "hung" (the HA crash-drill primitives); guarded-by: _lock
        self._endpoint_mode: Optional[str] = None
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-accept", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self):
        return self._listener.getsockname()[:2]

    # -- fault schedule ----------------------------------------------------

    def set_fault(
        self,
        kind: Union[str, Dict[str, float], None],
        probability: float = 1.0,
        limit: Optional[int] = None,
        delay_s: Optional[float] = None,
        hang_s: Optional[float] = None,
    ) -> None:
        """Arm the schedule: one ``kind`` with ``probability``, or a
        ``{kind: probability}`` mix. ``limit`` bounds TOTAL injections
        before auto-disarm (deterministic single-fault tests use
        ``probability=1.0, limit=1``); None = unlimited. ``None`` kind
        disarms."""
        with self._lock:
            if kind is None:
                self._faults = {}
            elif isinstance(kind, str):
                if kind not in _ALL_KINDS:
                    raise ValueError(f"unknown fault {kind!r} (use {_ALL_KINDS})")
                self._faults = {kind: probability}
            else:
                bad = set(kind) - set(_ALL_KINDS)
                if bad:
                    raise ValueError(f"unknown faults {bad} (use {_ALL_KINDS})")
                self._faults = dict(kind)
            self._limit = limit
            if delay_s is not None:
                self.delay_s = delay_s
            if hang_s is not None:
                self.hang_s = hang_s

    def clear_fault(self) -> None:
        self.set_fault(None)

    # -- endpoint-level primitives (HA crash drills) -------------------------

    def kill_endpoint(self) -> None:
        """Crash the whole endpoint: every live connection dies with an RST
        and new connections are refused the same way — the kill -9 /
        instance-loss failure mode the failover gate drills. The listener
        stays bound (the address doesn't vanish, the process behind it
        did); ``restore_endpoint()`` brings it back, clients must redial."""
        with self._lock:
            self._endpoint_mode = "killed"
            conns = [s for s in self._socks if s is not self._listener]
            self._socks = [self._listener]
        for s in conns:
            self._rst_close(s)

    def partition_endpoint(self) -> None:
        """Network partition: connections stay up but no bytes cross in
        either direction; new connections are accepted, then black-holed.
        Clients see read timeouts, never a clean close."""
        with self._lock:
            self._endpoint_mode = "partitioned"

    def hang_endpoint(self) -> None:
        """Hung endpoint: requests still reach the server but every
        response is swallowed — the accepting-but-dead mode the client's
        bounded half-open probe exists for."""
        with self._lock:
            self._endpoint_mode = "hung"

    def restore_endpoint(self) -> None:
        """Clear the endpoint failure mode (connections killed or
        black-holed meanwhile stay dead — clients redial)."""
        with self._lock:
            self._endpoint_mode = None

    def endpoint_mode(self) -> Optional[str]:
        with self._lock:
            return self._endpoint_mode

    @staticmethod
    def _rst_close(s: socket.socket) -> None:
        try:
            s.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
            )
        except OSError:
            pass
        try:
            s.close()
        except OSError:
            pass

    def injected_counts(self) -> Dict[str, int]:
        """Snapshot of per-kind injection counters. The BST_LOCKCHECK sweep
        caught the callers reading ``.injected`` bare from the test thread
        while relay threads increment it — read through here instead."""
        with self._lock:
            return dict(self.injected)

    def _draw(self, kinds=FAULT_KINDS) -> Optional[str]:
        with self._lock:
            if not self._faults or self._limit == 0:
                return None
            for kind in kinds:
                p = self._faults.get(kind, 0.0)
                if p > 0 and self._rng.random() < p:
                    self.injected[kind] += 1
                    if self._limit is not None:
                        self._limit -= 1
                    return kind
            return None

    # -- plumbing ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                mode = self._endpoint_mode
            if mode == "killed":
                self._rst_close(client)  # dead process: dial answered by RST
                continue
            if mode == "partitioned":
                with self._lock:
                    self._socks.append(client)
                continue  # accepted, never relayed: the black-hole
            try:
                upstream = socket.create_connection(self._upstream, timeout=5.0)
            except OSError:
                client.close()
                continue
            with self._lock:
                self._socks += [client, upstream]
            threading.Thread(
                target=(
                    self._pump_frames_c2s if self._c2s_frames
                    else self._pump_raw
                ),
                args=(client, upstream),
                name="chaos-c2s", daemon=True,
            ).start()
            threading.Thread(
                target=self._pump_frames, args=(upstream, client),
                name="chaos-s2c", daemon=True,
            ).start()

    @staticmethod
    def _close_pair(a: socket.socket, b: socket.socket) -> None:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass

    def _read_exact(self, sock: socket.socket, n: int) -> Optional[bytes]:
        chunks = []
        while n:
            try:
                chunk = sock.recv(min(n, 1 << 20))
            except OSError:
                return None
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _pump_raw(self, src: socket.socket, dst: socket.socket) -> None:
        """client -> server: relay untouched."""
        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(1 << 16)
                except OSError:
                    break
                if not data:
                    break
                mode = self.endpoint_mode()
                if mode == "killed":
                    break
                if mode == "partitioned":
                    continue  # swallow: the partition eats the bytes
                dst.sendall(data)
        except OSError:
            pass
        finally:
            self._close_pair(src, dst)

    def _pump_frames_c2s(self, src: socket.socket, dst: socket.socket) -> None:
        """client -> server: relay at frame granularity, injecting the
        request-direction faults (drop/duplicate one frame, connection
        kept alive) — the delta-stream chaos of docs/pipelining.md."""
        try:
            while not self._stop.is_set():
                header = self._read_exact(src, proto._HEADER.size)
                if header is None:
                    break
                _, _, length = proto._HEADER.unpack(header)
                payload = b""
                if length:
                    payload = self._read_exact(src, length)
                    if payload is None:
                        break
                mode = self.endpoint_mode()
                if mode == "killed":
                    break
                if mode == "partitioned":
                    continue  # swallow: the partition eats the frame
                fault = self._draw(C2S_FAULT_KINDS)
                if fault == "drop_c2s":
                    continue  # the frame never arrives; the stream lives
                dst.sendall(header + payload)
                if fault == "dup_c2s":
                    dst.sendall(header + payload)
        except OSError:
            pass
        finally:
            self._close_pair(src, dst)

    def _pump_frames(self, src: socket.socket, dst: socket.socket) -> None:
        """server -> client: relay at frame granularity, injecting faults."""
        try:
            while not self._stop.is_set():
                header = self._read_exact(src, proto._HEADER.size)
                if header is None:
                    break
                _, _, length = proto._HEADER.unpack(header)
                payload = b""
                if length:
                    payload = self._read_exact(src, length)
                    if payload is None:
                        break
                mode = self.endpoint_mode()
                if mode == "killed":
                    break
                if mode in ("partitioned", "hung"):
                    continue  # response swallowed; keep draining upstream
                fault = self._draw()
                if fault is None:
                    dst.sendall(header + payload)
                elif fault == "delay":
                    time.sleep(self.delay_s)
                    dst.sendall(header + payload)
                elif fault == "reset":
                    # SO_LINGER 0: close sends RST, the client sees
                    # ECONNRESET instead of a clean EOF
                    dst.setsockopt(
                        socket.SOL_SOCKET,
                        socket.SO_LINGER,
                        struct.pack("ii", 1, 0),
                    )
                    break
                elif fault == "hang":
                    # black-hole: swallow the frame, go silent, then drop
                    self._stop.wait(self.hang_s)
                    break
                elif fault == "truncate":
                    dst.sendall(header + payload[: len(payload) // 2])
                    break
                elif fault == "garbage":
                    # draw under the lock: Random's state is shared with
                    # _draw across every relay thread
                    with self._lock:
                        junk = bytes(
                            self._rng.randrange(256) for _ in range(28)
                        )
                    dst.sendall(b"JUNK" + junk)
                    break
        except OSError:
            pass
        finally:
            self._close_pair(src, dst)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            socks, self._socks = self._socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
