"""Canonical simulated scenarios: the BASELINE.md measurement ladder.

Scenario 1 reproduces the reference README's resource-race demo (two
minMember=5 groups racing for ~7.1 free CPUs on one node: exactly one group
schedules). The generators scale the same shape up to the 10k-pod / 5k-node
north-star configs.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.types import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    new_uid,
)
from ..api.quantity import parse_resource_list
from ..utils.labels import POD_GROUP_LABEL

__all__ = [
    "make_sim_node",
    "make_sim_group",
    "make_member_pods",
    "race_scenario",
    "readback_tail_scenarios",
    "spot_vs_guaranteed_scenario",
    "synthetic_cluster",
    "tenant_oracle_stream",
    "XLClusterSpec",
    "xl_scan_operands",
    "xl_churn_burst",
]


def make_sim_node(
    name: str,
    allocatable: Optional[Dict] = None,
    labels: Optional[Dict] = None,
    taints: Optional[List] = None,
) -> Node:
    alloc = parse_resource_list(
        allocatable or {"cpu": "32", "memory": "128Gi", "pods": 110}, floor=True
    )
    return Node(
        metadata=ObjectMeta(name=name, uid=new_uid("node"), labels=labels or {}),
        spec=NodeSpec(taints=list(taints or [])),
        status=NodeStatus(allocatable=alloc, capacity=dict(alloc)),
    )


def make_sim_group(
    name: str,
    min_member: int,
    namespace: str = "default",
    max_schedule_time: Optional[float] = None,
    creation_ts: float = 0.0,
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=new_uid("pg"),
            creation_timestamp=creation_ts,
        ),
        spec=PodGroupSpec(
            min_member=min_member, max_schedule_time=max_schedule_time
        ),
    )


def make_member_pods(
    group: str,
    count: int,
    requests: Optional[Dict] = None,
    namespace: str = "default",
    priority: int = 0,
    node_selector: Optional[Dict] = None,
    tolerations: Optional[List] = None,
) -> List[Pod]:
    return [
        Pod(
            metadata=ObjectMeta(
                name=f"{group}-{i}",
                namespace=namespace,
                uid=new_uid("pod"),
                labels={POD_GROUP_LABEL: group},
            ),
            spec=PodSpec(
                containers=[
                    Container.from_raw(requests=requests or {"cpu": "1"})
                ],
                priority=priority,
                node_selector=dict(node_selector or {}),
                tolerations=list(tolerations or []),
            ),
        )
        for i in range(count)
    ]


def race_scenario() -> Tuple[List[Node], List[PodGroup], Dict[str, List[Pod]]]:
    """BASELINE config 1: one 8-cpu node with 0.9 cpu of system pods, two
    minMember=5 gangs of 1-cpu pods (the reference README "Example")."""
    node = make_sim_node("node-1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    # wall-clock creation stamps (offset for deterministic ordering): the
    # controller's 48h GC guard compares them against schedule_start_time
    now = time.time()
    groups = [
        make_sim_group("web-group-race1", 5, creation_ts=now - 0.002),
        make_sim_group("web-group-race2", 5, creation_ts=now - 0.001),
    ]
    pods = {
        g.metadata.name: make_member_pods(g.metadata.name, 5, {"cpu": "1"})
        for g in groups
    }
    return [node], groups, pods


def readback_tail_scenarios():
    """Shared builders for the compact-readback tail checks (used by BOTH
    benchmarks/tpu_smoke.py on hardware and tests/test_oracle.py on CPU —
    one definition, two execution contexts): a gang spanning more distinct
    nodes than ASSIGNMENT_TOP_K with remaining near the packed halfword,
    and a single node whose per-member count exceeds it.

    Returns ((wide_nodes, wide_groups), (big_nodes, big_groups))."""
    from ..ops.snapshot import GroupDemand

    wide_nodes = [
        make_sim_node(
            f"w{i:03d}", {"cpu": "64", "memory": "256Gi", "pods": "200"}
        )
        for i in range(512)
    ]
    wide_groups = [
        GroupDemand(
            full_name="default/wide",
            min_member=60000,
            member_request={"cpu": 100},
            creation_ts=0.0,
        )
    ]
    big_nodes = [
        make_sim_node(
            "big", {"cpu": "100000", "memory": "1024Gi", "pods": "70000"}
        )
    ]
    big_groups = [
        GroupDemand(
            full_name="default/huge",
            min_member=66000,
            member_request={"cpu": 1},
            creation_ts=0.0,
        )
    ]
    return (wide_nodes, wide_groups), (big_nodes, big_groups)


def spot_vs_guaranteed_scenario(
    nodes: int = 2,
    node_cpu: str = "8",
    spot_gangs: int = 2,
    guaranteed_gangs: int = 1,
    min_member: int = 4,
    member_cpu: str = "2",
    guaranteed_priority: int = 10,
):
    """Mixed-tier preemption scenario (docs/policy.md): tier-0 "spot"
    gangs sized to fill the cluster, then tier-``guaranteed_priority``
    "guaranteed" gangs that can only place by evicting spot capacity
    through the policy engine's vectorized preemption pass. Defaults: 2
    nodes x 8 cpu, 2 spot gangs x 4 members x 2 cpu = 16 cpu (exactly
    full), 1 guaranteed gang needing 8 cpu — the shape the e2e test
    proves converges deterministically; wider shapes stress the respawn
    race (docs/policy.md "Known limitation") harder.

    Returns ``(nodes, groups, pods)`` with the guaranteed pods created
    LAST (the caller controls arrival order — create spot first, wait for
    them to bind, then create guaranteed to exercise preemption rather
    than queue priority; `sim --scenario spot-vs-guaranteed` stages
    exactly that)."""
    now = time.time()
    node_objs = [
        make_sim_node(
            f"node-{i:03d}",
            {"cpu": node_cpu, "memory": "32Gi", "pods": "110"},
            labels={"zone": f"z{i % 2}"},
        )
        for i in range(nodes)
    ]
    groups, pods = [], {}
    for s in range(spot_gangs):
        name = f"spot-{s:03d}"
        groups.append(
            make_sim_group(name, min_member, creation_ts=now - 1.0 + s * 1e-3)
        )
        pods[name] = make_member_pods(
            name, min_member, {"cpu": member_cpu}, priority=0
        )
    for g in range(guaranteed_gangs):
        name = f"guaranteed-{g:03d}"
        groups.append(
            make_sim_group(name, min_member, creation_ts=now + g * 1e-3)
        )
        pods[name] = make_member_pods(
            name, min_member, {"cpu": member_cpu},
            priority=guaranteed_priority,
        )
    return node_objs, groups, pods


@dataclass
class XLClusterSpec:
    """The 100k-node / 1M-pod XL scale tier (ROADMAP "hierarchical
    scoring"): packed scan operands, not API objects — at this size the
    interesting load lives on the device, and the delta snapshot packer
    (PR 4) already made the host-side pack O(churn).

    Shape knobs model the three things that make an XL control plane hard
    for a dense O(G·N) scan:

    - **zipf-sized gangs** (``zipf_a``): a heavy-tailed gang-size mix —
      most gangs are small (place on a handful of nodes), a few are huge
      (span hundreds) — the regime where per-gang candidate sets K ≪ N.
    - **hot-pool skew** (``hot_fraction`` / ``hot_load``): a slice of the
      cluster runs near-full while the rest idles, so the tightest-first
      selection's winners concentrate in the hot pool and a coarse rank
      finds them without walking the cold tail.
    - **churn bursts** (``churn_fraction``, ``xl_churn_burst``): batched
      release/consume rewrites of a node cohort between scans — the
      steady-state input mutation a control plane at this size sees
      every tick.

    ``request_profiles`` > 1 mixes distinct member-request rows so waves
    stop being uniform and the speculative (non-mega) scan path carries
    load too; the default models the bulk-submission north-star workload.
    """

    num_nodes: int = 100_000
    num_groups: int = 4096
    lanes: int = 6
    zipf_a: float = 1.4
    gang_min: int = 2
    gang_cap: int = 512
    hot_fraction: float = 0.125
    hot_load: float = 0.85
    cold_load: float = 0.25
    churn_fraction: float = 0.02
    request_profiles: int = 1
    seed: int = 0


def xl_scan_operands(spec: XLClusterSpec):
    """Packed assignment-scan operands for one XL batch:
    ``(left[N, R], group_req[G, R], remaining[G], fit_mask[1, N],
    order[G])`` — int32 numpy, ready for ``ops.oracle.assign_gangs*`` or
    a jitted wrapper (benchmarks/xl_scaling.py). Lane 0 is cpu-like
    (millicores), lane 1 memory-like (MiB), lane 2 a pod-slot lane, the
    rest extended-resource lanes (sparse: most nodes saturate them)."""
    import numpy as np

    rng = np.random.default_rng(spec.seed)
    n, g, r = spec.num_nodes, spec.num_groups, spec.lanes
    # node capacity lanes: 64-cpu-class boxes with mild heterogeneity
    cpu = rng.choice([32_000, 64_000, 96_000], size=n, p=[0.2, 0.6, 0.2])
    mem = cpu * 4  # MiB-class numbers, same int32 domain
    pods = np.full(n, 110)
    lanes = [cpu, mem, pods]
    for _ in range(r - 3):
        # sparse extended lanes: a small slice of nodes expose capacity
        ext = np.where(rng.random(n) < 0.05, 8, 0)
        lanes.append(ext)
    capacity = np.stack(lanes[:r], axis=1).astype(np.int64)
    # hot-pool skew: a contiguous-by-shuffle cohort runs near-full
    hot = rng.random(n) < spec.hot_fraction
    load = np.where(hot, spec.hot_load, spec.cold_load)
    load = load * rng.uniform(0.85, 1.15, size=n)
    used = (capacity.astype(np.float64) * load[:, None]).astype(np.int64)
    left = np.clip(capacity - used, 0, None).astype(np.int32)

    # zipf gang sizes, clipped to [gang_min, gang_cap]
    sizes = rng.zipf(spec.zipf_a, size=g)
    remaining = np.clip(
        sizes + spec.gang_min - 1, spec.gang_min, spec.gang_cap
    ).astype(np.int32)
    # member-request profiles: 4-cpu-class members; profile > 0 varies
    # the ratio so waves mixing profiles exercise the speculative path
    profiles = []
    for p in range(max(1, spec.request_profiles)):
        row = np.zeros(r, np.int32)
        row[0] = 4_000 + 1_000 * p
        row[1] = 8_192 + 2_048 * p
        row[2] = 1
        profiles.append(row)
    which = rng.integers(0, len(profiles), size=g)
    if len(profiles) == 1:
        which[:] = 0
    group_req = np.stack([profiles[i] for i in which]).astype(np.int32)
    fit_mask = np.ones((1, n), np.int32)
    order = rng.permutation(g).astype(np.int32)
    return left, group_req, remaining, fit_mask, order


def xl_churn_burst(spec: XLClusterSpec, left, step: int):
    """One churn burst: a ``churn_fraction`` cohort of nodes releases or
    consumes capacity (gangs finishing / landing between scans). Pure
    numpy on the packed leftover — the device-side input mutation an XL
    tick loop feeds the scan; deterministic in ``(spec.seed, step)``."""
    import numpy as np

    rng = np.random.default_rng((spec.seed << 16) ^ (step + 1))
    n = left.shape[0]
    cohort = rng.random(n) < spec.churn_fraction
    scale = rng.uniform(0.5, 1.5, size=(int(cohort.sum()), 1))
    out = np.array(left, copy=True)
    out[cohort] = np.clip(
        out[cohort].astype(np.float64) * scale, 0, 2**30 - 1
    ).astype(np.int32)
    return out


@dataclass
class SyntheticSpec:
    num_nodes: int
    num_groups: int
    members_per_group: int
    node_shape: Dict = field(
        default_factory=lambda: {"cpu": "64", "memory": "256Gi", "pods": "110"}
    )
    member_request: Dict = field(default_factory=lambda: {"cpu": "4", "memory": "8Gi"})
    extended: Optional[Dict] = None  # e.g. {"nvidia.com/gpu": 8} per node
    member_extended: Optional[Dict] = None  # e.g. {"nvidia.com/gpu": 1}
    priority_classes: int = 1
    seed: int = 0


def synthetic_cluster(
    spec: SyntheticSpec,
) -> Tuple[List[Node], List[PodGroup], Dict[str, List[Pod]]]:
    """Generator for BASELINE configs 2-5: N nodes, G gangs, mixed
    priorities, optional extended resources."""
    rng = random.Random(spec.seed)
    node_shape = dict(spec.node_shape)
    if spec.extended:
        node_shape.update(spec.extended)
    nodes = [
        make_sim_node(f"node-{i:05d}", node_shape) for i in range(spec.num_nodes)
    ]
    member_request = dict(spec.member_request)
    if spec.member_extended:
        member_request.update(spec.member_extended)
    groups, pods = [], {}
    base_ts = time.time() - spec.num_groups * 1e-3
    for g in range(spec.num_groups):
        name = f"gang-{g:05d}"
        prio = rng.randrange(spec.priority_classes) if spec.priority_classes > 1 else 0
        pg = make_sim_group(
            name, spec.members_per_group, creation_ts=base_ts + g * 1e-3
        )
        groups.append(pg)
        pods[name] = make_member_pods(
            name, spec.members_per_group, member_request, priority=prio
        )
    return nodes, groups, pods


def tenant_oracle_stream(tenant: int, batches: int, nodes: int = 256,
                         gangs: int = 32, lanes: int = 4, seed: int = 0):
    """Deterministic per-tenant oracle request stream for the multi-client
    coalescer sim (docs/multitenancy.md): ``batches`` ScheduleRequests
    over one synthetic [nodes, lanes] cluster with light per-batch churn
    (a few requested rows and gang remainders move each step). Pure
    numpy — the coalescer acceptance compares plan digests between a
    coalescing sidecar and dedicated sidecars, so the SAME stream must be
    replayable against both; everything derives from (tenant, seed, batch
    index), nothing from wall-clock."""
    import numpy as np

    from ..service.protocol import ScheduleRequest

    rng = random.Random(seed * 1000003 + tenant)
    np_rng = np.random.RandomState(seed * 9176 + tenant)
    alloc = np_rng.randint(8, 96, size=(nodes, lanes)).astype("int32")
    requested = np_rng.randint(0, 6, size=(nodes, lanes)).astype("int32")
    group_req = np_rng.randint(1, 5, size=(gangs, lanes)).astype("int32")
    remaining = np_rng.randint(1, 6, size=gangs).astype("int32")
    out = []
    for b in range(batches):
        # churn: a handful of node rows and one gang's demand move
        for _ in range(4):
            row = rng.randrange(nodes)
            requested[row] = np_rng.randint(0, 6, size=lanes)
        g = rng.randrange(gangs)
        remaining[g] = rng.randrange(1, 6)
        out.append(
            ScheduleRequest(
                alloc=alloc.copy(),
                requested=requested.copy(),
                group_req=group_req.copy(),
                remaining=remaining.copy(),
                fit_mask=np.ones((1, nodes), dtype=bool),
                group_valid=np.ones(gangs, dtype=bool),
                order=np.arange(gangs, dtype="int32"),
                min_member=remaining.copy(),
                scheduled=np.zeros(gangs, dtype="int32"),
                matched=np.zeros(gangs, dtype="int32"),
                ineligible=np.zeros(gangs, dtype=bool),
                creation_rank=np.arange(gangs, dtype="int32"),
            )
        )
    return out
