"""Canonical simulated scenarios: the BASELINE.md measurement ladder.

Scenario 1 reproduces the reference README's resource-race demo (two
minMember=5 groups racing for ~7.1 free CPUs on one node: exactly one group
schedules). The generators scale the same shape up to the 10k-pod / 5k-node
north-star configs.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api.types import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    new_uid,
)
from ..api.quantity import parse_resource_list
from ..utils.labels import POD_GROUP_LABEL

__all__ = [
    "make_sim_node",
    "make_sim_group",
    "make_member_pods",
    "race_scenario",
    "readback_tail_scenarios",
    "synthetic_cluster",
]


def make_sim_node(
    name: str,
    allocatable: Optional[Dict] = None,
    labels: Optional[Dict] = None,
    taints: Optional[List] = None,
) -> Node:
    alloc = parse_resource_list(
        allocatable or {"cpu": "32", "memory": "128Gi", "pods": 110}, floor=True
    )
    return Node(
        metadata=ObjectMeta(name=name, uid=new_uid("node"), labels=labels or {}),
        spec=NodeSpec(taints=list(taints or [])),
        status=NodeStatus(allocatable=alloc, capacity=dict(alloc)),
    )


def make_sim_group(
    name: str,
    min_member: int,
    namespace: str = "default",
    max_schedule_time: Optional[float] = None,
    creation_ts: float = 0.0,
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=new_uid("pg"),
            creation_timestamp=creation_ts,
        ),
        spec=PodGroupSpec(
            min_member=min_member, max_schedule_time=max_schedule_time
        ),
    )


def make_member_pods(
    group: str,
    count: int,
    requests: Optional[Dict] = None,
    namespace: str = "default",
    priority: int = 0,
    node_selector: Optional[Dict] = None,
    tolerations: Optional[List] = None,
) -> List[Pod]:
    return [
        Pod(
            metadata=ObjectMeta(
                name=f"{group}-{i}",
                namespace=namespace,
                uid=new_uid("pod"),
                labels={POD_GROUP_LABEL: group},
            ),
            spec=PodSpec(
                containers=[
                    Container.from_raw(requests=requests or {"cpu": "1"})
                ],
                priority=priority,
                node_selector=dict(node_selector or {}),
                tolerations=list(tolerations or []),
            ),
        )
        for i in range(count)
    ]


def race_scenario() -> Tuple[List[Node], List[PodGroup], Dict[str, List[Pod]]]:
    """BASELINE config 1: one 8-cpu node with 0.9 cpu of system pods, two
    minMember=5 gangs of 1-cpu pods (the reference README "Example")."""
    node = make_sim_node("node-1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    # wall-clock creation stamps (offset for deterministic ordering): the
    # controller's 48h GC guard compares them against schedule_start_time
    now = time.time()
    groups = [
        make_sim_group("web-group-race1", 5, creation_ts=now - 0.002),
        make_sim_group("web-group-race2", 5, creation_ts=now - 0.001),
    ]
    pods = {
        g.metadata.name: make_member_pods(g.metadata.name, 5, {"cpu": "1"})
        for g in groups
    }
    return [node], groups, pods


def readback_tail_scenarios():
    """Shared builders for the compact-readback tail checks (used by BOTH
    benchmarks/tpu_smoke.py on hardware and tests/test_oracle.py on CPU —
    one definition, two execution contexts): a gang spanning more distinct
    nodes than ASSIGNMENT_TOP_K with remaining near the packed halfword,
    and a single node whose per-member count exceeds it.

    Returns ((wide_nodes, wide_groups), (big_nodes, big_groups))."""
    from ..ops.snapshot import GroupDemand

    wide_nodes = [
        make_sim_node(
            f"w{i:03d}", {"cpu": "64", "memory": "256Gi", "pods": "200"}
        )
        for i in range(512)
    ]
    wide_groups = [
        GroupDemand(
            full_name="default/wide",
            min_member=60000,
            member_request={"cpu": 100},
            creation_ts=0.0,
        )
    ]
    big_nodes = [
        make_sim_node(
            "big", {"cpu": "100000", "memory": "1024Gi", "pods": "70000"}
        )
    ]
    big_groups = [
        GroupDemand(
            full_name="default/huge",
            min_member=66000,
            member_request={"cpu": 1},
            creation_ts=0.0,
        )
    ]
    return (wide_nodes, wide_groups), (big_nodes, big_groups)


@dataclass
class SyntheticSpec:
    num_nodes: int
    num_groups: int
    members_per_group: int
    node_shape: Dict = field(
        default_factory=lambda: {"cpu": "64", "memory": "256Gi", "pods": "110"}
    )
    member_request: Dict = field(default_factory=lambda: {"cpu": "4", "memory": "8Gi"})
    extended: Optional[Dict] = None  # e.g. {"nvidia.com/gpu": 8} per node
    member_extended: Optional[Dict] = None  # e.g. {"nvidia.com/gpu": 1}
    priority_classes: int = 1
    seed: int = 0


def synthetic_cluster(
    spec: SyntheticSpec,
) -> Tuple[List[Node], List[PodGroup], Dict[str, List[Pod]]]:
    """Generator for BASELINE configs 2-5: N nodes, G gangs, mixed
    priorities, optional extended resources."""
    rng = random.Random(spec.seed)
    node_shape = dict(spec.node_shape)
    if spec.extended:
        node_shape.update(spec.extended)
    nodes = [
        make_sim_node(f"node-{i:05d}", node_shape) for i in range(spec.num_nodes)
    ]
    member_request = dict(spec.member_request)
    if spec.member_extended:
        member_request.update(spec.member_extended)
    groups, pods = [], {}
    base_ts = time.time() - spec.num_groups * 1e-3
    for g in range(spec.num_groups):
        name = f"gang-{g:05d}"
        prio = rng.randrange(spec.priority_classes) if spec.priority_classes > 1 else 0
        pg = make_sim_group(
            name, spec.members_per_group, creation_ts=base_ts + g * 1e-3
        )
        groups.append(pg)
        pods[name] = make_member_pods(
            name, spec.members_per_group, member_request, priority=prio
        )
    return nodes, groups, pods
