"""SimCluster: the full framework wired over a simulated cluster.

Composes API server + informers + ClusterState + Scheduler + plugin runtime
(operation/controller/leader gate) + SimKubelet into one in-process system —
the test/bench harness standing in for a real Kubernetes deployment, sized
for anything from the README race demo to 10k pods / 5k nodes.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ..api.types import Node, Pod, PodGroup, PodGroupPhase, PodPhase, to_dict
from ..client.apiserver import APIServer
from ..client.clientset import Clientset
from ..client.informers import SharedInformerFactory
from ..framework.cluster import ClusterState
from ..framework.scheduler import Scheduler
from ..plugin.factory import PluginConfig, new_plugin_runtime
from ..utils.labels import POD_GROUP_LABEL
from .kubelet import SimKubelet

__all__ = ["SimCluster", "drive_multi_client", "wait_p95"]


def wait_p95(xs):
    """p95 by sorted index over a non-empty sample list — ONE copy of the
    percentile convention shared by ``sim --multi-client``'s report and
    the coalesce gate's enforced starvation bound, so the CLI can never
    report a different p95 than the gate checks."""
    xs = sorted(xs)
    return xs[min(int(len(xs) * 0.95), len(xs) - 1)]


def drive_multi_client(
    addr: str,
    clients: int = 8,
    batches: int = 8,
    nodes: int = 256,
    gangs: int = 32,
    concurrent: bool = True,
    seed: int = 0,
    deadline_ms: Optional[int] = None,
    tenant_batches: Optional[Dict[str, int]] = None,
    client_kwargs: Optional[Dict] = None,
    on_batch: Optional[Callable[[str, int], None]] = None,
):
    """Drive K scheduler clients' oracle request streams through ONE
    sidecar (docs/multitenancy.md "Multi-client sim") — the coalescer
    acceptance harness, shared by ``sim --multi-client``, ``make
    bench-coalesce`` and the tests.

    Each client is a ResilientOracleClient with its own tenant label
    (``tenant-<i>``) replaying the deterministic
    ``sim.scenarios.tenant_oracle_stream`` for that tenant.
    ``concurrent=True`` runs every client on its own thread (the
    coalesced deployment); ``False`` runs them strictly one request at a
    time in round-robin (the "K dedicated sidecars, time-sliced over one
    device" equivalent — same total device work, no overlap). The same
    (clients, batches, nodes, gangs, seed) always replays the same
    streams, so per-tenant plan digests compare across deployments.

    ``tenant_batches`` overrides the per-tenant batch count (whale
    scenarios: {"tenant-0": 64} floods tenant 0 while the rest stay at
    ``batches``).

    ``addr`` may be a comma list (``"h1:p1,h2:p2"``) — each client then
    gets the whole warm-standby pool and promotes on DRAINING /
    breaker-open (docs/resilience.md "High availability"); the failover
    gate drives a storm through exactly this. ``client_kwargs`` forwards
    extra ResilientOracleClient options (the gate tightens
    retry/breaker budgets so a crash promotes within one call; callable
    values are invoked per client — pass a CircuitBreaker FACTORY, not a
    shared instance);
    ``on_batch(tenant, index)`` observes each completed request (the
    gate's mid-storm kill trigger).

    Returns ``{tenant: {"digests": [...], "waits": [...], "busy": int}}``
    plus a ``"_wall_s"`` entry with the run's wall-clock."""
    import numpy as np

    from ..service.client import ResilientOracleClient
    from ..utils import audit as audit_mod
    from ..utils.errors import OracleBusyError
    from .scenarios import tenant_oracle_stream

    def digest(resp) -> str:
        return audit_mod.plan_digest(
            {
                "gang_feasible": np.asarray(resp.gang_feasible),
                "placed": np.asarray(resp.placed),
                "progress": np.asarray(resp.progress),
                "best": int(resp.best),
                "best_exists": bool(resp.best_exists),
                "assignment_nodes": np.asarray(resp.assignment_nodes),
                "assignment_counts": np.asarray(resp.assignment_counts),
            }
        )

    labels = [f"tenant-{i}" for i in range(clients)]
    streams = {
        labels[i]: tenant_oracle_stream(
            i,
            (tenant_batches or {}).get(labels[i], batches),
            nodes=nodes,
            gangs=gangs,
            seed=seed,
        )
        for i in range(clients)
    }
    out: Dict[str, Dict] = {
        t: {"digests": [], "waits": [], "busy": 0} for t in labels
    }
    def _client_kwargs() -> Dict:
        # callable values are invoked PER CLIENT — a CircuitBreaker is
        # stateful, so the failover gate passes a factory rather than
        # sharing one instance across every tenant's connection
        return {
            k: (v() if callable(v) else v)
            for k, v in (client_kwargs or {}).items()
        }

    conns = {
        t: ResilientOracleClient(
            addr, deadline_ms=deadline_ms, name=t, **_client_kwargs()
        )
        for t in labels
    }

    def run_one(tenant: str, req, index: int = 0) -> None:
        t0 = time.perf_counter()
        try:
            resp = conns[tenant].schedule(req, tenant=tenant)
        except OracleBusyError:
            # retries exhausted while saturated: count it and move on —
            # the driver measures the bound, it doesn't crash on it
            out[tenant]["busy"] += 1
            return
        out[tenant]["waits"].append(time.perf_counter() - t0)
        out[tenant]["digests"].append(digest(resp))
        if on_batch is not None:
            on_batch(tenant, index)

    wall0 = time.perf_counter()
    if concurrent:
        import threading

        def run_tenant(tenant: str) -> None:
            for i, req in enumerate(streams[tenant]):
                run_one(tenant, req, i)

        threads = [
            threading.Thread(
                target=run_tenant, args=(t,), name=f"mc-{t}", daemon=True
            )
            for t in labels
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
    else:
        # the time-sliced dedicated equivalent: one request in flight
        # EVER, round-robin across tenants (one device, K sidecars that
        # each get the device serially)
        cursors = {t: 0 for t in labels}
        live = set(labels)
        while live:
            for t in list(labels):
                if t not in live:
                    continue
                i = cursors[t]
                if i >= len(streams[t]):
                    live.discard(t)
                    continue
                run_one(t, streams[t][i], i)
                cursors[t] = i + 1
    wall = time.perf_counter() - wall0
    for conn in conns.values():
        conn.close()
    result: Dict = dict(out)
    result["_wall_s"] = wall
    return result


class SimCluster:
    def __init__(
        self,
        scorer: str = "oracle",
        max_schedule_minutes: Optional[float] = None,
        kubelet_start_delay: float = 0.02,
        kubelet_run_duration: Optional[float] = None,
        fail_pod: Optional[Callable[[str], bool]] = None,
        bind_workers: int = 8,
        backoff_base: float = 0.2,
        backoff_cap: float = 2.0,
        controller_resync_seconds: float = 0.1,
        enabled_points=None,
        min_batch_interval: float = 0.0,
        oracle_background_refresh: bool = False,
        oracle_dispatch_ahead: bool = False,
        oracle_compile_warmer: bool = False,
        audit_log=None,
        identity_audit_every: int = 0,
        policy=None,
        api=None,
    ):
        # ``api``: any APIServer-interface implementation — pass an
        # HTTPAPIServer to run the WHOLE stack (scheduler, plugin runtime,
        # controller, informers, kubelet) against a remote k8s-shaped
        # endpoint instead of the in-memory server
        self.api = api if api is not None else APIServer()
        self.clientset = Clientset(self.api)
        self.cluster = ClusterState()

        kwargs = {} if enabled_points is None else {"enabled_points": frozenset(enabled_points)}
        config = PluginConfig(
            scorer=scorer,
            max_schedule_minutes=max_schedule_minutes,
            controller_resync_seconds=controller_resync_seconds,
            min_batch_interval_seconds=min_batch_interval,
            oracle_background_refresh=oracle_background_refresh,
            oracle_dispatch_ahead=oracle_dispatch_ahead,
            oracle_compile_warmer=oracle_compile_warmer,
            oracle_audit_log=audit_log,
            oracle_identity_audit_every=identity_audit_every,
            # policy engine config (batch_scheduler_tpu.policy.PolicyConfig);
            # None reads BST_POLICY from the environment
            policy=policy,
            **kwargs,
        )
        self.runtime = None

        # framework informers: nodes + pods feed ClusterState and the queue;
        # shared with the plugin runtime so each event dispatches once
        self._fwk_informers = SharedInformerFactory(self.api)

        def plugin_factory(handle):
            self.runtime = new_plugin_runtime(
                self.api, handle, config, informers=self._fwk_informers
            )
            return self.runtime.plugin
        self.scheduler = Scheduler(
            self.clientset,
            self.cluster,
            plugin_factory=plugin_factory,
            bind_workers=bind_workers,
            backoff_base=backoff_base,
            backoff_cap=backoff_cap,
            pod_informer=self._fwk_informers.informer("Pod"),
        )
        self.kubelet = SimKubelet(
            self.api,
            start_delay=kubelet_start_delay,
            run_duration=kubelet_run_duration,
            fail_pod=fail_pod,
        )

        self._fwk_informers.informer("Node").add_event_handler(
            on_add=self.cluster.add_node,
            on_update=lambda old, new: self.cluster.update_node(new),
            on_delete=lambda n: self.cluster.remove_node(n.metadata.name),
        )
        # all Pod events ride the raw fast path: ADDED seeds the queue with
        # a lazy entry (typed pod materialises on the scheduling thread),
        # bind commits and kubelet phase flips are ~3 MODIFIED events per
        # pod and never need typed rehydration (observe_pod_raw)
        self._fwk_informers.informer("Pod").add_event_handler(
            on_add=self._pod_added_raw,
            on_update=lambda old, new: self.cluster.observe_pod_raw(new),
            on_delete=self.cluster.remove_pod_raw,
            raw=True,
        )
        self._started = False

    def _pod_added_raw(self, d: dict) -> None:
        if (d.get("spec") or {}).get("node_name"):
            self.cluster.observe_pod_raw(d)
        else:
            self.scheduler.enqueue_raw(d)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._fwk_informers.start()
        self.runtime.start()
        self.kubelet.start()
        self._fwk_informers.wait_for_cache_sync()
        self.runtime.informers.wait_for_cache_sync()
        self._wait_for_status_cache()
        self.scheduler.start()

    def _wait_for_status_cache(self, timeout: float = 10.0) -> None:
        """Block until the leader-gated controller has synced every
        already-created PodGroup into the gang status cache — the analog of
        kube-scheduler's WaitForCacheSync barrier. Without it the first
        scheduling cycles race the controller's lease acquisition (~1s poll)
        and burn pod backoff attempts on PodGroupNotFound."""
        want = {
            f"{pg['metadata']['namespace']}/{pg['metadata']['name']}"
            for pg in self.api.list("PodGroup")
        }
        if not want:
            return
        cache = self.runtime.operation.status_cache
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(cache.get(name) is not None for name in want):
                return
            time.sleep(0.01)

    def stop(self) -> None:
        self.scheduler.stop()
        self.kubelet.stop()
        self.runtime.stop()
        self._fwk_informers.stop()

    # -- populate ----------------------------------------------------------

    def add_nodes(self, nodes: List[Node]) -> None:
        create_many = getattr(self.api, "create_many", None)
        if create_many is not None:
            docs = []
            for node in nodes:
                d = to_dict(node)
                d.setdefault("metadata", {})["namespace"] = ""  # cluster-scoped
                docs.append(d)
            create_many("Node", docs, assume_fresh=True)
            return
        for node in nodes:
            self.clientset.nodes().create(node)

    def create_group(self, pg: PodGroup) -> PodGroup:
        return self.clientset.podgroups(pg.metadata.namespace).create(pg)

    def create_pods(self, pods: List[Pod]) -> None:
        # bulk ingest when the API supports it: load generation must not
        # serialize on per-pod response copies it never reads
        create_many = getattr(self.api, "create_many", None)
        if create_many is not None:
            create_many(
                "Pod", [to_dict(pod) for pod in pods], assume_fresh=True
            )
            return
        for pod in pods:
            self.clientset.pods(pod.metadata.namespace).create(pod)

    def create_pod_docs(self, docs: List[dict]) -> None:
        """Raw-dict bulk ingest: the caller already serialized the
        documents (load-generator-side work — a real client ships JSON it
        built on its own clock). The store takes ownership
        (assume_fresh); the docs must not be retained by the caller."""
        create_many = getattr(self.api, "create_many", None)
        if create_many is not None:
            create_many("Pod", docs, assume_fresh=True)
            return
        # fallback (e.g. HTTP API without the bulk verb): rehydrate — the
        # typed clientset serializes dataclasses, not raw dicts
        from ..api.serde import pod_from_dict

        for d in docs:
            pod = pod_from_dict(d)
            self.clientset.pods(pod.metadata.namespace).create(pod)

    # -- observation -------------------------------------------------------

    def group(self, name: str, namespace: str = "default") -> PodGroup:
        return self.clientset.podgroups(namespace).get(name)

    def group_phase(self, name: str, namespace: str = "default") -> PodGroupPhase:
        return self.group(name, namespace).status.phase

    def member_pods(self, group: str, namespace: str = "default") -> List[Pod]:
        return self.clientset.pods(namespace).list(
            label_selector={POD_GROUP_LABEL: group}
        )

    def member_phase_counts(self, group: str, namespace: str = "default") -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for pod in self.member_pods(group, namespace):
            phase = pod.status.phase.value if pod.spec.node_name else "Unscheduled"
            counts[phase] = counts.get(phase, 0) + 1
        return counts

    def decisions(self, group: Optional[str] = None) -> Dict[str, list]:
        """The gang decision flight recorder's records (utils.trace):
        why a gang was placed/denied/parked, with blame reasons and the
        device evidence — the harness-side view of /debug/decisions.
        ``group`` may be "name" (default namespace assumed) or
        "namespace/name"."""
        from ..utils.trace import DEFAULT_FLIGHT_RECORDER

        if group is not None and "/" not in group:
            group = f"default/{group}"
        return DEFAULT_FLIGHT_RECORDER.snapshot(group)

    def health(self) -> Dict:
        """The live SLO health model's verdict (utils.health) — the
        harness-side view of /debug/health, so tests and gates can assert
        ok/warn/breach without standing up the metrics endpoint."""
        from ..utils.health import DEFAULT_HEALTH

        return DEFAULT_HEALTH.evaluate()

    def explain(self, group: str) -> Dict:
        """Why is this gang pending (core.explain) — the harness-side
        view of /debug/explain. ``group`` may be "name" (default
        namespace assumed) or "namespace/name"."""
        from ..core.explain import active_observatory

        if "/" not in group:
            group = f"default/{group}"
        obs = active_observatory()
        if obs is None:
            return {"error": "no observatory (oracle mode required)"}
        return obs.explain(group)

    def capacity(self) -> Dict:
        """The capacity observatory's report (ops.capacity) — the
        harness-side view of /debug/capacity: last summary, downsampled
        series, sampler counters."""
        from ..ops.capacity import capacity_debug_view

        payload, _status = capacity_debug_view()
        return payload

    def whatif(self, counterfactual: Dict, rung: str = "steady") -> Dict:
        """Score one counterfactual against live cluster state on a
        forked device-state buffer (core.explain) — the harness-side view
        of /debug/whatif. ``counterfactual`` is the canonical dict form
        (e.g. ``{"kind": "drain", "node": "sim-node-0000"}``)."""
        from ..core.explain import active_observatory

        obs = active_observatory()
        if obs is None:
            return {"error": "no observatory (oracle mode required)"}
        return obs.whatif(dict(counterfactual), rung=rung)

    def wait_for(
        self,
        predicate: Callable[[], bool],
        timeout: float = 15.0,
        interval: float = 0.05,
    ) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(interval)
        return predicate()

    def wait_for_group_phase(
        self,
        name: str,
        phases,
        timeout: float = 15.0,
        namespace: str = "default",
    ) -> bool:
        if isinstance(phases, PodGroupPhase):
            phases = (phases,)
        return self.wait_for(
            lambda: self.group_phase(name, namespace) in phases, timeout
        )

    def wait_for_bound(self, group: str, count: int, timeout: float = 15.0) -> bool:
        return self.wait_for(
            lambda: sum(1 for p in self.member_pods(group) if p.spec.node_name)
            >= count,
            timeout,
        )
