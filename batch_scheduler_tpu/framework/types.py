"""Scheduling-framework data types: pod queue entries and cycle statuses.

Shapes mirror the k8s scheduler framework v1alpha1 surface the reference
plugs into (PodInfo with queue timestamp, Status codes Success/
Unschedulable/Wait/Error) without depending on it.
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass
from typing import Optional

from ..api.types import Pod
from ..utils.labels import POD_GROUP_LABEL

__all__ = ["PodInfo", "StatusCode", "CycleStatus"]

_seq = itertools.count(1)


class PodInfo:
    """One queue entry. Constructed either from a typed ``Pod`` or from the
    informer's RAW stored dict (``raw=``): the raw form defers the deep
    copy + rehydrate to first ``.pod`` access on the scheduling thread, so
    the watch-dispatch thread (which feeds the queue and every other
    event consumer) only parses the handful of scalars the queue itself
    needs — at 10k pods the per-event typed materialisation was the
    dispatch thread's dominant cost.

    The scalar fields (``namespace``/``name``/``uid``/``priority``/
    ``gang``) are snapshot at construction and power the queue comparator
    and gang index without touching ``.pod``."""

    __slots__ = (
        "_pod",
        "raw",
        "timestamp",
        "attempts",
        "seq",
        "namespace",
        "name",
        "uid",
        "priority",
        "gang",
    )

    def __init__(
        self,
        pod: Optional[Pod] = None,
        timestamp: float = 0.0,
        attempts: int = 0,
        raw: Optional[dict] = None,
    ):
        if pod is None and raw is None:
            raise ValueError("PodInfo needs a pod or a raw dict")
        self._pod = pod
        self.raw = raw
        self.timestamp = timestamp
        self.attempts = attempts
        # Monotonic tiebreak so heap ordering is total even when Less()
        # says neither pod precedes the other.
        self.seq = next(_seq)
        if pod is not None:
            self.namespace = pod.metadata.namespace
            self.name = pod.metadata.name
            self.uid = pod.metadata.uid
            # nullable in external JSON: an explicit null must not poison
            # the queue's -priority sort key on the watch-dispatch thread
            self.priority = pod.spec.priority or 0
            self.gang = (pod.metadata.labels or {}).get(POD_GROUP_LABEL, "")
        else:
            meta = raw.get("metadata") or {}
            self.namespace = meta.get("namespace", "default")
            self.name = meta.get("name", "")
            self.uid = meta.get("uid", "")
            self.priority = (raw.get("spec") or {}).get("priority") or 0
            self.gang = (meta.get("labels") or {}).get(POD_GROUP_LABEL, "")

    @property
    def pod(self) -> Pod:
        if self._pod is None:
            from ..api.serde import pod_from_dict

            # no defensive deepcopy: pod_from_dict copies every nested
            # container it keeps (dict()/list builds), so the typed object
            # shares nothing mutable with the informer's stored dict
            self._pod = pod_from_dict(self.raw)
        return self._pod

    @pod.setter
    def pod(self, value: Pod) -> None:
        self._pod = value


class StatusCode(enum.Enum):
    SUCCESS = "Success"
    UNSCHEDULABLE = "Unschedulable"
    WAIT = "Wait"
    ERROR = "Error"


@dataclass
class CycleStatus:
    code: StatusCode
    message: str = ""
    # for WAIT: permit timeout in seconds
    timeout: float = 0.0
