"""Scheduling-framework data types: pod queue entries and cycle statuses.

Shapes mirror the k8s scheduler framework v1alpha1 surface the reference
plugs into (PodInfo with queue timestamp, Status codes Success/
Unschedulable/Wait/Error) without depending on it.
"""

from __future__ import annotations

import enum
import itertools
from dataclasses import dataclass, field

from ..api.types import Pod

__all__ = ["PodInfo", "StatusCode", "CycleStatus"]

_seq = itertools.count(1)


@dataclass
class PodInfo:
    pod: Pod
    timestamp: float = 0.0
    attempts: int = 0
    # Monotonic tiebreak so heap ordering is total even when Less() says
    # neither pod precedes the other.
    seq: int = field(default_factory=lambda: next(_seq))


class StatusCode(enum.Enum):
    SUCCESS = "Success"
    UNSCHEDULABLE = "Unschedulable"
    WAIT = "Wait"
    ERROR = "Error"


@dataclass
class CycleStatus:
    code: StatusCode
    message: str = ""
    # for WAIT: permit timeout in seconds
    timeout: float = 0.0
