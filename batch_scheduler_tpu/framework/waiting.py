"""Waiting pods: the Permit gate's parking lot.

Event-driven equivalent of the k8s framework's waitingPodsMap + per-pod
goroutine: a pod whose Permit returns Wait parks here with a deadline; the
gang-release choreography resolves it via ``allow``/``reject``
(reference batchscheduler.go:310-343,347-354), and a single timer thread
enforces deadlines. Resolution is pushed onto a ready queue consumed by the
bind worker pool — no thread blocks per waiting pod, so 10k parked pods
cost zero threads.
"""

from __future__ import annotations

import heapq
import queue
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..api.types import Pod

__all__ = ["WaitingPod", "WaitingPods"]

ALLOW = "allow"
REJECT = "reject"
TIMEOUT = "timeout"


class WaitingPod:
    def __init__(self, pod: Pod, node_name: str, deadline: float):
        self.pod = pod
        self.node_name = node_name
        self.deadline = deadline
        self._lock = threading.Lock()
        self._outcome: Optional[Tuple[str, str]] = None  # guarded-by: _lock
        # written once by park() before the pod is published (single-thread
        # phase); read under _lock thereafter
        self._sink: Optional[Callable[["WaitingPod", str, str], None]] = None  # guarded-by: _lock

    def get_pod(self) -> Pod:
        return self.pod

    def _resolve(self, outcome: str, message: str) -> bool:
        with self._lock:
            if self._outcome is not None:
                return False
            self._outcome = (outcome, message)
            sink = self._sink
        if sink is not None:
            sink(self, outcome, message)
        return True

    def allow(self, plugin_name: str) -> bool:
        """Release the pod to bind (reference waitingPod.Allow)."""
        return self._resolve(ALLOW, plugin_name)

    def reject(self, message: str) -> bool:
        """Fail the pod's wait (reference waitingPod.Reject)."""
        return self._resolve(REJECT, message)


class WaitingPods:
    """Registry + deadline enforcement + resolution fan-in."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.RLock()
        self._pods: Dict[str, WaitingPod] = {}  # guarded-by: _lock
        self._deadlines: list = []  # heap of (deadline, uid); guarded-by: _lock
        self.resolved: "queue.Queue[Tuple[WaitingPod, str, str]]" = queue.Queue()
        self._stop = threading.Event()
        self._timer = threading.Thread(
            target=self._timer_loop, name="permit-timeouts", daemon=True
        )
        self._timer.start()

    def park(self, wp: WaitingPod) -> None:
        # sink BEFORE publishing: once the pod is visible in _pods, a
        # concurrent allow()/reject() must find the sink or its resolution
        # would be lost and the gang stuck one bind short
        wp._sink = self._on_resolved
        with self._lock:
            self._pods[wp.pod.metadata.uid] = wp
            heapq.heappush(self._deadlines, (wp.deadline, wp.pod.metadata.uid))

    def get(self, uid: str) -> Optional[WaitingPod]:
        with self._lock:
            return self._pods.get(uid)

    def iterate(self, fn: Callable[[WaitingPod], None]) -> None:
        """reference frameworkHandler.IterateOverWaitingPods."""
        with self._lock:
            pods = list(self._pods.values())
        for wp in pods:
            fn(wp)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pods)

    def _on_resolved(self, wp: WaitingPod, outcome: str, message: str) -> None:
        with self._lock:
            self._pods.pop(wp.pod.metadata.uid, None)
        self.resolved.put((wp, outcome, message))

    def _timer_loop(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(0.05)
            now = self._clock()
            expired = []
            with self._lock:
                while self._deadlines and self._deadlines[0][0] <= now:
                    _, uid = heapq.heappop(self._deadlines)
                    wp = self._pods.get(uid)
                    if wp is not None:
                        expired.append(wp)
            for wp in expired:
                wp._resolve(TIMEOUT, "permit wait deadline exceeded")

    def close(self) -> None:
        self._stop.set()
