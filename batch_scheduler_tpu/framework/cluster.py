"""ClusterState: the scheduler's live view of nodes and pod placements.

Plays the role of kube-scheduler's scheduler cache + snapshot shared lister
(what the reference reads via frameworkHandler.SnapshotSharedLister(),
core.go:437,567): nodes, per-node requested resources from bound pods, and
*assumed* pods — pods the scheduler has decided to place but whose binds
have not committed — so successive scheduling cycles see reserved capacity.

Implements core.ClusterStateProvider, so both the serial scorer and the
oracle snapshot pack straight from here.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

from ..api.types import Node, Pod, PodPhase

__all__ = ["ClusterState"]

_TERMINAL = (PodPhase.SUCCEEDED, PodPhase.FAILED)


class ClusterState:
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[str, Node] = {}  # guarded-by: _lock
        # node -> pod uid -> canonical requested resources (incl. pod slot)
        self._requested: Dict[str, Dict[str, Dict[str, int]]] = {}  # guarded-by: _lock
        # pod uid -> node, for pods assumed but not yet observed bound
        self._assumed: Dict[str, str] = {}  # guarded-by: _lock
        self._pod_nodes: Dict[str, str] = {}  # guarded-by: _lock
        # pod uid -> Pod object, for victim search in the preemption cycle
        self._pod_objs: Dict[str, Pod] = {}  # guarded-by: _lock
        # bumped on every capacity-relevant change; the oracle scorer uses it
        # to invalidate its batch without explicit mark_dirty plumbing
        self._version = 0  # guarded-by: _lock
        # event subscribers (ops.events.EventLog.note_bump, weakly held):
        # the emission invariant is ONE _emit per _version += 1, each
        # naming the nodes whose requested view changed under that bump —
        # subscribers prove fold completeness by matching bump counts
        # against version deltas (docs/pipelining.md "Event ingest")
        self._event_subs: list = []  # guarded-by: _lock

    def subscribe_events(self, fn) -> None:
        """Register a bound method called as ``fn(kind, names)`` once per
        version bump, under the cluster lock (callees must not call back
        into this state). ``kind`` is ``"node-object"`` for node add /
        update / remove (structural — lane schema may move) and
        ``"node-requested"`` for capacity accounting; ``names`` lists the
        affected node names. Held via weakref: a collected subscriber is
        pruned, never leaked."""
        with self._lock:
            self._event_subs.append(weakref.WeakMethod(fn))

    def _emit(self, kind: str, names=()) -> None:  # lock-held: _lock
        if not self._event_subs:
            return
        dead = []
        for ref in self._event_subs:
            fn = ref()
            if fn is None:
                dead.append(ref)
                continue
            try:
                fn(kind, names)
            except Exception:  # noqa: BLE001 — a broken subscriber must
                pass  # never poison informer handling; fold just degrades
        for ref in dead:
            self._event_subs.remove(ref)

    def version(self) -> int:
        with self._lock:
            return self._version

    # -- node lifecycle ----------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.metadata.name] = node
            self._requested.setdefault(node.metadata.name, {})
            self._version += 1
            self._emit("node-object", (node.metadata.name,))

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            self._nodes.pop(name, None)
            self._requested.pop(name, None)
            self._version += 1
            self._emit("node-object", (name,))

    # -- pod lifecycle -----------------------------------------------------

    @staticmethod
    def _require(pod: Pod) -> Dict[str, int]:
        req = dict(pod.resource_require())
        req["pods"] = req.get("pods", 0) + 1
        return req

    def assume(self, pod: Pod, node_name: str) -> None:
        """Reserve the pod's resources on the node before bind commits."""
        with self._lock:
            uid = pod.metadata.uid
            # a re-assume after a failed cycle must release the old node
            prev = self._pod_nodes.get(uid)
            if prev is not None and prev != node_name:
                self._requested.get(prev, {}).pop(uid, None)
            self._requested.setdefault(node_name, {})[uid] = self._require(pod)
            self._assumed[uid] = node_name
            self._pod_nodes[uid] = node_name
            self._pod_objs[uid] = pod
            self._version += 1
            touched = (
                (node_name,) if prev in (None, node_name)
                else (node_name, prev)
            )
            self._emit("node-requested", touched)

    def assume_many(self, pairs) -> None:
        """Batch form of :meth:`assume` — one lock pass for a whole gang's
        seat assignment (per-member acquisitions contend with the watch
        handlers ~quorum times per gang). ``pairs``: (pod, node_name)."""
        with self._lock:
            for pod, node_name in pairs:
                uid = pod.metadata.uid
                prev = self._pod_nodes.get(uid)
                if prev is not None and prev != node_name:
                    self._requested.get(prev, {}).pop(uid, None)
                self._requested.setdefault(node_name, {})[uid] = self._require(
                    pod
                )
                self._assumed[uid] = node_name
                self._pod_nodes[uid] = node_name
                self._pod_objs[uid] = pod
                touched = (
                    (node_name,) if prev in (None, node_name)
                    else (node_name, prev)
                )
                self._emit("node-requested", touched)
            self._version += len(pairs)

    def forget(self, pod_uid: str) -> None:
        """Drop an assumed pod whose permit/bind failed."""
        with self._lock:
            node = self._assumed.pop(pod_uid, None)
            if node is None:
                return
            self._pod_nodes.pop(pod_uid, None)
            self._pod_objs.pop(pod_uid, None)
            self._requested.get(node, {}).pop(pod_uid, None)
            self._version += 1
            self._emit("node-requested", (node,))

    def finish_binding(self, pod_uid: str) -> None:
        with self._lock:
            self._assumed.pop(pod_uid, None)

    def finish_binding_many(self, pod_uids) -> None:
        with self._lock:
            for uid in pod_uids:
                self._assumed.pop(uid, None)

    def observe_pod(self, pod: Pod) -> None:
        """Apply an informer event for a pod: bound pods charge their node,
        terminal pods release it.

        The version only bumps when the *capacity view* actually changes:
        the assumed→bound transition of a pod already charged to the same
        node with the same request is a no-op here, so a gang member's bind
        commit does not invalidate the oracle batch that planned it."""
        if not pod.spec.node_name:
            return
        with self._lock:
            uid = pod.metadata.uid
            node = pod.spec.node_name
            if pod.status.phase in _TERMINAL:
                charged = self._requested.get(node, {}).pop(uid, None)
                known = self._pod_nodes.pop(uid, None)
                self._assumed.pop(uid, None)
                self._pod_objs.pop(uid, None)
                if charged is not None or known is not None:
                    self._version += 1
                    self._emit("node-requested", (node,))
                return
            req = self._require(pod)
            unchanged = (
                self._pod_nodes.get(uid) == node
                and self._requested.get(node, {}).get(uid) == req
            )
            prev = self._pod_nodes.get(uid)
            if prev is not None and prev != node:
                self._requested.get(prev, {}).pop(uid, None)
            self._requested.setdefault(node, {})[uid] = req
            self._pod_nodes[uid] = node
            self._pod_objs[uid] = pod
            self._assumed.pop(uid, None)
            if not unchanged:
                self._version += 1
                touched = (
                    (node,) if prev in (None, node) else (node, prev)
                )
                self._emit("node-requested", touched)

    def observe_pod_raw(self, d: dict) -> None:
        """Raw-dict fast path for pod watch events (the informer's ``raw``
        handler form): terminal phases release by uid, and a pod already
        charged to the same node is a no-op WITHOUT parsing its resource
        quantities — k8s pod requests are immutable, so same (uid, node)
        implies same charge. Only a placement this state has never charged
        (an external/bound-elsewhere pod) pays typed rehydration and
        delegates to :meth:`observe_pod`."""
        spec = d.get("spec") or {}
        node = spec.get("node_name")
        if not node:
            return
        meta = d.get("metadata") or {}
        uid = meta.get("uid", "")
        phase = (d.get("status") or {}).get("phase") or "Pending"
        with self._lock:
            if phase in ("Succeeded", "Failed"):
                charged = self._requested.get(node, {}).pop(uid, None)
                known = self._pod_nodes.pop(uid, None)
                self._assumed.pop(uid, None)
                self._pod_objs.pop(uid, None)
                if charged is not None or known is not None:
                    self._version += 1
                    self._emit("node-requested", (node,))
                return
            if self._pod_nodes.get(uid) == node:
                self._assumed.pop(uid, None)  # bind commit observed
                return
        from ..api.serde import pod_from_dict

        # no defensive deepcopy: pod_from_dict copies every nested
        # container it keeps (same contract PodInfo.pod relies on)
        self.observe_pod(pod_from_dict(d))

    def remove_pod(self, pod: Pod) -> None:
        self._remove_uid(pod.metadata.uid)

    def remove_pod_raw(self, d: dict) -> None:
        self._remove_uid(((d.get("metadata") or {}).get("uid", "")))

    def _remove_uid(self, uid: str) -> None:
        with self._lock:
            node = self._pod_nodes.pop(uid, None)
            self._assumed.pop(uid, None)
            self._pod_objs.pop(uid, None)
            if node is not None:
                self._requested.get(node, {}).pop(uid, None)
                self._version += 1
                self._emit("node-requested", (node,))

    # -- ClusterStateProvider ---------------------------------------------

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def node_requested(self, node_name: str) -> Dict[str, int]:
        with self._lock:
            total: Dict[str, int] = {}
            for req in self._requested.get(node_name, {}).values():
                for k, v in req.items():
                    total[k] = total.get(k, 0) + v
            return total

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(name)

    def pod_count(self, node_name: str) -> int:
        with self._lock:
            return len(self._requested.get(node_name, {}))

    def pods_on(self, node_name: str) -> List[Pod]:
        """Pods currently charged to a node (bound or assumed) — the victim
        candidate set for the preemption cycle."""
        with self._lock:
            return [
                self._pod_objs[uid]
                for uid in self._requested.get(node_name, {})
                if uid in self._pod_objs
            ]

    def is_assumed(self, pod_uid: str) -> bool:
        with self._lock:
            return pod_uid in self._assumed
