"""Priority scheduling queue with a pluggable queue-sort comparator.

The active queue orders by the QueueSort plugin's Less (the reference's
Compare chain: priority -> group creation time -> name -> pod timestamp,
batchscheduler.go:214-216); unschedulable pods re-enter after per-pod
exponential backoff, promoted by a flusher thread.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Optional

from .types import PodInfo

__all__ = ["SchedulingQueue"]

LessFn = Callable[[PodInfo, PodInfo], bool]


class _Entry:
    __slots__ = ("info", "less", "dead", "group", "key")

    def __init__(
        self,
        info: PodInfo,
        less: LessFn,
        group: Optional[str] = None,
        key=None,
    ):
        self.info = info
        self.less = less
        self.dead = False  # lazily-deleted (drained as part of its gang)
        self.group = group
        # precomputed total-order key (plugin sort_key): heap comparisons
        # become tuple compares instead of two Less() attribute walks
        self.key = key

    def __lt__(self, other: "_Entry") -> bool:
        if self.key is not None and other.key is not None:
            return self.key < other.key
        if self.less(self.info, other.info):
            return True
        if self.less(other.info, self.info):
            return False
        return self.info.seq < other.info.seq  # stable total order


class SchedulingQueue:
    def __init__(
        self,
        less_fn: Optional[LessFn] = None,
        backoff_base: float = 1.0,
        backoff_cap: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        group_key_fn: Optional[Callable[[PodInfo], Optional[str]]] = None,
        sort_key_fn: Optional[Callable[[PodInfo], tuple]] = None,
    ):
        self._less = less_fn or (lambda a, b: a.timestamp < b.timestamp)
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        self._clock = clock
        self._group_key = group_key_fn
        # when provided, entries carry a precomputed total-order key (one
        # plugin call per push) instead of paying O(log n) Less() chains
        # per heap operation
        self._sort_key = sort_key_fn
        self._cond = threading.Condition()
        self._active: list = []
        self._active_dead = 0
        # gang-unit admission index: group key -> live active entries, so a
        # batch-planned gang's queued members drain in one cycle instead of
        # one heap pop + full comparator churn each (pop_group)
        self._groups: dict = {}
        self._backoff: list = []  # heap of (ready_at, seq, PodInfo)
        self._closed = False
        self._flusher = threading.Thread(
            target=self._flush_loop, name="queue-backoff-flusher", daemon=True
        )
        self._flusher.start()

    def _push_active_locked(self, info: PodInfo) -> None:
        group = self._group_key(info) if self._group_key else None
        key = None
        if self._sort_key is not None:
            key = (*self._sort_key(info), info.seq)  # seq: stable tiebreak
        entry = _Entry(info, self._less, group, key)
        heapq.heappush(self._active, entry)
        if group is not None:
            self._groups.setdefault(group, set()).add(entry)

    def _drop_from_group_locked(self, entry: "_Entry") -> None:
        if entry.group is not None:
            bucket = self._groups.get(entry.group)
            if bucket is not None:
                bucket.discard(entry)
                if not bucket:
                    del self._groups[entry.group]

    def push(self, info: PodInfo) -> None:
        if not info.timestamp:
            info.timestamp = self._clock()
        with self._cond:
            self._push_active_locked(info)
            self._cond.notify()

    def group_size(self, group: str) -> int:
        """Live queued members of ``group`` — the gang-transaction quorum
        check (popped entries leave their bucket, so len is exact)."""
        with self._cond:
            bucket = self._groups.get(group)
            return len(bucket) if bucket else 0

    def pop_group(self, group: str) -> list:
        """Remove and return every queued member of ``group`` (arbitrary
        order — the caller admits them against an already-priority-ordered
        batch plan). Their heap entries are lazily deleted."""
        with self._cond:
            bucket = self._groups.pop(group, None)
            if not bucket:
                return []
            out = []
            for entry in bucket:
                if not entry.dead:
                    entry.dead = True
                    self._active_dead += 1
                    out.append(entry.info)
            return out

    def push_backoff(self, info: PodInfo) -> None:
        """Re-queue an unschedulable pod after exponential backoff."""
        info.attempts += 1
        delay = min(
            self._backoff_base * (2 ** (info.attempts - 1)), self._backoff_cap
        )
        with self._cond:
            heapq.heappush(
                self._backoff, (self._clock() + delay, info.seq, info)
            )

    def pop(self, timeout: Optional[float] = None) -> Optional[PodInfo]:
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                while not self._active:
                    if self._closed:
                        return None
                    wait = None
                    if deadline is not None:
                        wait = deadline - self._clock()
                        if wait <= 0:
                            return None
                    if self._backoff:
                        due = self._backoff[0][0] - self._clock()
                        wait = due if wait is None else min(wait, due)
                    if wait is not None and wait <= 0:
                        self._promote_locked()
                        continue
                    self._cond.wait(wait if wait is None else max(wait, 0.01))
                    self._promote_locked()
                entry = heapq.heappop(self._active)
                if entry.dead:
                    self._active_dead -= 1
                    continue  # lazily-deleted (drained via pop_group)
                self._drop_from_group_locked(entry)
                return entry.info

    def _promote_locked(self) -> None:
        now = self._clock()
        moved = False
        while self._backoff and self._backoff[0][0] <= now:
            _, _, info = heapq.heappop(self._backoff)
            self._push_active_locked(info)
            moved = True
        if moved:
            self._cond.notify_all()

    def _flush_loop(self) -> None:
        while not self._closed:
            time.sleep(0.05)
            with self._cond:
                self._promote_locked()

    def __len__(self) -> int:
        with self._cond:
            return (
                len(self._active) - self._active_dead + len(self._backoff)
            )

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
