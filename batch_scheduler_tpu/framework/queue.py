"""Priority scheduling queue with a pluggable queue-sort comparator.

The active queue orders by the QueueSort plugin's Less (the reference's
Compare chain: priority -> group creation time -> name -> pod timestamp,
batchscheduler.go:214-216); unschedulable pods re-enter after per-pod
exponential backoff, promoted by a flusher thread.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Callable, Optional

from .types import PodInfo

__all__ = ["SchedulingQueue"]

LessFn = Callable[[PodInfo, PodInfo], bool]


class _Entry:
    __slots__ = ("info", "less", "dead", "group", "key", "in_heap")

    def __init__(
        self,
        info: PodInfo,
        less: LessFn,
        group: Optional[str] = None,
        key=None,
    ):
        self.info = info
        self.less = less
        self.dead = False  # lazily-deleted (drained as part of its gang)
        self.group = group
        # precomputed total-order key (plugin sort_key): heap comparisons
        # become tuple compares instead of two Less() attribute walks
        self.key = key
        self.in_heap = False  # heap-resident vs parked in a gang FIFO

    def __lt__(self, other: "_Entry") -> bool:
        if self.key is not None and other.key is not None:
            return self.key < other.key
        if self.less(self.info, other.info):
            return True
        if self.less(other.info, self.info):
            return False
        return self.info.seq < other.info.seq  # stable total order


class SchedulingQueue:
    def __init__(
        self,
        less_fn: Optional[LessFn] = None,
        backoff_base: float = 1.0,
        backoff_cap: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        group_key_fn: Optional[Callable[[PodInfo], Optional[str]]] = None,
        sort_key_fn: Optional[Callable[[PodInfo], tuple]] = None,
    ):
        self._less = less_fn or (lambda a, b: a.timestamp < b.timestamp)
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        self._clock = clock
        self._group_key = group_key_fn
        # when provided, entries carry a precomputed total-order key (one
        # plugin call per push) instead of paying O(log n) Less() chains
        # per heap operation
        self._sort_key = sort_key_fn
        self._cond = threading.Condition()
        self._active: list = []  # guarded-by: _cond
        self._active_dead = 0  # guarded-by: _cond
        self._live_active = 0  # guarded-by: _cond
        # gang-unit admission index: group key -> live active entries, so a
        # batch-planned gang's queued members drain in one cycle instead of
        # one heap pop + full comparator churn each (pop_group)
        self._groups: dict = {}  # guarded-by: _cond
        # Two-level gang queueing: the heap holds ONE resident entry per
        # (group, priority) bucket; later same-bucket arrivals park in a
        # FIFO and are promoted when the resident pops. Same-bucket pods
        # are mutually adjacent under the Compare chain (identical
        # priority/creation/name — only the queue timestamp differs), so
        # bucket-FIFO order matches the heap order they would have had,
        # and the ~quorum-1 members per gang skip the heap entirely (at
        # 10k pods that was most of the push cost). One deviation: a
        # backoff RE-entry re-parks at its bucket's FIFO tail even though
        # its original timestamp may precede a queued sibling's.
        self._fifos: dict = {}  # guarded-by: _cond
        self._heads: dict = {}  # guarded-by: _cond
        self._backoff: list = []  # heap of (ready_at, seq, PodInfo)
        self._closed = False
        self._flusher = threading.Thread(
            target=self._flush_loop, name="queue-backoff-flusher", daemon=True
        )
        self._flusher.start()

    def _push_active_locked(self, info: PodInfo) -> None:  # lock-held: _cond
        group = self._group_key(info) if self._group_key else None
        entry = _Entry(info, self._less, group)
        self._live_active += 1
        if group is not None:
            self._groups.setdefault(group, set()).add(entry)
            bucket = (group, info.priority)
            if bucket in self._heads:
                # a sibling is heap-resident: park (no heap op, no key)
                self._fifos.setdefault(bucket, deque()).append(entry)
                return
            self._heads[bucket] = entry
        self._heap_insert_locked(entry)

    def _heap_insert_locked(self, entry: _Entry) -> None:  # lock-held: _cond
        if self._sort_key is not None:
            # seq appended for a stable total order
            entry.key = (*self._sort_key(entry.info), entry.info.seq)
        entry.in_heap = True
        heapq.heappush(self._active, entry)

    def _promote_bucket_locked(self, entry: _Entry) -> None:  # lock-held: _cond
        """A gang bucket's heap-resident entry was popped (live or dead):
        promote its next live FIFO member into the heap."""
        bucket = (entry.group, entry.info.priority)
        if self._heads.get(bucket) is not entry:
            return
        fifo = self._fifos.get(bucket)
        while fifo:
            nxt = fifo.popleft()
            if not nxt.dead:
                self._heads[bucket] = nxt
                self._heap_insert_locked(nxt)
                return
        self._heads.pop(bucket, None)
        self._fifos.pop(bucket, None)

    def _drop_from_group_locked(self, entry: "_Entry") -> None:  # lock-held: _cond
        if entry.group is not None:
            bucket = self._groups.get(entry.group)
            if bucket is not None:
                bucket.discard(entry)
                if not bucket:
                    del self._groups[entry.group]

    def push(self, info: PodInfo) -> None:
        if not info.timestamp:
            info.timestamp = self._clock()
        with self._cond:
            self._push_active_locked(info)
            self._cond.notify()

    def group_size(self, group: str) -> int:
        """Live queued members of ``group`` — the gang-transaction quorum
        check (popped entries leave their bucket, so len is exact)."""
        with self._cond:
            bucket = self._groups.get(group)
            return len(bucket) if bucket else 0

    def pop_group(self, group: str) -> list:
        """Remove and return every queued member of ``group`` (arbitrary
        order — the caller admits them against an already-priority-ordered
        batch plan). Heap-resident entries are lazily deleted; FIFO-parked
        entries never touch the heap at all."""
        with self._cond:
            bucket = self._groups.pop(group, None)
            if not bucket:
                return []
            out = []
            for entry in bucket:
                if not entry.dead:
                    entry.dead = True
                    self._live_active -= 1
                    if entry.in_heap:
                        self._active_dead += 1
                    out.append(entry.info)
            return out

    def push_backoff(self, info: PodInfo) -> None:
        """Re-queue an unschedulable pod after exponential backoff."""
        info.attempts += 1
        delay = min(
            self._backoff_base * (2 ** (info.attempts - 1)), self._backoff_cap
        )
        with self._cond:
            heapq.heappush(
                self._backoff, (self._clock() + delay, info.seq, info)
            )

    def pop(self, timeout: Optional[float] = None) -> Optional[PodInfo]:
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                while not self._active:
                    if self._closed:
                        return None
                    wait = None
                    if deadline is not None:
                        wait = deadline - self._clock()
                        if wait <= 0:
                            return None
                    if self._backoff:
                        due = self._backoff[0][0] - self._clock()
                        wait = due if wait is None else min(wait, due)
                    if wait is not None and wait <= 0:
                        self._promote_locked()
                        continue
                    self._cond.wait(wait if wait is None else max(wait, 0.01))
                    self._promote_locked()
                entry = heapq.heappop(self._active)
                entry.in_heap = False
                if entry.group is not None:
                    # live or dead, the popped resident hands its bucket's
                    # heap slot to the next parked sibling
                    self._promote_bucket_locked(entry)
                if entry.dead:
                    self._active_dead -= 1
                    continue  # lazily-deleted (drained via pop_group)
                self._drop_from_group_locked(entry)
                self._live_active -= 1
                return entry.info

    def _promote_locked(self) -> None:
        now = self._clock()
        moved = False
        while self._backoff and self._backoff[0][0] <= now:
            _, _, info = heapq.heappop(self._backoff)
            self._push_active_locked(info)
            moved = True
        if moved:
            self._cond.notify_all()

    def _flush_loop(self) -> None:
        while not self._closed:
            time.sleep(0.05)
            with self._cond:
                self._promote_locked()

    def __len__(self) -> int:
        with self._cond:
            return self._live_active + len(self._backoff)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
